#!/usr/bin/env python3
"""Headline benchmark: commit signatures verified per second on a
150-validator chain (BASELINE.md config 1/3 — the block-sync verification
hot path).

Procedure:
  1. Build a 150-validator ed25519 set and a range of signed commits
     (the shape block-sync sees when replaying history).
  2. CPU baseline: single-threaded host verification of one commit's
     signatures (OpenSSL-backed — the stand-in for the reference's Go
     ed25519, which is not runnable in this image).
  3. TPU path: range-batched verification — all commits' signatures in one
     kernel launch (how blocksync batches ranges of historical commits),
     end-to-end including host sign-bytes construction and hashing.

Robustness (round-1 postmortem: the driver recorded value=0 because axon
backend init failed once and the script gave up):
  - backend init runs on a watchdog thread with retries + backoff;
  - if the TPU backend never comes up, the benchmark falls back to the JAX
    CPU backend so a nonzero end-to-end number is always recorded;
  - the validity bitmap is checked on both the all-valid and the
    corrupted-signature path before any rate is reported.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


#: Commits per TPU range at 150 validators: two full 8192-signature
#: chunks (verify_resolved's _MAX_BUCKET), so host prep of chunk 2
#: overlaps chunk 1's device execution. Used for BOTH the headline batch
#: and the blocksync window so the two benches measure the same shape.
TPU_RANGE_COMMITS = 2 * 8192 // 150  # 108


def _attach_log() -> list:
    """Structured backend-attach attempt records, persisted ACROSS
    re-execs via the environment so the final JSON line carries the
    whole story (round-1..5 postmortem: the failure mode lived only in
    a captured stderr tail)."""
    try:
        return json.loads(os.environ.get("TMTPU_BENCH_ATTACH_LOG", "[]"))
    except json.JSONDecodeError:
        return []


def _record_attach(entry: dict) -> None:
    """Append one attach attempt record; emit it as a structured stderr
    line AND stash it in the env for any re-exec'd successor."""
    entries = _attach_log()
    entries.append(entry)
    os.environ["TMTPU_BENCH_ATTACH_LOG"] = json.dumps(entries)
    log(json.dumps({"phase": "backend_attach", **entry}))


def _reexec(env_updates: dict, reason: str) -> None:
    """Replace this process with a fresh run of the benchmark. A hung
    thread inside xla_bridge.backends() holds jax's global backend lock,
    so no jax call in this process can ever complete — the ONLY safe
    recovery is a fresh interpreter."""
    log(f"{reason}; re-execing ({env_updates})")
    sys.stderr.flush()
    sys.stdout.flush()
    env = dict(os.environ, **env_updates)
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def reexec_forced_cpu(reason: str) -> None:
    _reexec({"JAX_PLATFORMS": "cpu", "TMTPU_BENCH_FORCED_CPU": "1"}, reason)


def reexec_fresh_tpu(reason: str, counter_var: str, max_tries: int) -> None:
    """Retry the TPU backend in a FRESH process before giving up on the
    chip (round-4 postmortem: one transient tunnel wedge cost the round
    its only TPU datapoint because the first hang went straight to the
    CPU re-exec). counter_var tracks re-exec attempts across execs;
    when exhausted, fall through to the forced-CPU run."""
    n = int(os.environ.get(counter_var, "0"))
    if n + 1 >= max_tries:
        reexec_forced_cpu(f"{reason} (fresh-TPU retries exhausted: {n + 1}/{max_tries})")
    time.sleep(10.0)  # give a flapping tunnel a beat before reconnecting
    _reexec({counter_var: str(n + 1)}, f"{reason} (fresh-TPU retry {n + 1}/{max_tries})")


def init_backend(attempts: int = 3, timeout_s: float = 120.0) -> str:
    """Initialize a JAX backend, preferring the ambient platform (the TPU
    tunnel), with a watchdog thread per attempt. Failed (raised) inits are
    retried in-process; a HUNG init re-execs into a fresh TPU attempt
    (fresh xla_bridge state) up to 3 total tries, and only then re-execs
    with JAX_PLATFORMS=cpu. Returns the platform."""
    import jax

    if os.environ.get("TMTPU_BENCH_FORCED_CPU") == "1":
        # re-exec fallback (or smoke test): pin CPU via live config —
        # the axon plugin registration latches the platform at interpreter
        # start, so the JAX_PLATFORMS env var alone does not redirect.
        jax.config.update("jax_platforms", "cpu")
        t0 = time.time()
        platform = jax.devices()[0].platform
        _record_attach(
            {
                "latency_s": round(time.time() - t0, 3),
                "outcome": "ok",
                "device_kind": platform,
                "forced_cpu": True,
            }
        )
        log(f"forced-CPU run: {jax.devices()}")
        return platform

    def try_devices(result):
        try:
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            result["error"] = e

    for i in range(attempts):
        result: dict = {}
        t = threading.Thread(target=try_devices, args=(result,), daemon=True)
        t0 = time.time()
        t.start()
        t.join(timeout_s)
        if "devices" in result:
            platform = result["devices"][0].platform
            _record_attach(
                {
                    "latency_s": round(time.time() - t0, 3),
                    "outcome": "ok",
                    "device_kind": platform,
                }
            )
            log(f"backend up after {time.time()-t0:.1f}s: {result['devices']}")
            return platform
        if t.is_alive():
            _record_attach(
                {
                    "latency_s": round(time.time() - t0, 3),
                    "outcome": "hung",
                    "reason": f"backend init hung past {timeout_s:.0f}s",
                }
            )
            reexec_fresh_tpu(
                f"backend init hung past {timeout_s:.0f}s",
                "TMTPU_BENCH_INIT_RETRY",
                max_tries=3,
            )
        _record_attach(
            {
                "latency_s": round(time.time() - t0, 3),
                "outcome": "error",
                "reason": repr(result.get("error")),
            }
        )
        log(f"backend init attempt {i+1}/{attempts} failed: "
            f"{result.get('error')!r}")
        if i < attempts - 1:
            time.sleep(5 * (i + 1))
    log("TPU backend unavailable — falling back to CPU backend in-process")
    jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    _record_attach(
        {
            "latency_s": 0.0,
            "outcome": "fallback",
            "device_kind": platform,
            "reason": "in-process CPU fallback after exhausted attempts",
        }
    )
    return platform


def _build_commit_items(n_vals, n_commits, chain_id="bench-chain"):
    from tendermint_tpu import testing as tt

    vals, keys = tt.make_validator_set(n_vals, power=10)
    commits = []
    for h in range(1, n_commits + 1):
        bid = tt.make_block_id(b"block-%d" % h)
        commits.append((bid, tt.make_commit(chain_id, h, 0, bid, vals, keys)))
    items = []
    for _, commit in commits:
        for idx, cs in enumerate(commit.signatures):
            val = vals.validators[idx]
            items.append(
                (val.pub_key.bytes(), commit.vote_sign_bytes(chain_id, idx), cs.signature)
            )
    return vals, keys, commits, items


def kernel_breakdown(items: list) -> dict:
    """Stage-level timing of the batch-equation kernel on the live backend
    (VERDICT r4 #1: decompress vs window scans vs Horner fold, plus a
    field-mul count and achieved-FLOP estimate). Each stage is jitted
    separately on the SAME padded batch; the deltas attribute the
    end-to-end time. Diagnostics only — production uses the fused kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tendermint_tpu.crypto.tpu import curve, msm
    from tendermint_tpu.crypto.tpu import verify as tpuv
    from tendermint_tpu.crypto.tpu.curve import Point

    # cap the stage-timing batch: the sub-stages are separate XLA
    # compiles, and 1024 is representative without risking the driver's
    # time budget on compile
    entries = [tpuv.resolve_ed25519(*it) for it in items[:1024]]
    b = tpuv._bucket(len(entries))
    ua_bytes, r_bytes, ga_digits, r_digits, zs_digits, s_valid, gidx = (
        tpuv.prepare_batch_eq(entries, pad_to=b)
    )
    gb = ua_bytes.shape[0]

    def timeit(fn, *args, reps=5):
        out = fn(*args)
        out = np.asarray(jax.tree.leaves(out)[0])  # compile + warm + sync
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        np.asarray(jax.tree.leaves(out)[0])  # force execution (axon defers)
        return (time.perf_counter() - t0) / reps

    dec = jax.jit(
        lambda ab, rb: curve.decompress(
            jnp.concatenate([ab, rb], axis=0).astype(jnp.int32)
        )
    )
    t_dec = timeit(dec, ua_bytes, r_bytes)
    stacked, _ok = dec(ua_bytes, r_bytes)
    # A-side timed at gb+1 rows exactly as _kernel_eq runs it (the +1
    # base-point row keeps the length a power of two -> blocked-prefix
    # path; gb alone would fall back to the associative_scan branch and
    # time a different algorithm)
    bpt = curve.base_point(())
    a_pts = Point(
        *(
            jnp.concatenate([jnp.asarray(c[:gb]), bc[None]], axis=0)
            for c, bc in zip(stacked, bpt)
        )
    )
    r_pts = Point(*(jnp.asarray(c[gb : gb + b]) for c in stacked))
    ga_full = jnp.concatenate(
        [jnp.asarray(ga_digits), jnp.asarray(zs_digits)], axis=1
    ).astype(jnp.int32)

    msm_fn = jax.jit(msm.msm)
    t_msm_a = timeit(msm_fn, a_pts, ga_full)  # 32 windows, grouped + base row
    t_msm_r = timeit(msm_fn, r_pts, jnp.asarray(r_digits, jnp.int32))  # 16 windows
    t_full = timeit(
        jax.jit(tpuv._kernel_eq),
        ua_bytes, r_bytes, ga_digits, r_digits, zs_digits, s_valid, gidx,
    )

    # arithmetic accounting: point_add ≈ 9 field muls, double ≈ 8.
    # Per window: sort + blocked boundary prefixes (~M + 2M/16 + 256
    # adds) + 256-leaf collapse (~264 adds) + 255× multiply (7 dbl +
    # 7 add). 16 R-group windows at M=b, 32 A-group windows at M=gb+1;
    # Horner fold adds 8 dbl + 1 add per window.
    def window_adds(m):
        return m + 2 * m // 16 + 256 + 264 + 14

    adds = 16 * window_adds(b) + 32 * window_adds(gb + 1)
    fmuls = adds * 9 + 48 * (8 * 8 + 9)
    # one field mul (GEMM path) routes 32*32*32 ≈ 32.8k f32 MACs through
    # the MXU per element-pair after batching
    flops = fmuls * 2 * 32 * 32 * 32
    bd = {
        "batch": b,
        "groups": gb,
        "decompress_ms": round(t_dec * 1e3, 2),
        "msm_a32_ms": round(t_msm_a * 1e3, 2),
        "msm_r16_ms": round(t_msm_r * 1e3, 2),
        "fused_total_ms": round(t_full * 1e3, 2),
        "field_muls_est": fmuls,
        "achieved_tflops_est": round(flops / t_full / 1e12, 3),
    }
    log(f"kernel breakdown: {bd}")
    if tpuv.field_mul_probe:
        bd["field_mul_probe"] = dict(tpuv.field_mul_probe)
        log(f"field-mul A/B probe: {tpuv.field_mul_probe}")
    return bd


def bench_mixed_commit(n_vals: int, n_commits: int) -> float:
    """BASELINE config 4: mixed ed25519 + secp256k1 validator set through
    verify_commit_light (reference types/validator_set.go VerifyCommitLight
    with a heterogeneous key set). Returns sigs/sec."""
    from tendermint_tpu import testing as tt
    from tendermint_tpu.types import validation

    chain_id = "mixed-bench"
    vals, keys = tt.make_validator_set(
        n_vals, power=10, key_types=("ed25519", "secp256k1")
    )
    pairs = []
    for h in range(1, n_commits + 1):
        bid = tt.make_block_id(b"mixed-%d" % h)
        pairs.append((bid, tt.make_commit(chain_id, h, 0, bid, vals, keys)))
    t0 = time.perf_counter()
    total = 0
    for bid, commit in pairs:
        validation.verify_commit_light(chain_id, vals, bid, commit.height, commit)
        total += sum(1 for cs in commit.signatures if cs.is_commit())
    dt = time.perf_counter() - t0
    rate = total / dt
    log(
        f"mixed-key commit: {total} sigs over {n_commits} commits in {dt:.2f}s "
        f"-> {rate:,.1f} sigs/s"
    )
    return rate


def bench_statesync(n_blocks: int, n_vals: int) -> float:
    """BASELINE config 5: statesync snapshot restore + backfill commit
    verification (reference internal/statesync/reactor.go:348-369 shape,
    in-process). Returns backfilled+verified blocks/sec."""
    import asyncio

    from tendermint_tpu.testing import statesync_restore_scenario

    t0 = time.perf_counter()
    n_verified = asyncio.run(statesync_restore_scenario(n_blocks, n_vals))
    dt = time.perf_counter() - t0
    rate = n_verified / dt
    log(
        f"statesync: restored + backfilled {n_verified} blocks in {dt:.2f}s "
        f"-> {rate:,.1f} blocks/s"
    )
    return rate


def bench_light_client(n_headers: int, n_vals: int) -> float:
    """BASELINE config 2: sequential VerifyAdjacent over a chain of signed
    headers (reference light/client_benchmark_test.go shape), every commit
    going through the real verify_commit_light -> batch verifier path.
    Returns headers/sec."""
    import time as _t

    from tendermint_tpu import testing as tt
    from tendermint_tpu.crypto.hashes import sha256
    from tendermint_tpu.light import verifier
    from tendermint_tpu.light.types import LightBlock, SignedHeader
    from tendermint_tpu.types.block import BlockID, Header, PartSetHeader

    chain_id = "light-bench"
    vals, keys = tt.make_validator_set(n_vals, power=10)
    vh = vals.hash()
    t0 = _t.perf_counter()
    blocks = []
    base_ts = 1_700_000_000_000_000_000
    prev_hash = sha256(b"genesis")
    for h in range(1, n_headers + 1):
        hdr = Header(
            chain_id=chain_id,
            height=h,
            time_ns=base_ts + h * 1_000_000_000,
            last_block_id=BlockID(prev_hash, PartSetHeader(1, sha256(b"pp"))),
            data_hash=sha256(b"data-%d" % h),
            validators_hash=vh,
            next_validators_hash=vh,
            consensus_hash=sha256(b"consensus"),
            app_hash=sha256(b"app-%d" % h),
            last_results_hash=sha256(b"res"),
            proposer_address=vals.validators[h % n_vals].address,
        )
        bid = BlockID(hdr.hash(), PartSetHeader(1, sha256(b"parts-%d" % h)))
        commit = tt.make_commit(
            chain_id, h, 0, bid, vals, keys, timestamp_ns=hdr.time_ns
        )
        blocks.append(LightBlock(SignedHeader(hdr, commit), vals))
        prev_hash = hdr.hash()
    log(f"light: built {n_headers} signed headers in {_t.perf_counter()-t0:.1f}s")

    period = 10 * 365 * 24 * 3600 * 10**9
    now_ns = base_ts + (n_headers + 10) * 1_000_000_000
    t0 = _t.perf_counter()
    trusted = verifier.verify_adjacent_chain(
        chain_id, blocks[0], blocks[1:], period, now_ns
    )
    assert trusted.height == n_headers
    dt = _t.perf_counter() - t0
    rate = (n_headers - 1) / dt
    log(f"light: verified {n_headers-1} adjacent headers in {dt:.2f}s -> {rate:,.1f} headers/s")
    return rate


async def _bench_blocksync_async(n_blocks: int, n_vals: int, window: int) -> float:
    """BASELINE config 3: replay a prebuilt kvstore chain through the REAL
    blocksync reactor (fetch -> range-batched verify -> ApplyBlock) over an
    in-process channel bridge. Returns blocks/sec."""
    import asyncio
    import time as _t

    from tendermint_tpu import testing as tt
    from tendermint_tpu.abci.kvstore import KVStoreApp
    from tendermint_tpu.blocksync import BLOCKSYNC_CHANNEL
    from tendermint_tpu.blocksync import messages as bsm
    from tendermint_tpu.blocksync.reactor import BlockSyncReactor
    from tendermint_tpu.consensus.harness import make_genesis
    from tendermint_tpu.p2p.peermanager import PeerStatus, PeerUpdate
    from tendermint_tpu.p2p.router import Channel
    from tendermint_tpu.proxy import AppConns
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.state import state_from_genesis
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.blockstore import BlockStore
    from tendermint_tpu.store.db import MemDB
    from tendermint_tpu.testing import det_priv_keys
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    # genesis with n_vals validators
    keys = det_priv_keys(n_vals)
    gvals = [GenesisValidator(k.pub_key(), 10, f"v{i}") for i, k in enumerate(keys)]
    genesis = GenesisDoc(
        chain_id="bs-bench",
        initial_height=1,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=gvals,
    )
    by_addr = {k.pub_key().address(): k for k in keys}

    async def build_source():
        app = KVStoreApp()
        conns = AppConns.local(app)
        bstore = BlockStore(MemDB())
        sstore = StateStore(MemDB())
        state = state_from_genesis(genesis)
        from tendermint_tpu.consensus.replay import Handshaker

        state = await Handshaker(sstore, state, bstore, genesis).handshake(conns)
        sstore.save(state)
        ex = BlockExecutor(sstore, conns.consensus, block_store=bstore)
        commit = None
        t0 = _t.perf_counter()
        for h in range(1, n_blocks + 1):
            block, parts = ex.create_proposal_block(
                h, state, commit, state.validators.get_proposer().address
            )
            bid = block.block_id(parts.header)
            state, _ = await ex.apply_block(state, bid, block)
            commit = tt.make_commit(
                "bs-bench", h, 0, bid, state.last_validators, by_addr,
                timestamp_ns=block.header.time_ns + 1,
            )
            bstore.save_block(block, parts, commit)
        log(f"blocksync: built {n_blocks}-block chain in {_t.perf_counter()-t0:.1f}s")
        return bstore, conns

    src_store, src_conns = await build_source()

    # target node: fresh state, real reactor
    app = KVStoreApp()
    conns = AppConns.local(app)
    bstore = BlockStore(MemDB())
    sstore = StateStore(MemDB())
    state = state_from_genesis(genesis)
    from tendermint_tpu.consensus.replay import Handshaker

    state = await Handshaker(sstore, state, bstore, genesis).handshake(conns)
    sstore.save(state)
    ex = BlockExecutor(sstore, conns.consensus, block_store=bstore)

    ch = Channel(
        BLOCKSYNC_CHANNEL, "blocksync", 5, bsm.encode_message, bsm.decode_message
    )
    peer_q: asyncio.Queue = asyncio.Queue()
    reactor = BlockSyncReactor(
        state, ex, bstore, ch, peer_q, window=window, active=True
    )

    async def serve_peer():
        """Answer the reactor's outbound envelopes from the source store
        (the in-process stand-in for a remote peer's reactor)."""
        while True:
            env = await ch.out_q.get()
            msg = env.message
            from tendermint_tpu.p2p.types import Envelope

            if isinstance(msg, bsm.StatusRequest):
                await ch.in_q.put(
                    Envelope(
                        BLOCKSYNC_CHANNEL,
                        bsm.StatusResponse(src_store.height(), src_store.base()),
                        from_="peer0",
                    )
                )
            elif isinstance(msg, bsm.BlockRequest):
                block = src_store.load_block(msg.height)
                if block is not None:
                    await ch.in_q.put(
                        Envelope(
                            BLOCKSYNC_CHANNEL,
                            bsm.BlockResponse(block),
                            from_="peer0",
                        )
                    )

    server = asyncio.get_running_loop().create_task(serve_peer())
    await peer_q.put(PeerUpdate("peer0", PeerStatus.UP))
    t0 = _t.perf_counter()
    await reactor.start()
    await asyncio.wait_for(reactor.synced.wait(), timeout=3600)
    dt = _t.perf_counter() - t0
    server.cancel()
    await reactor.stop()
    await conns.stop()
    await src_conns.stop()
    applied = reactor.metrics["blocks_applied"]
    sigs = reactor.metrics["sigs_verified"]
    assert bstore.height() >= n_blocks - 1, (bstore.height(), n_blocks)
    rate = applied / dt
    log(
        f"blocksync: applied {applied} blocks ({sigs} sigs verified, "
        f"{reactor.metrics['ranges']} ranges) in {dt:.2f}s -> {rate:,.1f} blocks/s"
    )
    return rate


def bench_blocksync(n_blocks: int, n_vals: int, window: int) -> float:
    import asyncio

    return asyncio.run(_bench_blocksync_async(n_blocks, n_vals, window))


def bench_crash_recovery(n_heights: int = 400, msgs_per_height: int = 20) -> dict:
    """crash_recovery config: WAL replay throughput after a seeded crash.
    Build a WAL of `n_heights` heights (message records + fsync'd
    end-height markers) through the chaos-fs layer, tear the un-fsynced
    tail mid-record at a simulated crash, then measure (a) the open-time
    repair (truncate to the last whole record, rotate damaged tail
    aside) and (b) replay rate in heights/sec and records/sec — the
    downtime a validator spends between restart and first vote."""
    import shutil
    import tempfile
    import time as _t

    from tendermint_tpu.consensus.wal import KIND_END_HEIGHT, WAL
    from tendermint_tpu.libs.chaosfs import ChaosFS, ChaosFSConfig

    d = tempfile.mkdtemp(prefix="benchwal-")
    try:
        fs = ChaosFS(ChaosFSConfig(seed=9, torn_write_rate=1.0))
        wal = WAL(d, fs=fs)
        payload = b"\x12\x40" + b"\xab" * 126  # ~128B opaque consensus msg
        for h in range(1, n_heights + 1):
            for _ in range(msgs_per_height):
                wal.write(payload)
            wal.write_end_height(h)  # fsync: the durable watermark
        for _ in range(msgs_per_height):
            wal.write(payload)  # un-fsynced tail, torn by the crash
        fs.halt()
        wal.close()
        fs.simulate_crash()

        t0 = _t.perf_counter()
        wal2 = WAL(d, fs=fs)  # open-time repair
        repair_dt = _t.perf_counter() - t0
        t0 = _t.perf_counter()
        n_recs = heights = 0
        for rec in wal2.iter_records():
            n_recs += 1
            if rec.kind == KIND_END_HEIGHT:
                heights = rec.height
        replay_dt = _t.perf_counter() - t0
        wal2.close()
        out = {
            "replay_heights_per_s": round(heights / replay_dt, 1),
            "replay_records_per_s": round(n_recs / replay_dt, 1),
            "repair_ms": round(repair_dt * 1e3, 2),
            "repaired_files": len(wal2.last_repair),
            "heights": heights,
            "records": n_recs,
        }
        log(
            f"crash recovery: repaired {out['repaired_files']} file(s) in "
            f"{out['repair_ms']}ms, replayed {heights} heights "
            f"({n_recs} records) in {replay_dt:.3f}s -> "
            f"{out['replay_heights_per_s']:,.1f} heights/s"
        )
        assert heights == n_heights, (heights, n_heights)
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_chaos_soak(sizes: tuple = (4, 50)) -> dict:
    """chaos_soak config: the robustness trajectory MEASURED, not
    asserted — blocks/s and time-to-recover per named fault scenario
    (consensus/scenarios.py) at 4 and 50 validators, over REAL routers +
    ChaosTransport (RouterNet). BOUNDED, structured outcomes (the
    multichip discipline): every run carries the scenario engine's own
    liveness-watchdog deadline plus an outer asyncio timeout, and a
    wedge/timeout is a record, never a hang. The committee scale is wall
    clock, so 50-validator rows run a trimmed scenario list with a
    height-2 target."""
    import asyncio

    from tendermint_tpu.consensus import scenarios as sc

    seed = int(os.environ.get("TMTPU_BENCH_SOAK_SEED", "7") or 7)
    out: dict = {"seed": seed, "runs": []}
    for n_vals in sizes:
        small = n_vals <= 8
        names = (
            list(sc.SCENARIOS)
            if small
            else [
                "baseline",
                "lossy_links",
                "corrupt_wire",
                "asym_partition",
                "full_taxonomy",
            ]
        )
        target = 3 if small else 2
        timeout_s = 75.0 if small else 300.0
        for name in names:
            t0 = time.perf_counter()

            async def one(_name=name, _n=n_vals, _target=target, _to=timeout_s):
                return await sc.run_scenario(
                    _name,
                    n_vals=_n,
                    target_height=_target,
                    seed=seed,
                    timeout_s=_to,
                    stall_s=25.0 if small else 90.0,
                    time_scale=1.0 if small else 4.0,
                    degree=8,
                )

            try:
                res = asyncio.run(
                    asyncio.wait_for(one(), timeout_s + 60.0)
                ).as_dict()
            except Exception as e:  # noqa: BLE001 — structured outcome
                res = {
                    "scenario": name,
                    "n_vals": n_vals,
                    "outcome": f"error: {e!r}"[:200],
                }
            res["wall_s"] = round(time.perf_counter() - t0, 2)
            out["runs"].append(res)
            rec = res.get("recover_s")
            log(
                f"chaos_soak {n_vals:>3}v {name:<18} "
                f"{res.get('outcome', '?'):<7} "
                f"{res.get('blocks_per_s', 0)} blk/s "
                f"recover={'-' if rec is None else f'{rec}s'} "
                f"wall={res['wall_s']}s"
            )
    ok = [r for r in out["runs"] if r.get("outcome") == "ok"]
    out["ok_runs"] = len(ok)
    out["total_runs"] = len(out["runs"])
    return out


def bench_wiregen(soak_vals: int = 50) -> dict:
    """wiregen config: the compiled hot codec A/B'd against the
    interpreted codec it was generated from. Two halves:

      * per-family encode/decode frames/s, paired-interleaved: each rep
        times interpreted then generated back-to-back in the same
        window and the best rep wins, so shared-host steal lands on
        both sides instead of skewing the ratio;
      * chaos_soak blocks/s with the codec flipped — the same seeded
        baseline scenario at `soak_vals` validators, run once per
        codec, nets built AFTER the `use_wiregen` flip so every node
        dispatches through the codec under test.

    Pure host work; the device is not on this path."""
    import asyncio

    import tendermint_tpu.types.block as blk
    from tendermint_tpu.consensus import messages as cm
    from tendermint_tpu.consensus import wire_gen as wg
    from tendermint_tpu.crypto.merkle import Proof
    from tendermint_tpu.types.block import BlockID, PartSetHeader
    from tendermint_tpu.types.keys import BLOCK_PART_SIZE, SignedMsgType
    from tendermint_tpu.types.part_set import Part
    from tendermint_tpu.types.vote import Vote

    bid = BlockID(b"\xab" * 32, PartSetHeader(4, b"\xcd" * 32))

    def _vote(i: int) -> Vote:
        return Vote(
            type=SignedMsgType.PREVOTE,
            height=1000 + i,
            round=2,
            block_id=bid,
            timestamp_ns=1_700_000_000_000_000_000 + i,
            validator_address=bytes([i % 256]) * 20,
            validator_index=i,
            signature=bytes([i % 256]) * 64,
        )

    def _soak_part() -> cm.BlockPartMessage:
        # the shape chaos_soak actually gossips: a single-part block
        # (50-sig commit + a few txs), whose one-leaf proof has 0 aunts
        sigs = tuple(
            blk.CommitSig(
                flag=blk.BLOCK_ID_FLAG_COMMIT,
                validator_address=bytes([i % 256]) * 20,
                timestamp_ns=1_700_000_000_000_000_000 + i,
                signature=bytes([i % 256]) * 64,
            )
            for i in range(50)
        )
        hdr = blk.Header(
            chain_id="soak",
            height=3,
            time_ns=1_700_000_000_000_000_000,
            last_block_id=bid,
            proposer_address=b"\x01" * 20,
            validators_hash=b"\x02" * 32,
            next_validators_hash=b"\x02" * 32,
            app_hash=b"\x03" * 32,
        )
        block = blk.Block(
            header=hdr,
            txs=(b"tx-aaaa", b"tx-bbbb"),
            last_commit=blk.Commit(
                height=2, round=0, block_id=bid, signatures=sigs
            ),
        )
        return cm.BlockPartMessage(3, 0, block.make_part_set().parts[0])

    families = {
        "Vote": (cm.VoteMessage(_vote(7)), 3000),
        "VoteBatch[64]": (
            cm.VoteBatchMessage(tuple(_vote(i) for i in range(64))),
            200,
        ),
        "HasVote": (cm.HasVoteMessage(1000, 2, SignedMsgType.PREVOTE, 7), 5000),
        "BlockPart[soak]": (_soak_part(), 1000),
        "BlockPart[64KiB]": (
            cm.BlockPartMessage(
                9,
                1,
                Part(
                    3,
                    bytes(range(256)) * (BLOCK_PART_SIZE // 256),
                    Proof(16, 3, b"\x11" * 32, tuple(b"\x22" * 32 for _ in range(4))),
                ),
            ),
            400,
        ),
    }

    def _paired_best(fa, fb, arg, iters, reps=12):
        best_a = best_b = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                fa(arg)
            t1 = time.perf_counter()
            for _ in range(iters):
                fb(arg)
            t2 = time.perf_counter()
            best_a = min(best_a, (t1 - t0) / iters)
            best_b = min(best_b, (t2 - t1) / iters)
        return best_a, best_b

    # warm the interpreter/caches before the first paired window
    warm = cm.encode_message_py(families["BlockPart[soak]"][0])
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.5:
        cm.decode_message_py(warm)
        wg.decode_message(warm)

    out: dict = {"families": {}}
    for name, (msg, iters) in families.items():
        frame = cm.encode_message_py(msg)
        assert frame == wg.encode_message(msg), f"{name}: A/B bytes differ"
        ei, eg = _paired_best(
            cm.encode_message_py, wg.encode_message, msg, iters
        )
        di, dg = _paired_best(
            cm.decode_message_py, wg.decode_message, frame, iters
        )
        row = {
            "frame_bytes": len(frame),
            "enc_interp_per_s": round(1.0 / ei, 1),
            "enc_gen_per_s": round(1.0 / eg, 1),
            "enc_speedup": round(ei / eg, 2),
            "dec_interp_per_s": round(1.0 / di, 1),
            "dec_gen_per_s": round(1.0 / dg, 1),
            "dec_speedup": round(di / dg, 2),
        }
        out["families"][name] = row
        log(
            f"wiregen {name:<16} enc {row['enc_speedup']:>5.2f}x "
            f"dec {row['dec_speedup']:>5.2f}x "
            f"({row['dec_gen_per_s']:,.0f} dec/s gen)"
        )

    # -- chaos_soak blocks/s with the codec flipped -----------------------
    if os.environ.get("TMTPU_BENCH_WIREGEN_SOAK") != "0":
        from tendermint_tpu.consensus import scenarios as sc

        seed = int(os.environ.get("TMTPU_BENCH_SOAK_SEED", "7") or 7)
        was = cm.wiregen_active()
        soak: dict = {"n_vals": soak_vals, "seed": seed, "scenario": "baseline"}
        try:
            for label, enabled in (("interpreted", False), ("generated", True)):
                cm.use_wiregen(enabled)

                async def one(_n=soak_vals):
                    return await sc.run_scenario(
                        "baseline",
                        n_vals=_n,
                        target_height=2,
                        seed=seed,
                        timeout_s=300.0,
                        stall_s=90.0,
                        time_scale=4.0,
                        degree=8,
                    )

                t0 = time.perf_counter()
                try:
                    res = asyncio.run(
                        asyncio.wait_for(one(), 360.0)
                    ).as_dict()
                except Exception as e:  # noqa: BLE001 — structured outcome
                    res = {"outcome": f"error: {e!r}"[:200]}
                res["wall_s"] = round(time.perf_counter() - t0, 2)
                soak[label] = res
                log(
                    f"wiregen soak[{label}] {res.get('outcome', '?')} "
                    f"{res.get('blocks_per_s', 0)} blk/s "
                    f"wall={res['wall_s']}s"
                )
            bi = soak.get("interpreted", {}).get("blocks_per_s") or 0
            bg = soak.get("generated", {}).get("blocks_per_s") or 0
            soak["soak_speedup"] = round(bg / bi, 2) if bi else None
        finally:
            cm.use_wiregen(was)
        out["chaos_soak_ab"] = soak
    return out


def bench_merkle(soak_vals: int = 50) -> dict:
    """merkle config: the HashHub's level-order batched tree builder
    A/B'd against the scalar recursive reference. Three halves:

      * leaves/s at 64 / 1k / 16k leaves (250-byte leaves — the tx
        shape), paired-interleaved best-of-reps like extra.wiregen:
        scalar recursive vs batched level-order (CPU), plus the device
        bucket route when TMTPU_HASH_TPU=1;
      * block-hash/s over a realistic header (14 cdc-encoded fields +
        50-sig commit root), memoization stripped per rep so the tree
        build itself is what's timed;
      * chaos_soak blocks/s with `use_hashhub` flipped — the same
        seeded baseline scenario at `soak_vals` validators once per
        builder.

    The CPU half IS the acceptance number (≥1.5× at 1024 leaves):
    batching amortizes Python frames the way VoteBatch amortized
    envelopes; the device half only engages when explicitly enabled."""
    import asyncio
    from dataclasses import replace as _dc_replace

    import tendermint_tpu.types.block as blk
    from tendermint_tpu.crypto import hash_hub, merkle
    from tendermint_tpu.types.block import BlockID, PartSetHeader

    def _paired_best(fa, fb, reps=9):
        best_a = best_b = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fa()
            t1 = time.perf_counter()
            fb()
            t2 = time.perf_counter()
            best_a = min(best_a, t1 - t0)
            best_b = min(best_b, t2 - t1)
        return best_a, best_b

    out: dict = {"leaves": {}}
    device_on = False
    try:
        from tendermint_tpu.crypto.tpu import sha256 as dev_sha

        device_on = dev_sha.device_enabled()
        if device_on:
            dev_sha.warmup()  # compile outside the timed windows
    except Exception as e:  # noqa: BLE001 — device half is optional
        log(f"merkle device warmup failed: {e!r}")
        device_on = False

    for n in (64, 1024, 16384):
        leaves = [bytes([i % 256, (i >> 8) % 256]) * 125 for i in range(n)]
        root_scalar = merkle.hash_from_byte_slices_scalar(leaves)
        was = merkle.hashhub_active()
        merkle.use_hashhub(True)
        try:
            assert merkle.hash_from_byte_slices(leaves) == root_scalar
            ts, tb = _paired_best(
                lambda: merkle.hash_from_byte_slices_scalar(leaves),
                lambda: merkle.hash_from_byte_slices(leaves),
            )
            row = {
                "scalar_leaves_per_s": round(n / ts, 1),
                "batched_cpu_leaves_per_s": round(n / tb, 1),
                "speedup": round(ts / tb, 2),
            }
            if device_on:
                saved = hash_hub.MIN_DEVICE_BATCH
                hash_hub.MIN_DEVICE_BATCH = 1
                try:
                    assert merkle.hash_from_byte_slices(leaves) == root_scalar
                    _, td = _paired_best(
                        lambda: None, lambda: merkle.hash_from_byte_slices(leaves)
                    )
                    row["device_leaves_per_s"] = round(n / td, 1)
                    row["device_speedup"] = round(ts / td, 2)
                finally:
                    hash_hub.MIN_DEVICE_BATCH = saved
        finally:
            merkle.use_hashhub(was)
        out["leaves"][str(n)] = row
        log(
            f"merkle {n:>6} leaves: scalar {row['scalar_leaves_per_s']:>12,.0f}/s "
            f"batched {row['batched_cpu_leaves_per_s']:>12,.0f}/s "
            f"-> {row['speedup']:.2f}x"
            + (
                f" device {row['device_leaves_per_s']:,.0f}/s"
                if "device_leaves_per_s" in row
                else ""
            )
        )

    # -- block-hash/s: header root with memoization stripped per rep ----
    bid = BlockID(b"\xab" * 32, PartSetHeader(4, b"\xcd" * 32))
    sigs = tuple(
        blk.CommitSig(
            flag=blk.BLOCK_ID_FLAG_COMMIT,
            validator_address=bytes([i % 256]) * 20,
            timestamp_ns=1_700_000_000_000_000_000 + i,
            signature=bytes([i % 256]) * 64,
        )
        for i in range(50)
    )
    commit = blk.Commit(height=2, round=0, block_id=bid, signatures=sigs)
    hdr = blk.Header(
        chain_id="bench",
        height=3,
        time_ns=1_700_000_000_000_000_000,
        last_block_id=bid,
        last_commit_hash=commit.hash(),
        proposer_address=b"\x01" * 20,
        validators_hash=b"\x02" * 32,
        next_validators_hash=b"\x02" * 32,
        app_hash=b"\x03" * 32,
    )
    iters = 2000
    was = merkle.hashhub_active()

    def _hash_headers():
        # replace() yields a fresh frozen instance, dropping the memo —
        # the 14-field tree build is what's measured
        for _ in range(iters):
            _dc_replace(hdr).hash()

    try:
        merkle.use_hashhub(False)
        assert _dc_replace(hdr).hash() == _dc_replace(hdr).hash()
        ref = _dc_replace(hdr).hash()
        merkle.use_hashhub(True)
        assert _dc_replace(hdr).hash() == ref, "builder A/B root mismatch"

        def _scalar():
            merkle.use_hashhub(False)
            _hash_headers()

        def _batched():
            merkle.use_hashhub(True)
            _hash_headers()

        ts, tb = _paired_best(_scalar, _batched, reps=7)
    finally:
        merkle.use_hashhub(was)
    out["block_hash"] = {
        "scalar_per_s": round(iters / ts, 1),
        "batched_per_s": round(iters / tb, 1),
        "speedup": round(ts / tb, 2),
    }
    log(
        f"merkle header-hash: scalar {out['block_hash']['scalar_per_s']:,.0f}/s "
        f"batched {out['block_hash']['batched_per_s']:,.0f}/s "
        f"-> {out['block_hash']['speedup']:.2f}x"
    )

    # -- chaos_soak blocks/s with the tree builder flipped ---------------
    if os.environ.get("TMTPU_BENCH_MERKLE_SOAK") != "0":
        from tendermint_tpu.consensus import scenarios as sc

        seed = int(os.environ.get("TMTPU_BENCH_SOAK_SEED", "7") or 7)
        was = merkle.hashhub_active()
        soak: dict = {"n_vals": soak_vals, "seed": seed, "scenario": "baseline"}
        try:
            for label, enabled in (("scalar", False), ("hashhub", True)):
                merkle.use_hashhub(enabled)

                async def one(_n=soak_vals):
                    return await sc.run_scenario(
                        "baseline",
                        n_vals=_n,
                        target_height=2,
                        seed=seed,
                        timeout_s=300.0,
                        stall_s=90.0,
                        time_scale=4.0,
                        degree=8,
                    )

                t0 = time.perf_counter()
                try:
                    res = asyncio.run(
                        asyncio.wait_for(one(), 360.0)
                    ).as_dict()
                except Exception as e:  # noqa: BLE001 — structured outcome
                    res = {"outcome": f"error: {e!r}"[:200]}
                res["wall_s"] = round(time.perf_counter() - t0, 2)
                soak[label] = res
                log(
                    f"merkle soak[{label}] {res.get('outcome', '?')} "
                    f"{res.get('blocks_per_s', 0)} blk/s "
                    f"wall={res['wall_s']}s"
                )
            bs = soak.get("scalar", {}).get("blocks_per_s") or 0
            bh = soak.get("hashhub", {}).get("blocks_per_s") or 0
            soak["soak_speedup"] = round(bh / bs, 2) if bs else None
        finally:
            merkle.use_hashhub(was)
        out["chaos_soak_ab"] = soak
    out["hashhub_stats"] = hash_hub.stats_snapshot()
    return out


def bench_byz_soak(sizes: tuple = (4, 50)) -> dict:
    """byz_soak config: Byzantine strategies over real routers measured
    per round — blocks/s under each traitor strategy, time-to-evidence-
    commit (heights from the committed pair's equivocation to its
    on-chain commitment), and the cross-node safety auditor's verdict
    (consensus/byzantine.audit_net), at 4 and 50 validators. BOUNDED,
    structured outcomes (the multichip/chaos_soak discipline): the
    scenario engine's liveness watchdog plus an outer asyncio timeout
    mean a wedge or an escape is a record, never a hang. The 50-row
    runs a trimmed strategy list with a height-4 target (evidence needs
    heights of headroom to commit)."""
    import asyncio

    from tendermint_tpu.consensus import scenarios as sc

    seed = int(os.environ.get("TMTPU_BENCH_BYZ_SEED", "7") or 7)
    out: dict = {"seed": seed, "runs": []}
    for n_vals in sizes:
        small = n_vals <= 8
        names = (
            [
                "byz_equivocation",
                "byz_equivocation_partition",
                "byz_amnesia_skew",
                "byz_withhold",
                "byz_invalid_sig",
                "byz_flood_lies",
                "byz_full_taxonomy",
            ]
            if small
            else [
                "byz_equivocation",
                "byz_invalid_sig",
                "byz_full_taxonomy",
            ]
        )
        timeout_s = 90.0 if small else 600.0
        for name in names:
            t0 = time.perf_counter()

            async def one(_name=name, _n=n_vals, _to=timeout_s):
                return await sc.run_scenario(
                    _name,
                    n_vals=_n,
                    target_height=4,
                    seed=seed,
                    timeout_s=_to,
                    stall_s=30.0 if small else 150.0,
                    time_scale=1.0 if small else 6.0,
                    degree=8,
                    audit_k=3 if small else 6,
                )

            try:
                full = asyncio.run(
                    asyncio.wait_for(one(), timeout_s + 60.0)
                ).as_dict()
                audit = full.get("audit") or {}
                ev_heights = audit.get("evidence_commit_heights") or {}
                # time-to-evidence-commit: worst lag across traitors
                # (commit height − the equivocation height the committed
                # pair attributes — the auditor's promptness anchor)
                lags = list((audit.get("evidence_lag_heights") or {}).values())
                tte = max(lags) if lags else None
                res = {
                    "scenario": name,
                    "n_vals": n_vals,
                    "outcome": full["outcome"],
                    "blocks_per_s": full["blocks_per_s"],
                    "elapsed_s": full["elapsed_s"],
                    "byz_indices": full["byz_indices"],
                    "byz_action_counts": [
                        b.get("counts", {}) for b in full["byz_actions"]
                    ],
                    "audit_ok": audit.get("ok"),
                    "evidence_committed": len(ev_heights),
                    "evidence_commit_heights": ev_heights,
                    "time_to_evidence_commit_heights": tte,
                    "conflicting_commits": len(
                        audit.get("conflicting_commits") or []
                    ),
                    "peer_penalties": audit.get("peer_penalties") or {},
                }
            except Exception as e:  # noqa: BLE001 — structured outcome
                res = {
                    "scenario": name,
                    "n_vals": n_vals,
                    "outcome": f"error: {e!r}"[:200],
                }
            res["wall_s"] = round(time.perf_counter() - t0, 2)
            out["runs"].append(res)
            log(
                f"byz_soak {n_vals:>3}v {name:<26} "
                f"{res.get('outcome', '?'):<7} "
                f"audit={'ok' if res.get('audit_ok') else 'FAIL'} "
                f"ev={res.get('evidence_committed', 0)} "
                f"{res.get('blocks_per_s', 0)} blk/s wall={res['wall_s']}s"
            )
    ok = [
        r
        for r in out["runs"]
        if r.get("outcome") == "ok" and r.get("audit_ok")
    ]
    out["ok_runs"] = len(ok)
    out["total_runs"] = len(out["runs"])
    return out


def bench_routernet_xl(rows: tuple = ((50, 2),)) -> dict:
    """routernet_xl config: multi-process committees over real sockets
    (consensus/routernet_xl) measured per round. Each headline row is
    (validators × worker processes) over TCP with the full
    SecretConnection handshake on every cross-slice link, one shared
    verifyd sidecar (all workers pointed at it via TMTPU_VERIFYD_SOCK),
    and a mid-run SIGKILL + respawn of the last worker — so a row
    yields blocks/s, time-to-recover (WAL repair + re-handshake +
    catch-up across a process boundary), and the daemon's cross-tenant
    occupancy. A small-committee transport A/B (TCP vs UDS at 2 workers
    vs in-process memory at 1 worker — memory links cannot cross a
    process) isolates the socket tax. BOUNDED, structured outcomes (the
    chaos_soak discipline): XLNet's aggregated liveness watchdog plus
    an outer asyncio timeout make a wedge, a torn worker, or a timeout
    a record, never a hang. Rows default to 50×2 on CPU;
    TMTPU_BENCH_XL_ROWS (e.g. "50:2,150:4,500:4") widens to the paper's
    150/500-validator scales."""
    import asyncio

    from tendermint_tpu.consensus import routernet_xl as xl
    from tendermint_tpu.consensus.scenarios import Event

    seed = int(os.environ.get("TMTPU_BENCH_XL_SEED", "7") or 7)
    out: dict = {"seed": seed, "rows": [], "transport_ab": []}

    def budget(n_vals: int) -> tuple[float, float, float]:
        """(timeout_s, stall_s, time_scale) by committee size — the
        slow-soak envelopes from tests/test_routernet_xl.py."""
        if n_vals <= 8:
            return 180.0, 60.0, 1.0
        if n_vals <= 64:
            return 420.0, 150.0, 4.0
        if n_vals <= 200:
            return 900.0, 300.0, 8.0
        return 3000.0, 900.0, 15.0

    def one(label: str, **kw) -> dict:
        t0 = time.perf_counter()
        to = kw.get("timeout_s", 300.0)
        try:
            res = asyncio.run(
                asyncio.wait_for(xl.run_xl(**kw), to + 120.0)
            )
            rec = {
                k: res.get(k)
                for k in (
                    "outcome",
                    "scenario",
                    "n_vals",
                    "workers",
                    "transport",
                    "blocks_per_s",
                    "recover_s",
                    "honest_min",
                    "elapsed_s",
                    "process_events_applied",
                    "verifyd",
                    "worker_errors",
                )
            }
            rec["audit_ok"] = bool((res.get("audit") or {}).get("ok"))
        except Exception as e:  # noqa: BLE001 — structured outcome
            rec = {"outcome": f"error: {e!r}"[:200]}
        rec["label"] = label
        rec["wall_s"] = round(time.perf_counter() - t0, 2)
        rec_r = rec.get("recover_s")
        log(
            f"routernet_xl {label:<22} {rec.get('outcome', '?'):<7} "
            f"{rec.get('blocks_per_s', 0)} blk/s "
            f"recover={'-' if rec_r is None else f'{rec_r}s'} "
            f"wall={rec['wall_s']}s"
        )
        return rec

    # headline rows: blocks/s + time-to-recover + verifyd occupancy at
    # each (validators × workers) scale, kill+respawn of the last worker
    for n_vals, workers in rows:
        to, stall, scale = budget(n_vals)
        out["rows"].append(
            one(
                f"{n_vals}v x{workers}w tcp",
                scenario="baseline",
                n_vals=n_vals,
                workers=workers,
                transport="tcp",
                seed=seed,
                target_height=2,
                preload=4,
                timeout_s=to,
                stall_s=stall,
                time_scale=scale,
                use_verifyd=True,
                durable=True,
                # 1-core boxes need slower, bigger-batch gossip at
                # committee scale (see the 500-val soak test)
                gossip_sleep=1.0 if n_vals > 200 else None,
                process_events=(
                    Event(2.0, "kill_worker", node=workers - 1),
                    Event(4.0, "restart_worker", node=workers - 1),
                ),
            )
        )
    # transport A/B at a small committee: the socket tax isolated from
    # committee-scale costs. memory runs 1 worker — in-process links
    # only — and is the A/B's no-socket control.
    ab_vals = int(os.environ.get("TMTPU_BENCH_XL_AB_VALS", "4"))
    to, stall, scale = budget(ab_vals)
    for transport, workers in (("tcp", 2), ("unix", 2), ("memory", 1)):
        out["transport_ab"].append(
            one(
                f"{ab_vals}v x{workers}w {transport}",
                scenario="baseline",
                n_vals=ab_vals,
                workers=workers,
                transport=transport,
                seed=seed,
                target_height=3,
                preload=4,
                timeout_s=to,
                stall_s=stall,
                time_scale=scale,
                durable=False,
            )
        )
    ok = [
        r
        for r in out["rows"] + out["transport_ab"]
        if r.get("outcome") == "ok"
    ]
    out["ok_runs"] = len(ok)
    out["total_runs"] = len(out["rows"]) + len(out["transport_ab"])
    return out


def bench_verify_hub(
    n_vals: int, n_submitters: int = 8, per_submitter: int = 200
) -> dict:
    """VerifyHub config: N concurrent submitters each feeding
    SINGLE-vote requests through the sync facade — the live-consensus
    shape (one vote at a time per caller, concurrency only across
    callers). Reports coalesced sigs/sec, mean batch occupancy, and the
    sequential single-vote CPU baseline the hub must beat. Duplicate
    submissions (the same vote from 'many peers') exercise the dedup
    cache; throughput is computed over UNIQUE verifications to keep the
    headline honest."""
    import queue as _queue
    import threading as _threading

    from tendermint_tpu import testing as tt
    from tendermint_tpu.crypto.verify_hub import VerifyHub
    from tendermint_tpu.types.keys import SignedMsgType

    chain_id = "hub-bench"
    vals, keys = tt.make_validator_set(min(n_vals, 64), power=10)
    key_list = [keys[v.address] for v in vals.validators]
    n_unique = n_submitters * per_submitter
    items = []
    for i in range(n_unique):
        vi = i % len(key_list)
        bid = tt.make_block_id(b"hub-%d" % (i // len(key_list)))
        vote = tt.make_vote(
            chain_id, key_list[vi], vi, 1 + i // len(key_list), 0,
            SignedMsgType.PREVOTE, bid,
        )
        items.append(
            (vals.validators[vi].pub_key, vote.sign_bytes(chain_id), vote.signature)
        )

    # sequential single-vote CPU baseline: one verify_signature at a
    # time, the pre-hub live-consensus path
    base_n = min(len(items), 400)
    t0 = time.perf_counter()
    for pk, msg, sig in items[:base_n]:
        assert pk.verify_signature(msg, sig)
    seq_rate = base_n / (time.perf_counter() - t0)
    log(f"hub bench: sequential single-vote baseline {seq_rate:,.1f} sigs/s")

    hub = VerifyHub(max_batch=256, window_ms=2.0, cache_size=4 * n_unique)
    hub.start()
    try:
        work: _queue.SimpleQueue = _queue.SimpleQueue()
        for it in items:
            work.put(it)
        # a 10% sample re-enters the queue — gossip duplicates for the
        # cache-hit measurement
        for d in items[::10]:
            work.put(d)
        errors: list = []

        def submitter():
            while True:
                try:
                    pk, msg, sig = work.get_nowait()
                except _queue.Empty:
                    return
                try:
                    if not hub.verify_sync(pk, msg, sig):
                        errors.append("bad verdict")
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))

        threads = [
            _threading.Thread(target=submitter, name=f"hub-sub-{i}")
            for i in range(n_submitters)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        assert not errors, errors[:3]
        s = hub.stats()
        hub_rate = n_unique / dt
        out = {
            "hub_sigs_per_s": round(hub_rate, 1),
            "sequential_cpu_sigs_per_s": round(seq_rate, 1),
            "speedup_vs_sequential": round(hub_rate / seq_rate, 2),
            "mean_batch_occupancy": round(s["mean_occupancy"], 2),
            "dispatches": int(s["dispatches"]),
            "cache_hits": int(s["cache_hits"] + s["coalesced"]),
            "submitters": n_submitters,
        }
        log(
            f"hub bench: {n_unique} unique sigs via {n_submitters} submitters in "
            f"{dt:.2f}s -> {hub_rate:,.1f} sigs/s (occupancy "
            f"{out['mean_batch_occupancy']}, {out['dispatches']} dispatches, "
            f"{out['cache_hits']} cache/coalesce hits)"
        )
        return out
    finally:
        hub.stop()


async def _bench_consensus_ingest_async(
    n_vals: int, waves: int, n_peers: int
) -> dict:
    """consensus_ingest config: votes ingested+applied per second by ONE
    node fed concurrently by `n_peers` simulated gossip peers — the
    single-node occupancy story. Baseline: the sequential facade
    (ingest_pipeline off → per-vote sync hub verify, occupancy pinned at
    1). Pipelined: stage-1 async verify with in-order apply. Each wave
    is a fresh set of uniquely-signed votes (rounds 0-1, both types,
    tallies kept below 2/3 so the parked state machine never
    transitions); the vote-set is reset between waves so the dedup
    stage sees every wave cold."""
    import asyncio

    from tendermint_tpu.consensus.harness import Node, fast_config, make_genesis
    from tendermint_tpu.consensus.types import HeightVoteSet
    from tendermint_tpu.crypto import verify_hub as vh
    from tendermint_tpu.types.block import NIL_BLOCK_ID
    from tendermint_tpu.types.keys import SignedMsgType
    from tendermint_tpu.types.vote import Vote

    genesis, keys = make_genesis(n_vals)
    # keep every (round, type) tally safely below 2/3 of total power
    cap = max(1, (2 * n_vals) // 3 - 2)
    combos = (
        (0, SignedMsgType.PREVOTE),
        (0, SignedMsgType.PRECOMMIT),
        (1, SignedMsgType.PREVOTE),
        (1, SignedMsgType.PRECOMMIT),
    )

    async def run_mode(pipeline: bool, n_waves: int) -> dict:
        cfg = fast_config()
        cfg.ingest_pipeline = pipeline
        # deep enough that a whole gossip wave overlaps: thread-handoff
        # latency amortizes across the wave instead of per vote
        cfg.ingest_max_inflight = 256
        # park the observer SM: tally votes, never drive rounds
        cfg.timeout_propose_ns = 3_600 * 10**9
        cfg.timeout_commit_ns = 0
        node = Node(genesis, None, config=cfg)
        await node.start()
        cs = node.cs
        vals = cs.rs.validators
        chain_id = cs.state.chain_id
        idx_key = sorted(
            (vals.get_by_address(k.pub_key().address())[0], k) for k in keys
        )
        base_ts = 1_700_000_000_000_000_000
        log(
            f"ingest bench[{'pipelined' if pipeline else 'sequential'}]: "
            f"signing {n_waves}x{len(combos) * cap} votes …"
        )
        wave_votes = []
        for w in range(n_waves):
            votes = []
            for round_, type_ in combos:
                for idx, key in idx_key[:cap]:
                    v = Vote(
                        type=type_,
                        height=cs.rs.height,
                        round=round_,
                        block_id=NIL_BLOCK_ID,
                        timestamp_ns=base_ts + w,  # unique sign-bytes per wave
                        validator_address=key.pub_key().address(),
                        validator_index=idx,
                        signature=b"",
                    )
                    sig = key.sign(v.sign_bytes(chain_id))
                    votes.append(
                        Vote(**{**v.__dict__, "signature": sig})
                    )
            wave_votes.append(votes)

        def tallied() -> int:
            total = 0
            for round_, type_ in combos:
                vs = (
                    cs.rs.votes.prevotes(round_)
                    if type_ == SignedMsgType.PREVOTE
                    else cs.rs.votes.precommits(round_)
                )
                if vs is not None:
                    total += sum(1 for v in vs.votes if v is not None)
            return total

        async def peer_feed(votes):
            for v in votes:
                await cs.add_vote(v, "bench-peer")

        total = 0
        t0 = time.perf_counter()
        try:
            for votes in wave_votes:
                tasks = [
                    asyncio.get_running_loop().create_task(
                        peer_feed(votes[p::n_peers])
                    )
                    for p in range(n_peers)
                ]
                await asyncio.gather(*tasks)
                want = len(votes)
                while tallied() < want:
                    await asyncio.sleep(0.002)
                total += want
                # fresh tally for the next wave (dedup stage sees it cold)
                cs.rs.votes = HeightVoteSet(chain_id, cs.rs.height, vals)
            dt = time.perf_counter() - t0
        finally:
            ingest_stats = dict(cs.ingest.stats) if cs.ingest else {}
            await node.stop()
        return {"rate": total / dt, "votes": total, "dt": dt, "ingest": ingest_stats}

    out: dict = {}
    # sequential facade baseline (~4ms/vote on the pure-python verify
    # fallback: fewer waves keep the baseline from eating the budget)
    hub = vh.acquire_hub(max_batch=256, window_ms=2.0, cache_size=8192)
    try:
        seq = await run_mode(False, max(1, waves // 3))
        s = hub.stats()
        out["sequential_votes_per_s"] = round(seq["rate"], 1)
        out["sequential_occupancy"] = round(s["mean_occupancy"], 2)
    finally:
        vh.release_hub()

    hub = vh.acquire_hub(max_batch=256, window_ms=2.0, cache_size=8192)
    try:
        # light concurrent backfill (pre-signed, one key) so the lane
        # mix under live load is measured, not assumed
        import threading as _threading

        bf_priv = keys[0]
        bf_pub = bf_priv.pub_key()
        bf_items = [
            (bf_pub, b"ingest-backfill-%d" % i, bf_priv.sign(b"ingest-backfill-%d" % i))
            for i in range(128)
        ]

        def backfill_feed():
            try:
                hub.verify_many(bf_items, lane="backfill")
            except Exception as e:  # noqa: BLE001
                log(f"backfill feeder failed: {e!r}")

        feeder = _threading.Thread(target=backfill_feed)
        feeder.start()
        pipe = await run_mode(True, waves)
        feeder.join()
        s = hub.stats()
        out.update(
            pipelined_votes_per_s=round(pipe["rate"], 1),
            speedup_vs_sequential=round(pipe["rate"] / seq["rate"], 2),
            mean_batch_occupancy=round(s["mean_occupancy"], 2),
            lane_live_sigs=int(s["lane_live_dispatched"]),
            lane_backfill_sigs=int(s["lane_backfill_dispatched"]),
            lane_promotions=int(s["lane_promotions"]),
            ingest_pre_verified=int(pipe["ingest"].get("pre_verified", 0)),
            ingest_dedup_drops=int(pipe["ingest"].get("dedup_drops", 0)),
            peers=n_peers,
        )
    finally:
        vh.release_hub()
    log(
        f"consensus ingest: pipelined {out['pipelined_votes_per_s']:,.1f} votes/s "
        f"(occupancy {out['mean_batch_occupancy']}, lane mix "
        f"{out['lane_live_sigs']}/{out['lane_backfill_sigs']} live/backfill) vs "
        f"sequential {out['sequential_votes_per_s']:,.1f} votes/s -> "
        f"{out['speedup_vs_sequential']}x"
    )
    return out


def bench_consensus_ingest(n_vals: int = 64, waves: int = 6, n_peers: int = 8) -> dict:
    import asyncio

    return asyncio.run(_bench_consensus_ingest_async(n_vals, waves, n_peers))


async def _bench_tx_flood_async(n_clients: int, txs_per_client: int) -> dict:
    """tx_flood config: open-loop flood of signed-envelope txs from
    `n_clients` distinct senders through the TxIngress front door —
    sustained admitted tx/s, per-tx p99 admission (CheckTx) latency and
    the shed rate under explicit backpressure. Clients are OPEN loop:
    they submit without waiting for verdicts (bursts with a cooperative
    yield), so when the bounded intake fills the ingress must shed with
    busy, never buffer; the pipeline keeps draining behind the flood and
    the number that matters is what it sustains, not what it drops."""
    import asyncio

    from tendermint_tpu.abci import types as abci_types
    from tendermint_tpu.abci.application import BaseApplication
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.config import MempoolConfig
    from tendermint_tpu.crypto import verify_hub as vh
    from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
    from tendermint_tpu.mempool.ingress import TxIngress, make_signed_tx
    from tendermint_tpu.mempool.pool import PriorityMempool

    class FloodApp(BaseApplication):
        def check_tx(self, req):
            # pseudo-random priority per tx (last byte = signature
            # tail): cheap variety in the resident ordering without an
            # app-state lookup; the pool is sized to hold the whole
            # flood, so eviction dynamics are tested in the suite, not
            # measured here
            prio = req.tx[-1] if req.tx else 0
            return abci_types.ResponseCheckTx(priority=prio, gas_wanted=1)

    n_total = n_clients * txs_per_client
    log(f"tx flood: signing {n_total} envelope txs from {n_clients} clients …")
    t0 = time.perf_counter()
    keys = [Ed25519PrivKey.generate() for _ in range(n_clients)]
    txs: list[bytes] = []
    for ci, key in enumerate(keys):
        for nonce in range(txs_per_client):
            txs.append(
                make_signed_tx(key, nonce, b"flood-%d-%d" % (ci, nonce))
            )
    sign_dt = time.perf_counter() - t0
    log(f"signed {n_total} txs in {sign_dt:.1f}s")

    cfg = MempoolConfig(
        # the pool must not be the bottleneck: this config measures the
        # front door (intake/verify/nonce-lane/checktx), not eviction
        size=n_total + 16,
        max_txs_bytes=1 << 30,
        cache_size=2 * n_total + 16,
    )
    # short park timeout: a shed nonce-0 makes its successor park, and
    # the flood should measure drain speed, not 3s park clocks
    cfg.ingress.nonce_park_timeout_ms = 250.0
    # deep stage-A: concurrent verify awaits are what fill the hub's
    # micro-batches (occupancy ~= workers under saturation)
    cfg.ingress.verify_workers = 64
    pool = PriorityMempool(cfg, LocalClient(FloodApp()))
    ingress = TxIngress(cfg.ingress, pool)
    await ingress.start()

    latencies: list[float] = []

    def on_done(fut, t_sub):
        if fut.exception() is None:
            latencies.append(time.perf_counter() - t_sub)

    t0 = time.perf_counter()
    burst = 256
    for i in range(0, len(txs), burst):
        for tx in txs[i : i + burst]:
            t_sub = time.perf_counter()
            fut = ingress.submit_nowait(tx, source="client")
            fut.add_done_callback(lambda f, t=t_sub: on_done(f, t))
        # open loop: yield so the pipeline runs, but never wait for it
        await asyncio.sleep(0)
    # drain: wait (bounded) for the pipeline + parked successors
    deadline = time.perf_counter() + 120.0
    while (
        ingress.occupancy > 0 or ingress.parked_count() > 0
    ) and time.perf_counter() < deadline:
        await asyncio.sleep(0.01)
    dt = time.perf_counter() - t0
    stats = dict(ingress.stats)
    admitted = int(pool.stats["admitted"])
    shed = int(stats["shed"])
    await ingress.stop()

    latencies.sort()
    p = lambda q: latencies[min(len(latencies) - 1, int(q * len(latencies)))] if latencies else 0.0  # noqa: E731
    out = {
        "clients": n_clients,
        "txs_per_client": txs_per_client,
        "submitted_total": n_total,
        "admitted": admitted,
        "admitted_tx_per_s": round(admitted / dt, 1),
        "checktx_p50_ms": round(p(0.50) * 1e3, 3),
        "checktx_p99_ms": round(p(0.99) * 1e3, 3),
        "shed": shed,
        "shed_rate": round(shed / n_total, 4),
        "parked": int(stats["parked"]),
        "park_expired": int(stats["park_expired"]),
        "park_adopted": int(stats["park_adopted"]),
        "sig_failed": int(stats["sig_failed"]),
        "flood_dt_s": round(dt, 3),
        "sign_dt_s": round(sign_dt, 1),
    }
    hub = vh.running_hub()
    if hub is not None:
        s = hub.stats()
        out["hub_occupancy"] = round(s["mean_occupancy"], 2)
        out["hub_backfill_sigs"] = int(s["lane_backfill_dispatched"])
    log(
        f"tx flood: {out['admitted_tx_per_s']:,.1f} admitted tx/s "
        f"(p99 {out['checktx_p99_ms']}ms, shed {out['shed_rate']:.1%}, "
        f"{admitted}/{n_total} admitted)"
    )
    return out


async def _bench_tx_flood_with_hub(n_clients: int, txs_per_client: int) -> dict:
    from tendermint_tpu.crypto import verify_hub as vh

    # the hub IS the front door's verify engine: envelope signatures
    # micro-batch on its backfill lane, so the flood must run against a
    # live hub to measure the production path (acquired on this loop)
    vh.acquire_hub(max_batch=512, window_ms=2.0, cache_size=65536)
    try:
        return await _bench_tx_flood_async(n_clients, txs_per_client)
    finally:
        vh.release_hub()


def bench_tx_flood(n_clients: int = 10_000, txs_per_client: int = 2) -> dict:
    import asyncio

    return asyncio.run(_bench_tx_flood_with_hub(n_clients, txs_per_client))


def bench_commit_ab(n_vals: int = 150, n_commits: int = 2) -> dict:
    """Aggregate-signature A/B (ISSUE 9 / arXiv:2302.00418): the SAME
    chain shape — n_vals validators, n_commits full commits — measured
    under both commit wire schemes:

      eddsa_batch    — one ed25519 signature per validator, batch
                       verified through the existing funnel;
      bls_aggregate  — ONE 96-byte G2 aggregate per commit, pairing
                       verified (BLS aggregation collapses gossip/
                       storage bandwidth to O(1) signatures at the cost
                       of pairing-heavy verification).

    Records, per scheme: commit wire bytes, commit-verify sigs/s (the
    live-consensus per-commit shape), and catch-up blocks/s (the
    blocksync verify_commit_range shape). Verification memos (the
    hash-to-curve LRU that signing pre-populated, the pure-ed25519
    verdict memo) are cleared before every timed pass, so the numbers
    are cold-verify rates, not cache reads. With TMTPU_BLS_TPU=1 and a
    live backend the aggregate check routes through the batched pairing
    kernel; otherwise the load-bearing pure-Python path is what is
    being measured (recorded in `route`)."""
    from tendermint_tpu import testing
    from tendermint_tpu.crypto import bls_math
    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.types import validation
    from tendermint_tpu.types.block import aggregate_commit

    chain_id = "ab-chain"
    out: dict = {"n_vals": n_vals, "n_commits": n_commits}
    for scheme, key_types in (
        ("eddsa_batch", ("ed25519",)),
        ("bls_aggregate", ("bls12381",)),
    ):
        log(f"commit_ab: building {n_vals}-val {scheme} commits …")
        vals, by_addr = testing.make_validator_set(
            n_vals, key_types=key_types, seed=b"ab-" + scheme.encode()
        )
        commits = []
        for h in range(1, n_commits + 1):
            bid = testing.make_block_id(b"ab%d" % h)
            c = testing.make_commit(
                chain_id, h, 0, bid, vals, by_addr,
                timestamp_ns=1_700_000_000_000_000_000 + h,
            )
            if scheme == "bls_aggregate":
                c = aggregate_commit(c, vals)
            commits.append((vals, bid, h, c))
        wire = len(commits[0][3].encode())
        bls_math._H2_MEMO.clear()
        _ed._VERIFY_MEMO.clear()
        t0 = time.perf_counter()
        for vs, bid, h, c in commits:
            validation.verify_commit(chain_id, vs, bid, h, c)
        dt = time.perf_counter() - t0
        bls_math._H2_MEMO.clear()
        _ed._VERIFY_MEMO.clear()
        t0 = time.perf_counter()
        validation.verify_commit_range(chain_id, commits)
        dt_range = time.perf_counter() - t0
        out[scheme] = {
            "commit_wire_bytes": wire,
            "sig_bytes_per_commit": 96 if scheme == "bls_aggregate" else 64 * n_vals,
            "verify_sigs_per_s": round(n_vals * n_commits / dt, 1),
            "verify_ms_per_commit": round(dt / n_commits * 1e3, 2),
            "catchup_blocks_per_s": round(n_commits / dt_range, 3),
        }
        log(
            f"commit_ab[{scheme}]: {wire} B/commit, "
            f"{out[scheme]['verify_sigs_per_s']:,.0f} sigs/s, "
            f"{out[scheme]['catchup_blocks_per_s']} catch-up blocks/s"
        )
    out["wire_ratio"] = round(
        out["eddsa_batch"]["commit_wire_bytes"]
        / out["bls_aggregate"]["commit_wire_bytes"],
        2,
    )
    out["route"] = (
        "pairing-kernel" if os.environ.get("TMTPU_BLS_TPU") == "1" else "pure-python"
    )
    return out


def bench_light_fleet(
    n_vals: int = 150,
    n_clients: int = 64,
    n_heights: int = 6,
    timeout_s: float = 420.0,
) -> dict:
    """light_fleet config: N open-loop light clients syncing genesis→tip
    against ONE LightD (light/fleet.py) — the first genuinely read-heavy
    "millions of users" workload. Measured per hop-proof scheme
    (aggregate-hop vs per-sig, the arXiv:2302.00418 A/B):

      syncs/s, p50/p99 sync latency, hop-cache hit rate, shed rate
      (bounded sessions + explicit busy-shed), verify sigs/s
      (signatures COVERED per second — one aggregate pairing covers the
      whole committee), hop-proof wire bytes, and the hop-cache
      amortization factor: (cold per-client verification hops × N) /
      hops LightD actually verified.

    BOUNDED (the multichip/chaos_soak discipline): every phase runs
    under an outer asyncio timeout and returns a structured outcome on
    wedge/error — never a hang. CPU-image scale-down via
    TMTPU_BENCH_LF_VALS / _CLIENTS / _HEIGHTS (pure-python BLS signing
    dominates chain construction there; the wire and amortization
    numbers are backend-independent)."""
    import asyncio

    from tendermint_tpu import testing
    from tendermint_tpu.config import LightDConfig
    from tendermint_tpu.light import fleet as lf
    from tendermint_tpu.light.client import LightClient, TrustOptions

    chain_id = "lf-chain"
    out: dict = {
        "n_vals": n_vals,
        "n_clients": n_clients,
        "n_heights": n_heights,
        "schemes": {},
    }

    async def _one_scheme(scheme: str, chain, aggregate_hops: bool) -> dict:
        import tempfile

        from tendermint_tpu.libs.watchdog import LoopWatchdog

        # watchdog + outer timeout (the chaos_soak bounding discipline):
        # the wait_for below hard-bounds the phase; the loop watchdog
        # dumps a stack + flight-recorder report if the serving loop
        # wedges mid-phase, so a hang is diagnosable from disk
        wd = LoopWatchdog(
            tempfile.mkdtemp(prefix="light-fleet-wd-"), threshold_s=30.0
        )
        wd.start()
        trust = TrustOptions(
            period_ns=10**18, height=1, hash=chain[0].header.hash()
        )
        now = chain[-1].header.time_ns + 10**9
        # cold baseline: ONE client verifying alone — the per-client
        # work the fleet would multiply by N without a serving layer
        cold_prov = testing.make_list_provider(chain, chain_id)
        lc = LightClient(chain_id, trust, cold_prov)
        t0 = time.perf_counter()
        await lc.verify_light_block_at_height(n_heights, now)
        cold_s = time.perf_counter() - t0
        cold_hops = cold_prov.fetches  # anchor + every hop fetched

        prov = testing.make_list_provider(chain, chain_id)
        d = lf.LightD(
            chain_id,
            trust,
            prov,
            config=LightDConfig(
                max_sessions=32, aggregate_hops=aggregate_hops
            ),
        )
        await d.start()
        latencies: list[float] = []
        shed = 0

        async def one_client():
            nonlocal shed
            c0 = time.perf_counter()
            try:
                await d.sync(n_heights, now_ns=now)
            except lf.LightDBusyError:
                shed += 1
                return
            latencies.append(time.perf_counter() - c0)

        try:
            t0 = time.perf_counter()
            await asyncio.gather(*(one_client() for _ in range(n_clients)))
            elapsed = max(time.perf_counter() - t0, 1e-9)
            proof = await d.hop_proof(n_heights)
            stats = dict(d.stats)
        finally:
            await d.stop()
            wd.stop()
        latencies.sort()

        def pct(p: float) -> float:
            if not latencies:
                return 0.0
            return latencies[min(len(latencies) - 1, int(p * len(latencies)))]

        hops = max(stats["hops_verified"], 1.0)
        lookups = stats["hop_cache_hits"] + stats["hop_cache_misses"]
        return {
            "proof_scheme": proof.scheme,
            "hop_proof_wire_bytes": proof.wire_bytes(),
            "sig_bytes_per_hop": (
                96 if proof.scheme == lf.SCHEME_AGGREGATE else 64 * n_vals
            ),
            "syncs_per_s": round(len(latencies) / elapsed, 1),
            "completed": len(latencies),
            "shed": shed,
            "shed_rate": round(shed / n_clients, 4),
            "p50_sync_s": round(pct(0.50), 5),
            "p99_sync_s": round(pct(0.99), 5),
            "hop_cache_hit_rate": round(
                stats["hop_cache_hits"] / lookups if lookups else 0.0, 4
            ),
            "coalesced": stats["coalesced"],
            "hops_verified": stats["hops_verified"],
            "sigs_covered_per_s": round(hops * n_vals / elapsed, 1),
            "cold_client_s": round(cold_s, 4),
            "cold_client_hops": cold_hops,
            # the headline: verification work a cold fleet would have
            # done / work the serving layer actually did
            "amortization_factor": round(
                (cold_hops * n_clients) / max(prov.fetches, 1), 2
            ),
        }

    for scheme, key_types, agg in (
        ("per_sig", ("ed25519",), False),
        ("bls_aggregate", ("bls12381",), True),
    ):
        t0 = time.perf_counter()
        try:
            log(f"light_fleet: building {n_vals}-val {scheme} chain …")
            vals, by_addr = testing.make_validator_set(
                n_vals, key_types=key_types, seed=b"lf-" + scheme.encode()
            )
            chain = testing.make_light_chain(
                n_heights, vals, by_addr, chain_id
            )
            build_s = time.perf_counter() - t0

            async def bounded(_chain=chain, _scheme=scheme, _agg=agg):
                return await asyncio.wait_for(
                    _one_scheme(_scheme, _chain, _agg), timeout_s
                )

            rec = asyncio.run(bounded())
            rec["outcome"] = "ok"
            rec["chain_build_s"] = round(build_s, 2)
        except Exception as e:  # noqa: BLE001 — structured outcome
            rec = {"outcome": f"error: {e!r}"[:200]}
        rec["wall_s"] = round(time.perf_counter() - t0, 2)
        out["schemes"][scheme] = rec
        log(
            f"light_fleet[{scheme}]: {rec.get('outcome')} "
            f"{rec.get('syncs_per_s', 0)} syncs/s "
            f"{rec.get('sigs_covered_per_s', 0)} sigs/s "
            f"hit={rec.get('hop_cache_hit_rate', 0)} "
            f"shed={rec.get('shed_rate', 0)} "
            f"amortization={rec.get('amortization_factor', 0)}x "
            f"wire={rec.get('hop_proof_wire_bytes', 0)}B"
        )
    per, agg = out["schemes"].get("per_sig", {}), out["schemes"].get(
        "bls_aggregate", {}
    )
    if per.get("outcome") == "ok" and agg.get("outcome") == "ok":
        out["wire_ratio"] = round(
            per["hop_proof_wire_bytes"] / agg["hop_proof_wire_bytes"], 2
        )
        out["sig_bytes_ratio"] = round(
            per["sig_bytes_per_hop"] / agg["sig_bytes_per_hop"], 1
        )
    return out


def bench_statesync_fleet(
    n_blocks: int = 64,
    n_vals: int = 21,
    n_joiners: int = 8,
    ab_vals: int = 64,
    ab_heights: int = 32,
    timeout_s: float = 420.0,
) -> dict:
    """statesync config: the BootFleet mass-onboarding workload — two
    bounded phases, both structured-outcome (the chaos_soak discipline):

      join_wave    — N concurrent cold joiners statesync against ONE
                     donor's BootD over the real reactor protocol:
                     joiners/s, chunks/s, time-to-synced p50/p99, the
                     donor-overhead story (app store reads per joiner +
                     the shared-chunk-cache amortization factor), shed
                     count at the session bound.
      backfill_ab  — the hub backfill-lane verification A/B on the same
                     window shape: per-sig ed25519 commits mega-batched
                     through verify_commit_range vs a BLS committee's
                     aggregate commits (ONE pairing per height via
                     verify_hub.verify_aggregate). Verification memos
                     cleared first, so both are cold-verify rates.

    CPU-image scale-down via TMTPU_BENCH_SS_* (pure-python BLS signing
    dominates A/B chain construction there; the amortization and wire
    numbers are backend-independent)."""
    import asyncio
    import tempfile

    from tendermint_tpu import testing
    from tendermint_tpu.libs.watchdog import LoopWatchdog
    from tendermint_tpu.statesync.fleet import verify_backfill_batch

    out: dict = {
        "n_blocks": n_blocks,
        "n_vals": n_vals,
        "n_joiners": n_joiners,
        "join_wave": {},
        "backfill_ab": {"n_vals": ab_vals, "n_heights": ab_heights},
    }

    # -- phase 1: the join wave -----------------------------------------
    t0 = time.perf_counter()
    try:
        wd = LoopWatchdog(
            tempfile.mkdtemp(prefix="statesync-wd-"), threshold_s=30.0
        )

        async def wave() -> dict:
            wd.start()
            try:
                return await asyncio.wait_for(
                    testing.statesync_fleet_scenario(
                        n_blocks, n_vals, n_joiners
                    ),
                    timeout_s,
                )
            finally:
                wd.stop()

        res = asyncio.run(wave())
        lat = sorted(res["time_to_synced_s"])

        def pct(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        st = res["server_stats"]
        elapsed = max(res["elapsed_s"], 1e-9)
        rec = {
            "outcome": "ok" if res["joined"] == n_joiners else "partial",
            "joined": res["joined"],
            "join_errors": res["join_errors"][:4],
            "joiners_per_s": round(res["joined"] / elapsed, 2),
            "chunks_per_s": round(st["chunks_served"] / elapsed, 1),
            "p50_time_to_synced_s": round(pct(0.50), 4),
            "p99_time_to_synced_s": round(pct(0.99), 4),
            "sheds": st["sheds"],
            "cache_hit_rate": round(
                st["cache_hits"]
                / max(st["cache_hits"] + st["cache_misses"], 1),
                4,
            ),
            # donor overhead: what serving the whole wave actually cost
            # the donor's app — reads amortized by the shared cache
            "donor_store_reads": st["store_reads"],
            "donor_store_reads_per_joiner": round(
                st["store_reads"] / max(res["joined"], 1), 3
            ),
            "chunk_amortization_factor": round(
                st["chunks_served"] / max(st["store_reads"], 1), 2
            ),
            "backfill_sigs": res["joiner_backfill"]["backfill_sigs"],
            "backfill_sigs_per_s": round(
                res["joiner_backfill"]["backfill_sigs"] / elapsed, 1
            ),
            "backfill_batches": res["joiner_backfill"]["backfill_batches"],
        }
    except Exception as e:  # noqa: BLE001 — structured outcome
        rec = {"outcome": f"error: {e!r}"[:200]}
    rec["wall_s"] = round(time.perf_counter() - t0, 2)
    out["join_wave"] = rec
    log(
        f"statesync[join_wave]: {rec.get('outcome')} "
        f"{rec.get('joiners_per_s', 0)} joiners/s "
        f"{rec.get('chunks_per_s', 0)} chunks/s "
        f"p99={rec.get('p99_time_to_synced_s', 0)}s "
        f"amortization={rec.get('chunk_amortization_factor', 0)}x"
    )

    # -- phase 2: backfill verification A/B -----------------------------
    from tendermint_tpu.crypto import bls_math
    from tendermint_tpu.crypto import ed25519 as _ed
    from tendermint_tpu.light.types import LightBlock, SignedHeader
    from tendermint_tpu.types.block import aggregate_commit

    chain_id = "ssab-chain"
    for scheme, key_types, agg in (
        ("per_sig", ("ed25519",), False),
        ("bls_aggregate", ("bls12381",), True),
    ):
        t0 = time.perf_counter()
        try:
            log(f"statesync: building {ab_vals}-val {scheme} backfill window …")
            vals, by_addr = testing.make_validator_set(
                ab_vals, key_types=key_types, seed=b"ssab-" + scheme.encode()
            )
            window = testing.make_light_chain(
                ab_heights, vals, by_addr, chain_id
            )
            if agg:
                window = [
                    LightBlock(
                        SignedHeader(
                            lb.signed_header.header,
                            aggregate_commit(lb.signed_header.commit, vals),
                        ),
                        vals,
                    )
                    for lb in window
                ]
            wire = len(window[0].signed_header.commit.encode())
            bls_math._H2_MEMO.clear()
            _ed._VERIFY_MEMO.clear()

            async def bounded(_w=window):
                return await asyncio.wait_for(
                    verify_backfill_batch(chain_id, _w), timeout_s
                )

            v0 = time.perf_counter()
            n_sigs = asyncio.run(bounded())
            dt = max(time.perf_counter() - v0, 1e-9)
            rec = {
                "outcome": "ok",
                "commit_wire_bytes": wire,
                "heights_per_s": round(ab_heights / dt, 1),
                "verify_sigs": n_sigs,
                # signatures COVERED per second: an aggregate commit
                # covers the committee with one pairing
                "sigs_covered_per_s": round(ab_heights * ab_vals / dt, 1),
            }
        except Exception as e:  # noqa: BLE001 — structured outcome
            rec = {"outcome": f"error: {e!r}"[:200]}
        rec["wall_s"] = round(time.perf_counter() - t0, 2)
        out["backfill_ab"][scheme] = rec
        log(
            f"statesync[backfill:{scheme}]: {rec.get('outcome')} "
            f"{rec.get('heights_per_s', 0)} heights/s "
            f"{rec.get('sigs_covered_per_s', 0)} sigs-covered/s "
            f"wire={rec.get('commit_wire_bytes', 0)}B"
        )
    per = out["backfill_ab"].get("per_sig", {})
    agg_rec = out["backfill_ab"].get("bls_aggregate", {})
    if per.get("outcome") == "ok" and agg_rec.get("outcome") == "ok":
        out["backfill_ab"]["wire_ratio"] = round(
            per["commit_wire_bytes"] / agg_rec["commit_wire_bytes"], 2
        )
    return out


def _multichip_measure(n_sigs: int, reps: int = 2) -> dict:
    """multichip config, in-process half: sharded vs single-device
    verification of the same batch on whatever mesh this process sees.
    Returns sigs/s for both routes plus per-device shard occupancy from
    the dispatch telemetry (the MULTICHIP_r01–r05 rc=124 blindness,
    replaced with data)."""
    import numpy as np

    import jax

    if os.environ.get("_TMTPU_MULTICHIP_CHILD"):
        # virtual mesh child: the ambient sitecustomize latches the axon
        # platform at interpreter start — pin the live config to CPU
        jax.config.update("jax_platforms", "cpu")
    from tendermint_tpu.crypto import backend_telemetry as bt
    from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
    from tendermint_tpu.crypto.tpu import verify as tpuv

    n_dev = len(jax.devices())
    out: dict = {"n_devices": n_dev, "n_sigs": n_sigs}
    if n_dev < 2:
        out["skipped"] = "single-device mesh; nothing to shard"
        return out

    items = []
    for i in range(n_sigs):
        priv = Ed25519PrivKey((i + 1).to_bytes(4, "little") * 8)
        msg = b"multichip-%d" % i
        items.append((priv.pub_key().bytes(), msg, priv.sign(msg)))

    def timed(env_on: dict, env_off: list) -> tuple[float, float]:
        for k in env_off:
            os.environ.pop(k, None)
        os.environ.update(env_on)
        try:
            t0 = time.perf_counter()
            bm = tpuv.verify_batch_eq(items)
            warm_s = time.perf_counter() - t0
            assert bool(np.asarray(bm).all()), "multichip batch rejected"
            t0 = time.perf_counter()
            for _ in range(reps):
                bm = tpuv.verify_batch_eq(items)
            return (time.perf_counter() - t0) / reps, warm_s
        finally:
            for k in env_on:
                os.environ.pop(k, None)

    single_dt, single_warm = timed({"TMTPU_NO_SHARDED": "1"}, ["TMTPU_FORCE_SHARDED"])
    bt.SHARD_SIGS.clear()
    shard_dt, shard_warm = timed({"TMTPU_FORCE_SHARDED": "1"}, ["TMTPU_NO_SHARDED"])
    info = tpuv.last_dispatch_info() or {}
    # shard capacity: every chunk pads to one shared bucket, split evenly
    chunk = min(n_sigs, tpuv._MAX_BUCKET)
    n_chunks = (n_sigs + tpuv._MAX_BUCKET - 1) // tpuv._MAX_BUCKET
    bucket = tpuv._bucket(chunk, n_dev)
    cap_per_dev = (bucket // n_dev) * n_chunks * (reps + 1)
    per_sigs = {k: int(v) for k, v in bt.SHARD_SIGS.items()}
    out.update(
        single_sigs_per_s=round(n_sigs / single_dt, 1),
        sharded_sigs_per_s=round(n_sigs / shard_dt, 1),
        speedup=round(single_dt / shard_dt, 2),
        single_warm_s=round(single_warm, 2),
        sharded_warm_s=round(shard_warm, 2),
        bucket=bucket,
        per_device_sigs=per_sigs,
        per_device_occupancy={
            k: round(v / cap_per_dev, 3) for k, v in per_sigs.items()
        },
        devices=info.get("devices"),
        mesh=dict(bt.MESH),
    )
    log(
        f"multichip: {out['sharded_sigs_per_s']:,.1f} sigs/s sharded over "
        f"{n_dev} devices vs {out['single_sigs_per_s']:,.1f} single "
        f"-> {out['speedup']}x"
    )
    return out


def bench_multichip(timeout_s: float = 600.0) -> dict:
    """multichip config driver — BOUNDED, always returns a record (the
    structured replacement for the rc=124 probe timeouts). With a real
    multi-device mesh attached it measures in-process; on a single-device
    or CPU image it re-runs the measurement in a subprocess pinned to a
    virtual 8-device CPU mesh (`--xla_force_host_platform_device_count`),
    with a hard subprocess timeout instead of an unbounded hang."""
    import subprocess
    import threading as _threading

    import jax

    res: dict = {}

    def probe():
        try:
            res["n"] = len(jax.devices())
        except Exception as e:  # noqa: BLE001
            res["error"] = repr(e)

    t = _threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(60.0)
    n_dev = res.get("n", 0)
    if n_dev >= 2:
        n_sigs = int(os.environ.get("TMTPU_BENCH_MULTICHIP_SIGS", "8192"))
        out = _multichip_measure(n_sigs)
        out["virtual_mesh"] = False
        return out

    # virtual-mesh subprocess: fresh interpreter, forced 8-device CPU
    # topology, hard timeout — a wedged child is a structured outcome
    env = dict(os.environ)
    env["_TMTPU_MULTICHIP_CHILD"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    n_sigs = int(os.environ.get("TMTPU_BENCH_MULTICHIP_SIGS", "512"))
    code = (
        "import json, bench; "
        f"print('MULTICHIP_JSON ' + json.dumps(bench._multichip_measure({n_sigs})))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {
            "n_devices": n_dev,
            "virtual_mesh": True,
            "outcome": f"timeout after {timeout_s:.0f}s (bounded — no rc=124)",
        }
    for line in proc.stdout.splitlines():
        if line.startswith("MULTICHIP_JSON "):
            out = json.loads(line[len("MULTICHIP_JSON "):])
            out["virtual_mesh"] = True
            out["outcome"] = "ok"
            return out
    return {
        "n_devices": n_dev,
        "virtual_mesh": True,
        "outcome": f"child rc={proc.returncode}, no record",
        "stderr_tail": proc.stderr[-500:],
    }


def _verifyd_worker(n_sigs: int) -> None:
    """verifyd config, worker half (runs in a subprocess): flood one
    hub with single-signature submissions and report aggregate rate +
    per-signature latency percentiles. With TMTPU_VERIFYD_SOCK in the
    env the hub ships its packed batches to the shared daemon (the
    sidecar shape); without it the worker pays its own in-process
    backend — the N-cold-attaches baseline."""
    import time as _t

    from tendermint_tpu.crypto import backend_telemetry as bt
    from tendermint_tpu.crypto import verifyd as vdmod
    from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
    from tendermint_tpu.crypto.verify_hub import VerifyHub

    wid = os.environ.get("_TMTPU_VD_WORKER", "0")
    priv = Ed25519PrivKey(int(wid).to_bytes(4, "big") * 8)
    pub = priv.pub_key()
    tag = b"vd-bench-%s-" % wid.encode()
    items = [(tag + b"%d" % i, priv.sign(tag + b"%d" % i)) for i in range(n_sigs)]

    hub = VerifyHub(window_ms=2.0, cache_size=0)
    hub.start()
    lats: list[float] = []
    bad: list[int] = []
    try:
        futs = []
        t0 = _t.perf_counter()
        for msg, sig in items:
            t_sub = _t.perf_counter()
            fut = hub.submit_nowait(pub, msg, sig)
            fut.add_done_callback(
                lambda f, t=t_sub: lats.append(_t.perf_counter() - t)
            )
            futs.append(fut)
        hub.flush()
        for f in futs:
            if not f.result(timeout=300):
                bad.append(1)
        dt = _t.perf_counter() - t0
    finally:
        hub.stop()
    assert not bad, f"{len(bad)} wrong verdicts"
    # hub.stop() above joined the runner thread that fires the
    # done-callbacks; sorted() copies first anyway, so a straggler
    # append can never corrupt the sort
    lats = sorted(lats)
    p = lambda q: lats[min(len(lats) - 1, int(q * len(lats)))] if lats else 0.0  # noqa: E731
    print(
        "VERIFYD_WORKER_JSON "
        + json.dumps(
            {
                "sigs": n_sigs,
                "dt_s": round(dt, 3),
                "sigs_per_s": round(n_sigs / dt, 1),
                "verify_p50_ms": round(p(0.50) * 1e3, 3),
                "verify_p99_ms": round(p(0.99) * 1e3, 3),
                "remote_dispatches": int(
                    vdmod.CLIENT_STATS["remote_dispatches"]
                ),
                "remote_fallbacks": int(vdmod.CLIENT_STATS["remote_fallbacks"]),
                "attach_attempts": int(bt.BACKEND["attach_attempts"]),
            }
        ),
        flush=True,
    )


def bench_verifyd(
    n_workers: int = 4, sigs_per_worker: int = 1000, timeout_s: float = 600.0
) -> dict:
    """verifyd config driver — BOUNDED, structured outcomes only (the
    multichip discipline: hard subprocess timeouts, never an rc=124
    probe). N worker processes flood ONE daemon over its UDS, then the
    same N workers run against their own in-process backends; reports
    aggregate sigs/s for both shapes, the attach counts (1 daemon
    attach vs N worker attaches — the amortization headline), p50/p99
    per-signature verify latency, and the daemon's cross-client batch
    occupancy. On CPU-only images local workers set TMTPU_DISABLE_TPU
    (a JAX-CPU warm compile per worker would measure XLA, not the
    socket); the attach-count A/B is the real-TPU-round story."""
    import subprocess
    import tempfile

    sock = os.path.join(tempfile.mkdtemp(prefix="vd-bench-"), "vd.sock")
    repo = os.path.dirname(os.path.abspath(__file__))
    base_env = dict(os.environ, PYTHONPATH=repo)

    def run_workers(env_extra: dict) -> list[dict] | str:
        procs = []
        for i in range(n_workers):
            env = dict(base_env, _TMTPU_VD_WORKER=str(i + 1), **env_extra)
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable,
                        "-c",
                        f"import bench; bench._verifyd_worker({sigs_per_worker})",
                    ],
                    env=env,
                    cwd=repo,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL,
                    text=True,
                )
            )
        out = []
        deadline = time.monotonic() + timeout_s
        for p in procs:
            try:
                stdout, _ = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic())
                )
            except subprocess.TimeoutExpired:
                # kill EVERY worker, not just the timed-out one: a
                # leaked sibling would keep flooding through the local
                # baseline pass and skew the A/B this config reports
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                for q in procs:
                    q.wait()
                return f"worker timeout after {timeout_s:.0f}s (bounded)"
            for line in stdout.splitlines():
                if line.startswith("VERIFYD_WORKER_JSON "):
                    out.append(json.loads(line[len("VERIFYD_WORKER_JSON "):]))
        if len(out) != n_workers:
            return f"{len(out)}/{n_workers} workers reported"
        return out

    def agg(records: list[dict]) -> dict:
        wall = max(r["dt_s"] for r in records)
        return {
            "sigs_per_s": round(sum(r["sigs"] for r in records) / wall, 1),
            "verify_p50_ms": round(
                sorted(r["verify_p50_ms"] for r in records)[len(records) // 2], 3
            ),
            "verify_p99_ms": round(max(r["verify_p99_ms"] for r in records), 3),
            "attach_attempts": sum(r["attach_attempts"] for r in records),
            "remote_dispatches": sum(r["remote_dispatches"] for r in records),
            "remote_fallbacks": sum(r["remote_fallbacks"] for r in records),
        }

    out: dict = {"workers": n_workers, "sigs_per_worker": sigs_per_worker}
    daemon_env = dict(base_env)
    on_cpu = os.environ.get("TMTPU_BENCH_FORCED_CPU") == "1" or os.environ.get(
        "JAX_PLATFORMS"
    ) == "cpu"
    if on_cpu:
        # keep the daemon's background warm at the floor shape: the
        # config measures socket amortization here, not XLA compile
        daemon_env["TMTPU_MAX_BUCKET"] = "64"
    daemon = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "from tendermint_tpu.cli import main; "
            f"main(['verifyd', '--sock', {sock!r}])",
        ],
        env=daemon_env,
        cwd=repo,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        from tendermint_tpu.crypto.verifyd import VerifydClient

        stats = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            c = VerifydClient(sock)
            stats = c.remote_stats()
            c.close()
            if stats is not None:
                break
            time.sleep(0.5)
        if stats is None:
            out["outcome"] = "daemon never came up (bounded)"
            return out

        remote = run_workers({"TMTPU_VERIFYD_SOCK": sock})
        c = VerifydClient(sock)
        dstats = c.remote_stats()
        c.close()
    finally:
        daemon.kill()
        daemon.wait()
    local = run_workers(
        {"TMTPU_DISABLE_TPU": "1"} if on_cpu else {"TMTPU_MAX_BUCKET": "64"}
    )
    if isinstance(remote, str) or isinstance(local, str):
        out["outcome"] = remote if isinstance(remote, str) else local
        return out
    out["remote"] = agg(remote)
    out["local"] = agg(local)
    out["speedup_vs_local"] = round(
        out["remote"]["sigs_per_s"] / max(out["local"]["sigs_per_s"], 1e-9), 2
    )
    if dstats is not None:
        out["daemon"] = {
            "attach_attempts": dstats["backend"]["attach_attempts"],
            "active_kind": dstats["backend"]["active_kind"],
            "requests": dstats["daemon"]["requests"],
            "sigs": dstats["daemon"]["sigs"],
            "shed": dstats["daemon"]["shed"],
            "batch_occupancy": round(dstats["hub"]["mean_occupancy"], 2),
            "cross_client_packs": dstats["hub"]["cross_tenant_dispatches"],
        }
        # the headline: one attach serves every worker on the host
        out["attach_count_sidecar"] = dstats["backend"]["attach_attempts"]
        out["attach_count_local"] = out["local"]["attach_attempts"]
    out["outcome"] = "ok"
    log(
        f"verifyd: {out['remote']['sigs_per_s']:,.1f} sigs/s via sidecar "
        f"(occupancy {out.get('daemon', {}).get('batch_occupancy', '?')}, "
        f"{out.get('daemon', {}).get('cross_client_packs', '?')} cross-client "
        f"packs, p99 {out['remote']['verify_p99_ms']}ms) vs "
        f"{out['local']['sigs_per_s']:,.1f} local -> {out['speedup_vs_local']}x; "
        f"attaches {out.get('attach_count_sidecar', '?')} vs "
        f"{out.get('attach_count_local', '?')}"
    )
    return out


def main() -> None:
    import numpy as np

    from tendermint_tpu.crypto.batch import CPUBatchVerifier
    from tendermint_tpu.crypto.ed25519 import Ed25519PubKey
    from tendermint_tpu.crypto.tpu import verify as tpuv

    # backend first: the workload size depends on what we're running on —
    # on the CPU fallback the full 8192-signature range would take tens of
    # minutes and blow any driver time budget (the round-1 value=0 mode).
    backend = init_backend()
    log(f"jax backend: {backend}")
    reps = 3
    if backend == "cpu":
        # CPU fallback exists to record a nonzero number, not to race the
        # chip: tiny batch, one bucket, secondary configs skipped
        default_commits, reps = "3", 1
    else:
        default_commits = str(TPU_RANGE_COMMITS)
    n_commits = int(os.environ.get("TMTPU_BENCH_COMMITS", default_commits))

    n_vals = 150
    chain_id = "bench-chain"
    log(f"building {n_vals}-validator set + commits …")
    vals, keys, commits, items = _build_commit_items(n_vals, n_commits, chain_id)
    log(f"{len(commits)} commits, {len(items)} signatures")

    # -- CPU baseline -----------------------------------------------------
    base_items = items[: n_vals * 4]
    bv = CPUBatchVerifier(parallel=False)
    for pub, msg, sig in base_items:
        bv.add(Ed25519PubKey(pub), msg, sig)
    t0 = time.perf_counter()
    ok, _ = bv.verify()
    cpu_dt = time.perf_counter() - t0
    assert ok, "CPU baseline verification failed"
    cpu_rate = len(base_items) / cpu_dt
    log(f"CPU baseline (1 thread): {cpu_rate:,.0f} sigs/s ({cpu_dt*1e3:.1f} ms / {len(base_items)})")

    bv = CPUBatchVerifier(parallel=True)
    for pub, msg, sig in base_items:
        bv.add(Ed25519PubKey(pub), msg, sig)
    bv.verify()  # warm the pool
    bv2 = CPUBatchVerifier(parallel=True)
    for pub, msg, sig in base_items:
        bv2.add(Ed25519PubKey(pub), msg, sig)
    t0 = time.perf_counter()
    ok, _ = bv2.verify()
    cpu_mt_dt = time.perf_counter() - t0
    cpu_mt_rate = len(base_items) / cpu_mt_dt
    log(
        f"CPU baseline ({os.cpu_count()} cores): {cpu_mt_rate:,.0f} sigs/s "
        f"({cpu_mt_dt*1e3:.1f} ms / {len(base_items)})"
    )

    # -- TPU path (batch-equation kernel) --------------------------------
    # warmup (compile; persistent cache makes repeat runs cheap). Run it on
    # a watchdog thread: a tunnel that came up for init can still wedge on
    # the first compile/execute, and a hang here must degrade to the CPU
    # re-exec, not eat the driver's whole time budget silently.
    t0 = time.perf_counter()
    wres: dict = {}

    def do_warmup():
        try:
            wres["bitmap"] = tpuv.verify_batch_eq(items)
        except Exception as e:  # noqa: BLE001
            wres["error"] = e

    wt = threading.Thread(target=do_warmup, daemon=True)
    wt.start()
    wt.join(900.0 if backend != "cpu" else 3600.0)
    if "bitmap" not in wres:
        if os.environ.get("TMTPU_BENCH_FORCED_CPU") == "1" or backend == "cpu":
            raise RuntimeError(f"warmup failed on CPU backend: {wres.get('error')!r}")
        # a tunnel that came up for init can still wedge on the first
        # compile/execute: worth one fresh-process TPU retry before CPU
        _record_attach(
            {
                "latency_s": round(time.perf_counter() - t0, 3),
                "outcome": "warmup-hung" if wres.get("error") is None else "warmup-error",
                "reason": repr(wres.get("error")),
                "device_kind": backend,
            }
        )
        reexec_fresh_tpu(
            f"warmup hung/failed on {backend} ({wres.get('error')!r})",
            "TMTPU_BENCH_WARMUP_RETRY",
            max_tries=2,
        )
    bitmap = wres["bitmap"]
    assert bool(np.all(bitmap)), "verification failed on valid commits"
    compile_s = time.perf_counter() - t0
    log(f"warmup+compile: {compile_s:.1f}s")
    # classify the range-shape compile against the persistent cache
    # (hit ≈ deserialize, well under a second even for the 8192 bucket)
    from tendermint_tpu.crypto import backend_telemetry as _bt

    _bt.record_compile("bench-range", compile_s)

    # rejection path on a SMALL batch (the per-signature fallback kernel
    # compiles at the floor bucket, not the big range bucket)
    t0 = time.perf_counter()
    bad_items = list(items[:64])
    pub0, msg0, sig0 = bad_items[7]
    bad_items[7] = (pub0, msg0, sig0[:63] + bytes([sig0[63] ^ 0x01]))
    bm = tpuv.verify_batch_eq(bad_items)
    assert not bm[7] and bm[:7].all() and bm[8:].all(), "bad-sig bitmap wrong"
    log(f"corrupted-signature rejection: ok ({time.perf_counter()-t0:.1f}s incl fallback compile)")

    t0 = time.perf_counter()
    for _ in range(reps):
        bitmap = tpuv.verify_batch_eq(items)
    tpu_dt = (time.perf_counter() - t0) / reps
    assert bool(np.all(bitmap))
    tpu_rate = len(items) / tpu_dt
    log(f"{backend} end-to-end: {tpu_rate:,.0f} sigs/s ({tpu_dt*1e3:.1f} ms / {len(items)})")

    # -- secondary configs (BASELINE.md 2-5) ------------------------------
    extra = {}
    if backend != "cpu":
        from tendermint_tpu.crypto import batch as crypto_batch

        crypto_batch.tpu_wait_available()
        try:
            extra["kernel_breakdown"] = kernel_breakdown(items)
        except Exception as e:  # noqa: BLE001
            log(f"kernel breakdown failed: {e!r}")
        try:
            extra["light_headers_per_s"] = round(bench_light_client(1000, n_vals), 1)
        except Exception as e:  # noqa: BLE001
            log(f"light bench failed: {e!r}")
        try:
            extra["blocksync_blocks_per_s"] = round(
                bench_blocksync(1024, n_vals, window=TPU_RANGE_COMMITS), 1
            )
        except Exception as e:  # noqa: BLE001
            log(f"blocksync bench failed: {e!r}")
        try:
            extra["mixed_commit_sigs_per_s"] = round(bench_mixed_commit(n_vals, 4), 1)
        except Exception as e:  # noqa: BLE001
            log(f"mixed-key bench failed: {e!r}")
        try:
            extra["statesync_blocks_per_s"] = round(bench_statesync(64, 21), 1)
        except Exception as e:  # noqa: BLE001
            log(f"statesync bench failed: {e!r}")
    else:
        log("secondary configs skipped on cpu fallback")
    # hub config runs on BOTH backends: it measures the scheduler
    # (coalescing + dedup), which must beat the sequential single-vote
    # path even on the pure-CPU fallback
    try:
        n_sub = int(os.environ.get("TMTPU_BENCH_HUB_SUBMITTERS", "8"))
        per = 200 if backend != "cpu" else 40
        extra["verify_hub"] = bench_verify_hub(n_vals, n_sub, per)
    except Exception as e:  # noqa: BLE001
        log(f"verify-hub bench failed: {e!r}")
    # consensus_ingest runs on BOTH backends: it measures the pipelined
    # receive path (async hub adoption + in-order apply) against the
    # sequential facade on one node — the single-node occupancy story
    try:
        waves = 6 if backend != "cpu" else 3
        extra["consensus_ingest"] = bench_consensus_ingest(64, waves, 8)
    except Exception as e:  # noqa: BLE001
        log(f"consensus-ingest bench failed: {e!r}")
    # tx_flood runs on BOTH backends: the front-door admission pipeline
    # (bounded intake -> batched envelope verify on the hub backfill
    # lane -> nonce lanes -> CheckTx) under a 10k-client open-loop
    # flood; CPU images scale the client count down like the other
    # configs (pure-python signing would eat the driver budget)
    try:
        n_clients = 10_000 if backend != "cpu" else 300
        extra["tx_flood"] = bench_tx_flood(
            int(os.environ.get("TMTPU_BENCH_FLOOD_CLIENTS", str(n_clients))), 2
        )
    except Exception as e:  # noqa: BLE001
        log(f"tx-flood bench failed: {e!r}")
    # crash_recovery runs on BOTH backends: WAL repair + replay is pure
    # host work, and recovery downtime is a headline robustness number
    try:
        extra["crash_recovery"] = bench_crash_recovery()
    except Exception as e:  # noqa: BLE001
        log(f"crash-recovery bench failed: {e!r}")
    # chaos_soak runs on BOTH backends, BOUNDED: blocks/s +
    # time-to-recover per fault scenario over real routers +
    # ChaosTransport (RouterNet) at 4 and 50 validators — the robustness
    # trajectory measured per round. Pure host/event-loop work; the
    # device is not on this path.
    if os.environ.get("TMTPU_BENCH_CHAOS_SOAK") != "0":
        try:
            soak_vals = tuple(
                int(v)
                for v in os.environ.get(
                    "TMTPU_BENCH_SOAK_VALS", "4,50"
                ).split(",")
                if v.strip()
            )
            extra["chaos_soak"] = bench_chaos_soak(soak_vals)
        except Exception as e:  # noqa: BLE001
            log(f"chaos-soak bench failed: {e!r}")
    # wiregen runs on BOTH backends, BOUNDED: the compiled hot codec
    # (consensus/wire_gen.py, regenerated from the wire-schema lockfile
    # by scripts/wiregen) A/B'd against the interpreted codec —
    # per-family encode/decode frames/s plus chaos_soak blocks/s with
    # the codec flipped. Pure host work; the device is not on this path.
    if os.environ.get("TMTPU_BENCH_WIREGEN") != "0":
        try:
            wg_vals = int(os.environ.get("TMTPU_BENCH_WIREGEN_VALS", "50"))
            extra["wiregen"] = bench_wiregen(wg_vals)
        except Exception as e:  # noqa: BLE001
            log(f"wiregen bench failed: {e!r}")
    # merkle runs on BOTH backends, BOUNDED: the HashHub level-order
    # batched tree builder A/B'd against the scalar recursive reference
    # — leaves/s at 64/1k/16k, header-hash/s, and chaos_soak blocks/s
    # with the builder flipped. CPU-half is the acceptance number; the
    # device bucket route engages only under TMTPU_HASH_TPU=1.
    if os.environ.get("TMTPU_BENCH_MERKLE") != "0":
        try:
            mk_vals = int(os.environ.get("TMTPU_BENCH_MERKLE_VALS", "50"))
            extra["merkle"] = bench_merkle(mk_vals)
        except Exception as e:  # noqa: BLE001
            log(f"merkle bench failed: {e!r}")
    # byz_soak runs on BOTH backends, BOUNDED: Byzantine strategies over
    # real routers — blocks/s per strategy, time-to-evidence-commit,
    # and the cross-node safety auditor's verdict at 4 and 50
    # validators. Pure host/event-loop work like chaos_soak.
    if os.environ.get("TMTPU_BENCH_BYZ_SOAK") != "0":
        try:
            byz_vals = tuple(
                int(v)
                for v in os.environ.get(
                    "TMTPU_BENCH_BYZ_VALS", "4,50"
                ).split(",")
                if v.strip()
            )
            extra["byz_soak"] = bench_byz_soak(byz_vals)
        except Exception as e:  # noqa: BLE001
            log(f"byz-soak bench failed: {e!r}")
    # routernet_xl runs on BOTH backends, BOUNDED: multi-process
    # committees over real TCP/UDS sockets — blocks/s + time-to-recover
    # from a SIGKILLed worker per (validators × workers) row, the
    # TCP vs UDS vs memory transport A/B, and shared-verifyd occupancy.
    # Worker processes are spawned with JAX_PLATFORMS=cpu; the bench
    # process's device is not on this path.
    if os.environ.get("TMTPU_BENCH_ROUTERNET_XL") != "0":
        try:
            xl_rows = tuple(
                (int(r.split(":")[0]), int(r.split(":")[1]))
                for r in os.environ.get(
                    "TMTPU_BENCH_XL_ROWS", "50:2"
                ).split(",")
                if r.strip()
            )
            extra["routernet_xl"] = bench_routernet_xl(xl_rows)
        except Exception as e:  # noqa: BLE001
            log(f"routernet-xl bench failed: {e!r}")
    # commit_ab runs on BOTH backends: the aggregate-signature A/B —
    # EdDSA-batch vs BLS-aggregate on the same 150-validator chain
    # (commit wire bytes x verify sigs/s x catch-up blocks/s). On CPU
    # images the pure-Python pairing dominates the BLS side; the wire
    # numbers are backend-independent.
    try:
        ab_vals = int(os.environ.get("TMTPU_BENCH_AB_VALS", "150"))
        extra["commit_ab"] = bench_commit_ab(
            ab_vals, 4 if backend != "cpu" else 2
        )
    except Exception as e:  # noqa: BLE001
        log(f"commit-ab bench failed: {e!r}")
    # light_fleet runs on BOTH backends, BOUNDED: N open-loop light
    # clients syncing genesis→tip against one LightD — syncs/s, sigs/s,
    # hop-cache hit rate, shed rate, p50/p99 sync latency, and the
    # aggregate-hop vs per-sig A/B (wire bytes × sigs/s × syncs/s). On
    # CPU images the committee scales down (pure-python BLS signing
    # dominates chain construction); wire + amortization numbers are
    # backend-independent.
    if os.environ.get("TMTPU_BENCH_LIGHT_FLEET") != "0":
        try:
            lf_vals = int(
                os.environ.get(
                    "TMTPU_BENCH_LF_VALS",
                    "150" if backend != "cpu" else "25",
                )
            )
            lf_clients = int(
                os.environ.get(
                    "TMTPU_BENCH_LF_CLIENTS",
                    "64" if backend != "cpu" else "24",
                )
            )
            lf_heights = int(
                os.environ.get(
                    "TMTPU_BENCH_LF_HEIGHTS",
                    "6" if backend != "cpu" else "4",
                )
            )
            extra["light_fleet"] = bench_light_fleet(
                lf_vals, lf_clients, lf_heights
            )
        except Exception as e:  # noqa: BLE001
            log(f"light-fleet bench failed: {e!r}")
    # statesync runs on BOTH backends, BOUNDED: the BootFleet
    # mass-onboarding workload — N cold joiners vs one donor's BootD
    # (joiners/s, chunks/s, time-to-synced p50/p99, donor store-read
    # amortization) plus the hub backfill-lane per-sig vs bls-aggregate
    # verification A/B. On CPU images the committee and wave scale down
    # (pure-python BLS dominates A/B chain construction); amortization
    # and wire numbers are backend-independent.
    if os.environ.get("TMTPU_BENCH_STATESYNC") != "0":
        try:
            ss_blocks = int(
                os.environ.get(
                    "TMTPU_BENCH_SS_BLOCKS",
                    "64" if backend != "cpu" else "48",
                )
            )
            ss_vals = int(
                os.environ.get(
                    "TMTPU_BENCH_SS_VALS",
                    "21" if backend != "cpu" else "7",
                )
            )
            ss_joiners = int(
                os.environ.get(
                    "TMTPU_BENCH_SS_JOINERS",
                    "8" if backend != "cpu" else "4",
                )
            )
            ss_ab_vals = int(
                os.environ.get(
                    "TMTPU_BENCH_SS_AB_VALS",
                    "64" if backend != "cpu" else "16",
                )
            )
            ss_ab_heights = int(
                os.environ.get(
                    "TMTPU_BENCH_SS_AB_HEIGHTS",
                    "32" if backend != "cpu" else "8",
                )
            )
            extra["statesync"] = bench_statesync_fleet(
                ss_blocks, ss_vals, ss_joiners, ss_ab_vals, ss_ab_heights
            )
        except Exception as e:  # noqa: BLE001
            log(f"statesync bench failed: {e!r}")
    # verifyd runs on BOTH backends, BOUNDED: N worker processes flood
    # one sidecar daemon vs N in-process backends — aggregate sigs/s,
    # attach counts (the one-warm-mesh amortization headline), p99
    # verify latency, cross-client batch occupancy. CPU images scale
    # down (the daemon verifies pure-python there; the attach-count A/B
    # is the real-TPU-round story).
    if os.environ.get("TMTPU_BENCH_VERIFYD") != "0":
        try:
            n_w = int(os.environ.get("TMTPU_BENCH_VERIFYD_WORKERS", "4"))
            n_s = int(
                os.environ.get(
                    "TMTPU_BENCH_VERIFYD_SIGS",
                    "2000" if backend != "cpu" else "200",
                )
            )
            extra["verifyd"] = bench_verifyd(n_w, n_s)
        except Exception as e:  # noqa: BLE001
            log(f"verifyd bench failed: {e!r}")
    # multichip runs on BOTH backends, BOUNDED (the rc=124 probes were
    # the only multi-device signal for five rounds): sharded vs
    # single-device sigs/s + per-device shard occupancy, on the real
    # mesh when one is attached, else on a virtual 8-device CPU mesh in
    # a hard-timeout subprocess
    if os.environ.get("TMTPU_BENCH_MULTICHIP") != "0":
        try:
            extra["multichip"] = bench_multichip()
        except Exception as e:  # noqa: BLE001
            log(f"multichip bench failed: {e!r}")
    extra["cpu_multicore_sigs_per_s"] = round(cpu_mt_rate, 1)

    # structured backend-attach phase record (ROADMAP: attach-rate as a
    # first-class metric): attach failures, per-attempt latency, chosen
    # fallback and compile/warm split are diagnosable from this JSON
    # alone — no stderr archaeology required for the next re-anchor
    from tendermint_tpu.crypto import backend_telemetry as bt

    attach_attempts = _attach_log()
    extra["backend_attach"] = {
        "device_kind": backend,
        "attach_ok": backend != "cpu"
        and os.environ.get("TMTPU_BENCH_FORCED_CPU") != "1",
        "forced_cpu": os.environ.get("TMTPU_BENCH_FORCED_CPU") == "1",
        "attempts": attach_attempts,
        "attach_ms": round(
            sum(a.get("latency_s", 0.0) for a in attach_attempts) * 1e3, 1
        ),
        "compile_ms": round(compile_s * 1e3, 1),  # first-call compile+warm
        "warm_ms": round(tpu_dt * 1e3, 3),  # steady-state warmed call
        # persistent-compile-cache outcome per shape (compile_ms ≈ 0 on
        # a warm cache): the attach item's measurable other half
        "compile_cache": {
            "hits": int(bt.BACKEND["compile_cache_hits"]),
            "misses": int(bt.BACKEND["compile_cache_misses"]),
            "per_shape": dict(bt.COMPILE_CACHE),
        },
        "telemetry": bt.snapshot(),
    }

    print(
        json.dumps(
            {
                "metric": "commit sigs verified/sec (150-validator commits, ed25519, range-batched)",
                "value": round(tpu_rate, 1),
                "unit": "sigs/sec",
                "vs_baseline": round(tpu_rate / cpu_rate, 2),
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always emit the one line the driver expects
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "commit sigs verified/sec (150-validator commits, ed25519, range-batched)",
                    "value": 0,
                    "unit": "sigs/sec",
                    "vs_baseline": 0,
                    "error": repr(e),
                }
            )
        )
