"""Flight-recorder tracing (libs/trace.py) — the ISSUE 6 acceptance
suite: span propagation across the live verify funnel, ring-buffer
eviction, dump-on-wedge, disabled-mode zero overhead, and the guard
that matters most — tracing must not perturb same-seed chaos
bit-reproducibility."""

import asyncio
import importlib.util
import json
import os
import time

import pytest

from tendermint_tpu.consensus.harness import LocalNetwork, fast_config
from tendermint_tpu.crypto import verify_hub as vh
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork
from tendermint_tpu.libs.clock import Clock, ManualClock
from tendermint_tpu.libs.trace import NOP_SPAN, FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MS = 1_000_000


def _load_tracectl():
    spec = importlib.util.spec_from_file_location(
        "tracectl", os.path.join(REPO, "scripts", "tracectl.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _StubClock(Clock):
    """Deterministic monotonic source: each read advances 1s."""

    def __init__(self):
        self.t = 0.0

    def now_ns(self) -> int:
        return 0

    def monotonic(self) -> float:
        self.t += 1.0
        return self.t


# ---------------------------------------------------------------------------
# recorder unit semantics


class TestRecorder:
    def test_ring_eviction_drop_on_full(self):
        rec = FlightRecorder(enabled=True, ring_size=8)
        for i in range(20):
            rec.emit("t", f"s{i}")
        assert len(rec) == 8
        assert rec.recorded == 20
        assert rec.dropped == 12
        names = [s["name"] for s in rec.dump()]
        assert names == [f"s{i}" for i in range(12, 20)]  # newest kept

    def test_disabled_mode_records_nothing_and_allocates_one_span(self):
        rec = FlightRecorder(enabled=False, ring_size=8)
        assert rec.start() is None
        assert rec.span("a", "b") is NOP_SPAN  # shared singleton
        with rec.span("a", "b") as sp:
            sp.set(x=1)  # no-op, no crash
        rec.emit("a", "b", duration_s=1.0)
        rec.record(None, "a", "b", 0.0, 1.0)
        rec.finish(None, "a", "b")
        assert len(rec) == 0 and rec.recorded == 0

    def test_span_context_manager_and_explicit_boundaries(self):
        rec = FlightRecorder(enabled=True, ring_size=64)
        clk = _StubClock()
        with rec.span("hub", "dispatch", clock=clk, lane="live") as sp:
            sp.set(batch=4)
        ctx = rec.start(clk)
        rec.record(ctx, "consensus", "ingest.wait", 10.0, 10.5, peer="p0")
        dump = rec.dump()
        assert dump[0]["subsystem"] == "hub"
        assert dump[0]["duration_ms"] == pytest.approx(1000.0)
        assert dump[0]["attrs"] == {"lane": "live", "batch": 4}
        assert dump[1]["trace_id"] == ctx.trace_id
        assert dump[1]["duration_ms"] == pytest.approx(500.0)
        # filters
        assert rec.dump(subsystem="hub") == dump[:1]
        assert rec.dump(trace_id=ctx.trace_id) == dump[1:]

    def test_span_records_error_attr_and_reraises(self):
        rec = FlightRecorder(enabled=True, ring_size=8)
        with pytest.raises(ValueError):
            with rec.span("t", "boom"):
                raise ValueError("x")
        (s,) = rec.dump()
        assert "ValueError" in s["attrs"]["error"]

    def test_auto_dump_writes_file(self, tmp_path):
        rec = FlightRecorder(enabled=True, ring_size=8, out_dir=str(tmp_path))
        rec.emit("t", "s1", duration_s=0.1)
        path = rec.auto_dump("breaker-trip")
        assert path is not None and os.path.exists(path)
        data = json.loads(open(path).read())
        assert data["reason"] == "breaker-trip"
        assert data["spans"][0]["name"] == "s1"
        assert rec.stats()["auto_dumps"][0]["path"] == path

    def test_auto_dump_sanitizes_reason_and_reports_failure(self, tmp_path):
        # reasons reach auto_dump from operator input
        # (/debug/flight?dump=<reason>): path characters must not escape
        # the dump dir, and a failed write must not report a path
        rec = FlightRecorder(enabled=True, ring_size=8, out_dir=str(tmp_path))
        rec.emit("t", "s1")
        path = rec.auto_dump("manual-a/b")
        assert path is not None and os.path.dirname(path) == str(tmp_path)
        assert os.path.exists(path)
        # out_dir pointing at a FILE: the write fails, the caller (and
        # /debug/flight) must see "no dump", not a phantom path
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("x")
        rec.out_dir = str(blocker)
        assert rec.auto_dump("wedge") is None
        assert "path" not in rec.stats()["auto_dumps"][-1]

    def test_manual_clock_spans_still_have_duration(self):
        # ManualClock freezes the wall-clock domain only: its monotonic
        # domain advances, so spans recorded under a frozen chaos clock
        # still measure real elapsed time
        rec = FlightRecorder(enabled=True, ring_size=8)
        clk = ManualClock(0)
        with rec.span("t", "s", clock=clk):
            time.sleep(0.01)
        (s,) = rec.dump()
        assert s["duration_ms"] >= 5.0


class TestWedgeDump:
    @pytest.mark.asyncio
    async def test_loop_wedge_triggers_flight_dump(self, tmp_path):
        """The LoopWatchdog wedge path must dump the span ring — the
        spans leading up to a stall are half the diagnosis."""
        from tendermint_tpu.libs.watchdog import LoopWatchdog

        old_dir, old_enabled = trace.RECORDER.out_dir, trace.RECORDER.enabled
        trace.RECORDER.out_dir = str(tmp_path)
        trace.RECORDER.enabled = True
        wd = LoopWatchdog(str(tmp_path), threshold_s=0.2, interval_s=0.1)
        wd.start()
        try:
            trace.emit("test", "pre-wedge")
            time.sleep(0.7)  # deliberately block the loop past threshold
            await asyncio.sleep(0.1)  # let the heartbeat recover
        finally:
            wd.stop()
            trace.RECORDER.out_dir = old_dir
            trace.RECORDER.enabled = old_enabled
        assert wd.reports, "watchdog never saw the wedge"
        flights = [f for f in os.listdir(tmp_path) if f.startswith("flight-loop-wedged")]
        assert flights, "wedge did not dump the flight recorder"
        spans = json.loads(open(os.path.join(tmp_path, flights[0])).read())["spans"]
        assert any(s["name"] == "pre-wedge" for s in spans)


class TestBackendInitWatchdog:
    """Bounded-retry watchdogged backend init (the attach path crypto/
    batch._probe_tpu runs behind) — no more one-shot 180 s cliff."""

    def setup_method(self):
        from tendermint_tpu.crypto import backend_telemetry as bt

        bt.reset()

    def test_success_first_attempt(self):
        from tendermint_tpu.crypto import backend_telemetry as bt
        from tendermint_tpu.libs.watchdog import BackendInitWatchdog

        wd = BackendInitWatchdog(attempts=3, timeout_s=5.0, backoff_s=0.0)
        assert wd.run(lambda: "backend") == "backend"
        assert wd.log == [{"latency_s": wd.log[0]["latency_s"], "outcome": "ok"}]
        assert bt.BACKEND["attach_attempts"] == 1
        assert bt.BACKEND["attach_failures"] == 0

    def test_bounded_attempts_on_error(self):
        from tendermint_tpu.crypto import backend_telemetry as bt
        from tendermint_tpu.libs.watchdog import BackendInitWatchdog

        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("tunnel down")

        wd = BackendInitWatchdog(attempts=3, timeout_s=5.0, backoff_s=0.0)
        assert wd.run(boom) is None
        assert len(calls) == 3
        assert [e["outcome"] for e in wd.log] == ["error"] * 3
        assert bt.BACKEND["attach_attempts"] == 3
        assert bt.BACKEND["attach_failures"] == 3

    def test_falsy_result_is_a_failed_attempt_not_an_attach(self):
        # backend_ready() returning False (no TPU behind the tunnel)
        # must not be telemetered as a successful attach — the exact
        # lost-TPU signal this subsystem exists to expose
        from tendermint_tpu.crypto import backend_telemetry as bt
        from tendermint_tpu.libs.watchdog import BackendInitWatchdog

        calls = []

        def unavailable():
            calls.append(1)
            return False

        wd = BackendInitWatchdog(attempts=3, timeout_s=5.0, backoff_s=0.0)
        assert wd.run(unavailable) is None
        assert len(calls) == 3
        assert [e["outcome"] for e in wd.log] == ["unavailable"] * 3
        assert bt.BACKEND["attach_attempts"] == 3
        assert bt.BACKEND["attach_failures"] == 3

    def test_hung_attempt_adopted_when_it_finishes_late(self):
        # attempt 1 outlives its per-attempt timeout; while attempt 2
        # waits, attempt 1 completes and its result is adopted — a
        # tunnel that comes up at t=70s is not thrown away by a 60s
        # timeout (the probe thread can't be killed, only outwaited)
        from tendermint_tpu.libs.watchdog import BackendInitWatchdog

        started = []

        def slow():
            started.append(time.monotonic())
            time.sleep(0.6)
            return "late"

        wd = BackendInitWatchdog(
            attempts=3, timeout_s=0.25, backoff_s=0.0, poll_s=0.05
        )
        assert wd.run(slow) == "late"
        assert wd.log[0]["outcome"] == "hung"
        assert wd.log[-1]["outcome"] == "ok"


class TestFallbackDumpGating:
    def test_flight_dump_only_on_active_kind_transition(self, tmp_path):
        """A flapping device re-probes via the half-open breaker; every
        failed probe records a fallback, but only an actual TPU->CPU
        TRANSITION dumps the flight ring (one file per transition, not
        one per failed batch)."""
        from tendermint_tpu.crypto import backend_telemetry as bt

        bt.reset()
        old_dir, old_enabled = trace.RECORDER.out_dir, trace.RECORDER.enabled
        trace.RECORDER.out_dir = str(tmp_path)
        trace.RECORDER.enabled = True
        try:
            bt.set_active("tpu")
            for _ in range(5):  # first trips the transition, rest flap
                bt.record_fallback("tpu", "cpu", "device error")
            assert bt.BACKEND["fallbacks"] == 5
            dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-")]
            assert len(dumps) == 1
            bt.set_active("tpu")  # breaker closed again
            bt.record_fallback("tpu", "cpu", "device error")
            dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-")]
            assert len(dumps) == 2
        finally:
            bt.reset()
            trace.RECORDER.out_dir = old_dir
            trace.RECORDER.enabled = old_enabled


# ---------------------------------------------------------------------------
# live propagation: gossip -> ingest -> hub -> apply


STAGES = ("ingest.wait", "ingest.verify", "ingest.reorder", "apply")


def _by_trace(spans):
    out: dict[int, dict[str, dict]] = {}
    for s in spans:
        if s["trace_id"]:
            out.setdefault(s["trace_id"], {})[f"{s['subsystem']}.{s['name']}"] = s
    return out


class TestLivePropagation:
    @pytest.mark.asyncio
    async def test_end_to_end_spans_answer_where_time_went(self, tmp_path):
        """Acceptance: a live 4-node LocalNetwork run produces
        end-to-end traces whose stage durations tile the observed
        ingest latency exactly, /debug/traces serves them, and
        tracectl renders the per-stage table from the dump."""
        old_enabled = trace.RECORDER.enabled
        trace.RECORDER.enabled = True
        trace.RECORDER.clear()
        # cache OFF: the in-process harness shares one hub across all 4
        # nodes, so a vote's signer (sync own-vote check) would otherwise
        # pre-cache every triple and peers' stage-1 submissions would all
        # short-circuit as cache hits — real per-process nodes dispatch
        # cold, which is the path this test pins
        hub = vh.acquire_hub(max_batch=64, window_ms=1.0, cache_size=0)
        net = LocalNetwork(4, config=fast_config())
        try:
            await net.start()
            await net.wait_for_height(2, timeout=60)
        finally:
            await net.stop()
            vh.release_hub()
            trace.RECORDER.enabled = old_enabled
        spans = trace.RECORDER.dump()
        assert spans, "tracing enabled but the live run recorded nothing"

        # every funnel stage appears somewhere in the run
        seen = {f"{s['subsystem']}.{s['name']}" for s in spans}
        for stage in (
            "consensus.ingest.wait", "consensus.ingest.verify",
            "consensus.ingest.reorder", "consensus.apply", "consensus.msg",
            "hub.queue", "hub.execute", "consensus.height",
        ):
            assert stage in seen, f"missing {stage} (saw {sorted(seen)})"

        # the tiling invariant: wait + verify + reorder + apply == msg
        complete = [
            t for t in _by_trace(spans).values()
            if all(f"consensus.{st}" in t for st in STAGES) and "consensus.msg" in t
        ]
        assert complete, "no trace carried the full stage set"
        for t in complete:
            total = sum(t[f"consensus.{st}"]["duration_ms"] for st in STAGES)
            assert total == pytest.approx(
                t["consensus.msg"]["duration_ms"], abs=0.01
            ), f"stages do not tile the end-to-end span: {t}"
        # hub spans join the same trace as the ingest stages
        assert any("hub.queue" in t and "hub.execute" in t for t in complete)

        # ... and the node edge serves it: /debug/traces + tracectl
        from tendermint_tpu.rpc.core import Environment
        from tendermint_tpu.rpc.server import RPCServer

        import aiohttp

        server = RPCServer(Environment(chain_id="trace-test"))
        await server.start("127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://127.0.0.1:{server.port}/debug/traces"
                ) as resp:
                    assert resp.status == 200
                    body = await resp.json()
                async with s.get(
                    f"http://127.0.0.1:{server.port}/debug/flight"
                ) as resp:
                    assert (await resp.json())["stats"]["ring_size"] > 0
                # the /metrics 404 fix: an env with NO metrics object
                # still serves an (empty) registry render with 200
                async with s.get(
                    f"http://127.0.0.1:{server.port}/metrics"
                ) as resp:
                    assert resp.status == 200
        finally:
            await server.stop()
        assert body["stats"]["recorded"] >= len(spans)
        fetched = body["spans"]
        assert {s["subsystem"] for s in fetched} >= {"consensus", "hub"}

        tracectl = _load_tracectl()
        table = tracectl.summarize(fetched)
        assert "consensus.msg" in table and "p50ms" in table and "p99ms" in table
        # single-trace rendering: a message's life, top to bottom
        tid = fetched[-1]["trace_id"] or next(
            s["trace_id"] for s in fetched if s["trace_id"]
        )
        assert f"trace {tid}" in tracectl.render_trace(fetched, tid)
        # round-trips through a dump file too (the auto-dump shape)
        dump_file = tmp_path / "dump.json"
        dump_file.write_text(json.dumps({"spans": fetched}))
        assert tracectl.load_spans(str(dump_file)) == fetched


# ---------------------------------------------------------------------------
# the determinism guard: tracing ON vs OFF, same seed, identical output


TARGET = 2


async def _chaos_run(seed: int):
    """Trimmed test_chaos_live run: 4 validators, asymmetric partition +
    clock skew on frozen ManualClocks. Returns (header times, own
    non-nil precommit timestamps)."""
    from tendermint_tpu.consensus import messages as m
    from tendermint_tpu.types.keys import SignedMsgType

    chaos = ChaosNetwork(ChaosConfig(seed=seed, clock_skew_ms=80.0))
    genesis_ns = 1_700_000_000_000_000_000
    net = LocalNetwork(
        4,
        config=fast_config(),
        chaos=chaos,
        base_clock=ManualClock(genesis_ns - 500 * MS),
    )
    chaos.partition_oneway("node0", "node1")
    precommit_ts: dict[tuple[int, int], int] = {}
    await net.start()
    try:
        for i, node in enumerate(net.nodes):
            orig = node.cs.broadcast_hook

            def hook(msg, _i=i, _orig=orig):
                if (
                    isinstance(msg, m.VoteMessage)
                    and msg.vote.type == SignedMsgType.PRECOMMIT
                    and not msg.vote.block_id.is_nil()
                ):
                    precommit_ts.setdefault(
                        (msg.vote.height, _i), msg.vote.timestamp_ns
                    )
                _orig(msg)

            node.cs.broadcast_hook = hook
        await asyncio.gather(
            *(n.cs.wait_for_height(TARGET, 60) for n in net.nodes)
        )
        header_times = {
            h: net.nodes[0].block_store.load_block(h).header.time_ns
            for h in range(1, TARGET + 1)
        }
    finally:
        await net.stop()
    return header_times, dict(precommit_ts)


class TestBitReproducibility:
    @pytest.mark.asyncio
    async def test_same_seed_identical_with_tracing_on_vs_off(self):
        """Tracing must never read wall clock in seeded paths or alter
        scheduling: a same-seed chaos run with the recorder ON produces
        the exact block/vote timestamps of a run with it OFF."""
        old = trace.RECORDER.enabled
        try:
            trace.RECORDER.enabled = True
            t_on, v_on = await _chaos_run(seed=424)
            trace.RECORDER.enabled = False
            t_off, v_off = await _chaos_run(seed=424)
        finally:
            trace.RECORDER.enabled = old
        genesis_ns = 1_700_000_000_000_000_000
        # the deterministic closed form still holds with tracing on
        assert t_on == {h: genesis_ns + (h - 1) * MS for h in t_on}
        assert t_on == t_off, "block timestamps diverged with tracing on"
        common = v_on.keys() & v_off.keys()
        assert common
        assert {k: v_on[k] for k in common} == {k: v_off[k] for k in common}
