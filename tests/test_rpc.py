"""RPC layer tests: JSON-RPC over HTTP, URI routes, websocket
subscriptions, the RPC client, and the HTTP light-client provider
(modeled on reference rpc/jsonrpc tests + rpc/client tests)."""

import asyncio

import pytest

from tendermint_tpu.crypto.hashes import sha256
from tendermint_tpu.p2p.types import NodeAddress
from tendermint_tpu.rpc.client import HTTPClient, HTTPProvider, RPCClientError
from tests.test_node import NodeNet

LONG_NS = 10 * 365 * 24 * 3600 * 10**9


async def rpc_net(n=2, pprof=False):
    net = NodeNet(n)
    for node in net.nodes:
        node.config.rpc_laddr = "127.0.0.1:0"
        node.config.rpc_pprof = pprof
    await net.start()
    await net.wait_for_height(2, timeout=60)
    clients = [
        HTTPClient(f"http://127.0.0.1:{node.rpc_server.port}") for node in net.nodes
    ]
    return net, clients


class TestRPC:
    @pytest.mark.asyncio
    async def test_status_block_commit_validators(self):
        net, clients = await rpc_net()
        c = clients[0]
        try:
            st = await c.status()
            assert int(st["sync_info"]["latest_block_height"]) >= 2
            blk = await c.block(1)
            assert blk["block"]["header"]["height"] == "1"
            com = await c.commit(1)
            assert com["signed_header"]["commit"]["height"] == "1"
            vals = await c.validators(1)
            assert int(vals["total"]) == 2
            # URI-style GET works too
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(c.base_url + "/health") as resp:
                    body = await resp.json()
                    assert body["result"] == {}
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()

    @pytest.mark.asyncio
    async def test_pprof_endpoints(self):
        """Live profiling routes (reference pprof-laddr analog): CPU
        profile over a window, heap snapshot arm+report+disarm, stack
        dump; off by default; NaN windows rejected."""
        net, clients = await rpc_net(pprof=True)
        c = clients[0]
        try:
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.get(
                    c.base_url + "/debug/pprof/profile?seconds=0.3"
                ) as resp:
                    body = await resp.text()
                    assert resp.status == 200 and "cumulative" in body
                # non-finite windows must be rejected, not park the
                # profiler forever
                async with s.get(
                    c.base_url + "/debug/pprof/profile?seconds=nan"
                ) as resp:
                    assert resp.status == 400
                async with s.get(c.base_url + "/debug/pprof/heap") as resp:
                    assert "tracemalloc armed" in await resp.text()
                async with s.get(c.base_url + "/debug/pprof/heap") as resp:
                    assert "heap snapshot" in await resp.text()
                async with s.get(
                    c.base_url + "/debug/pprof/heap?op=stop"
                ) as resp:
                    assert "disarmed" in await resp.text()
                async with s.get(c.base_url + "/debug/pprof/stacks") as resp:
                    assert "Thread" in await resp.text()

            # and OFF by default: a default-constructed server has no
            # pprof routes (the reference only serves pprof when
            # pprof-laddr is explicitly configured)
            from tendermint_tpu.rpc.server import RPCServer

            default_server = RPCServer(net.nodes[0].rpc_server.env)
            routes = {r.resource.canonical for r in default_server.app.router.routes()}
            assert "/debug/pprof/profile" not in routes
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()

    @pytest.mark.asyncio
    async def test_broadcast_tx_commit_and_query(self):
        net, clients = await rpc_net()
        c = clients[0]
        try:
            res = await c.broadcast_tx_commit(b"neptune=blue")
            assert res["check_tx"]["code"] == 0
            assert res["deliver_tx"]["code"] == 0
            height = int(res["height"])
            assert height > 0
            # app query via RPC
            q = await c.abci_query("", b"neptune")
            assert bytes.fromhex(q["response"]["value"]) == b"blue"
            # indexed tx lookup + search
            tx = await c.tx(sha256(b"neptune=blue"))
            assert bytes.fromhex(tx["tx"]) == b"neptune=blue"
            found = await c.tx_search(f"tx.height={height}")
            assert int(found["total_count"]) >= 1
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()

    @pytest.mark.asyncio
    async def test_error_handling(self):
        net, clients = await rpc_net()
        c = clients[0]
        try:
            with pytest.raises(RPCClientError):
                await c.block(10**9)
            with pytest.raises(RPCClientError):
                await c.call("no_such_method")
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()

    @pytest.mark.asyncio
    async def test_websocket_subscription(self):
        net, clients = await rpc_net()
        c = clients[0]
        try:
            events = c.websocket_events("tm.event='NewBlock'")
            got = await asyncio.wait_for(events.__anext__(), 20)
            assert got["data"]["type"] == "EventDataNewBlock"
            assert got["data"]["block_height"] >= 1
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()


class TestHTTPProvider:
    @pytest.mark.asyncio
    async def test_light_client_over_rpc(self):
        from tendermint_tpu.light.client import LightClient, TrustOptions

        net, clients = await rpc_net()
        try:
            await net.wait_for_height(3, timeout=60)
            provider = HTTPProvider(net.genesis.chain_id, clients[0])
            lb1 = await provider.light_block(1)
            assert lb1.height == 1
            lb1.validate_basic(net.genesis.chain_id)
            client = LightClient(
                net.genesis.chain_id,
                TrustOptions(LONG_NS, 1, lb1.header.hash()),
                provider,
            )
            lb3 = await client.verify_light_block_at_height(3)
            assert lb3.height == 3
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()
