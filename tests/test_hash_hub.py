"""HashHub (ISSUE 20): batched SHA-256/Merkle hot loop.

Bit-identity is the contract everything here pins: the level-order
batched tree builders must agree with the recursive reference builders
for EVERY shape (the odd-last-node promotion equivalence), the device
kernel must agree with hashlib for every message length it accepts, and
every degrade path — breaker open, device error, kill switch — must
return identical bytes, differing only in latency and accounting.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

import pytest

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto import hash_hub, merkle
from tendermint_tpu.libs import trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _items(n: int, size: int = 37) -> list[bytes]:
    # distinct, deterministic leaves; varying first bytes so a pairing
    # bug can't accidentally cancel out
    return [bytes([i & 0xFF, (i >> 8) & 0xFF]) + b"\xab" * size for i in range(n)]


#: every tree shape class the promotion equivalence has to cover: the
#: full small range (all pairing/promotion interleavings up to depth 7)
#: plus 2^k and 2^k +/- 1 at larger depths
TREE_SIZES = list(range(0, 70)) + [
    127, 128, 129, 255, 256, 257, 511, 512, 513, 1023, 1024, 1025,
]


# ---------------------------------------------------------------------------
# merkle bit-identity: batched level-order vs recursive reference


def test_root_bit_identity_every_shape():
    for n in TREE_SIZES:
        items = _items(n)
        assert merkle.hash_from_byte_slices(items) == \
            merkle.hash_from_byte_slices_scalar(items), f"root mismatch at n={n}"


def test_proofs_bit_identity_every_shape():
    for n in TREE_SIZES:
        items = _items(n)
        root_b, proofs_b = merkle.proofs_from_byte_slices(items)
        root_s, proofs_s = merkle.proofs_from_byte_slices_scalar(items)
        assert root_b == root_s, f"proof root mismatch at n={n}"
        assert len(proofs_b) == len(proofs_s) == n
        for i, (pb, ps) in enumerate(zip(proofs_b, proofs_s)):
            assert (pb.total, pb.index) == (ps.total, ps.index), (n, i)
            assert pb.leaf_hash == ps.leaf_hash, (n, i)
            assert pb.aunts == ps.aunts, f"aunts mismatch n={n} i={i}"


def test_batched_proofs_verify_against_batched_root():
    for n in (1, 2, 7, 14, 33, 129):
        items = _items(n)
        root, proofs = merkle.proofs_from_byte_slices(items)
        for i, p in enumerate(proofs):
            assert p.verify(root, items[i])
            # caller-supplied leaf hash skips re-derivation, same verdict
            assert p.verify(root, items[i], leaf_hash=p.leaf_hash)
            assert not p.verify(root, items[i] + b"x")
            assert not p.verify(root, items[i], leaf_hash=b"\x00" * 32)


def test_empty_tree_is_sha256_of_empty():
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    root, proofs = merkle.proofs_from_byte_slices([])
    assert root == hashlib.sha256(b"").digest() and proofs == []


# ---------------------------------------------------------------------------
# sha256_many / sha256_one vs hashlib


def test_sha256_many_matches_hashlib():
    msgs = [bytes([i & 0xFF]) * (i % 97) for i in range(300)]
    assert hash_hub.sha256_many(msgs) == [
        hashlib.sha256(m).digest() for m in msgs
    ]
    assert hash_hub.sha256_many([]) == []


def test_sha256_one_matches_hashlib():
    assert hash_hub.sha256_one(b"abc") == hashlib.sha256(b"abc").digest()


# ---------------------------------------------------------------------------
# lanes + stats accounting


def test_lane_accounting_explicit_and_ambient():
    hash_hub.reset_stats()
    assert hash_hub.current_lane() == hash_hub.LANE_BUILD
    hash_hub.sha256_many([b"a", b"b"], lane=hash_hub.LANE_VERIFY)
    with hash_hub.lane_ctx(hash_hub.LANE_LIGHT):
        assert hash_hub.current_lane() == hash_hub.LANE_LIGHT
        hash_hub.sha256_many([b"c"])
        with hash_hub.lane_ctx(hash_hub.LANE_VERIFY):  # re-entrant
            assert hash_hub.current_lane() == hash_hub.LANE_VERIFY
        assert hash_hub.current_lane() == hash_hub.LANE_LIGHT
    assert hash_hub.current_lane() == hash_hub.LANE_BUILD
    hash_hub.sha256_one(b"d")
    s = hash_hub.stats_snapshot()
    assert s["batches"] == 2 and s["messages"] == 3 and s["singles"] == 1
    assert s["lane_batches"] == {"build": 0, "verify": 1, "light": 1}
    assert s["lane_messages"] == {"build": 1, "verify": 2, "light": 1}
    assert s["max_batch"] == 2
    hash_hub.reset_stats()


def test_lane_ctx_rejects_unknown_lane():
    with pytest.raises(ValueError):
        hash_hub.lane_ctx("turbo")


def test_merkle_tags_the_requested_lane():
    hash_hub.reset_stats()
    merkle.hash_from_byte_slices(_items(5), lane=hash_hub.LANE_LIGHT)
    s = hash_hub.stats_snapshot()
    assert s["lane_messages"]["light"] == s["messages"] > 0
    hash_hub.reset_stats()


# ---------------------------------------------------------------------------
# kill switch: runtime flag + fresh-interpreter env


def test_use_hashhub_runtime_flip():
    items = _items(19)
    was = merkle.hashhub_active()
    try:
        merkle.use_hashhub(False)
        assert not merkle.hashhub_active()
        root_off = merkle.hash_from_byte_slices(items)
        _, proofs_off = merkle.proofs_from_byte_slices(items)
        merkle.use_hashhub(True)
        assert merkle.hashhub_active()
        assert merkle.hash_from_byte_slices(items) == root_off
        assert merkle.proofs_from_byte_slices(items)[1] == proofs_off
    finally:
        merkle.use_hashhub(was)


def test_env_kill_switch_fresh_interpreter():
    code = (
        "from tendermint_tpu.crypto import merkle; "
        "items = [bytes([i]) * 9 for i in range(21)]; "
        "print(merkle.hashhub_active(), "
        "merkle.hash_from_byte_slices(items) == "
        "merkle.hash_from_byte_slices_scalar(items))"
    )
    for env_val, expect in (("0", "False True"), ("1", "True True")):
        out = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO,
            env={**os.environ, "TMTPU_HASHHUB": env_val, "JAX_PLATFORMS": "cpu"},
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == expect


# ---------------------------------------------------------------------------
# device route (JAX-CPU backend stands in for the TPU)


@pytest.fixture
def device_on(monkeypatch):
    """Opt the kernel route in and make every batch device-eligible."""
    monkeypatch.setenv("TMTPU_HASH_TPU", "1")
    monkeypatch.setattr(hash_hub, "MIN_DEVICE_BATCH", 4)
    hash_hub._reset_device_probe()
    breaker = crypto_batch.tpu_breaker()
    breaker.record_success()  # start closed regardless of prior tests
    yield
    breaker.record_success()
    hash_hub._reset_device_probe()


def test_device_route_bit_identity(device_on):
    from tendermint_tpu.crypto.tpu import sha256 as dev

    # every padding boundary the packer has to get right: around the
    # 55/56 one-block limit, the 64-byte block edge, multi-block sizes,
    # and the 503-byte _MAX_BLOCKS ceiling
    lengths = [0, 1, 54, 55, 56, 63, 64, 118, 119, 120, 127, 128, 200,
               255, 256, 400, 503]
    msgs = [bytes([ln & 0xFF]) * ln for ln in lengths]
    assert dev.sha256_device(msgs) == [hashlib.sha256(m).digest() for m in msgs]


def test_device_route_through_hub(device_on):
    hash_hub.reset_stats()
    msgs = [bytes([i % 251]) * (i % 120) for i in range(64)]
    out = hash_hub.sha256_many(msgs)
    assert out == [hashlib.sha256(m).digest() for m in msgs]
    s = hash_hub.stats_snapshot()
    assert s["device_batches"] == 1 and s["device_messages"] == 64
    hash_hub.reset_stats()


def test_long_messages_stay_on_host(device_on):
    from tendermint_tpu.crypto.tpu import sha256 as dev

    hash_hub.reset_stats()
    big = b"\xcd" * (dev.max_device_bytes() + 1)
    msgs = [big] * 8
    assert hash_hub.sha256_many(msgs) == [
        hashlib.sha256(m).digest() for m in msgs
    ]
    s = hash_hub.stats_snapshot()
    assert s["device_batches"] == 0  # routed around the kernel, no error
    assert s["fallback_batches"] == 0
    with pytest.raises(ValueError):
        dev.sha256_device(msgs)  # the kernel itself refuses over-limit
    hash_hub.reset_stats()


def test_breaker_open_skips_device_identical_bytes(device_on):
    hash_hub.reset_stats()
    breaker = crypto_batch.tpu_breaker()
    for _ in range(breaker.failure_threshold):
        breaker.record_failure()
    assert breaker.state == "open"
    msgs = [bytes([i]) * 30 for i in range(32)]
    assert hash_hub.sha256_many(msgs) == [
        hashlib.sha256(m).digest() for m in msgs
    ]
    s = hash_hub.stats_snapshot()
    assert s["breaker_skips"] == 1 and s["device_batches"] == 0
    breaker.record_success()
    hash_hub.reset_stats()


def test_device_error_degrades_to_host(device_on, monkeypatch):
    from tendermint_tpu.crypto.tpu import sha256 as dev

    hash_hub.reset_stats()

    def boom(msgs):
        raise RuntimeError("device wedged")

    monkeypatch.setattr(dev, "sha256_device", boom)
    msgs = [bytes([i]) * 30 for i in range(32)]
    # latency, never correctness: the failed batch re-hashes inline
    assert hash_hub.sha256_many(msgs) == [
        hashlib.sha256(m).digest() for m in msgs
    ]
    s = hash_hub.stats_snapshot()
    assert s["fallback_batches"] == 1 and s["device_batches"] == 0
    crypto_batch.tpu_breaker().record_success()
    hash_hub.reset_stats()


def test_device_off_by_default():
    # without TMTPU_HASH_TPU=1 the probe caches False and wide batches
    # stay on the host loop
    assert os.environ.get("TMTPU_HASH_TPU") != "1"
    hash_hub._reset_device_probe()
    assert hash_hub._device_module() is False


# ---------------------------------------------------------------------------
# trace spans: wide batches only


def test_wide_batch_emits_hash_span(monkeypatch):
    monkeypatch.setattr(hash_hub, "MIN_DEVICE_BATCH", 8)
    before = len(trace.RECORDER.dump(subsystem="hash"))
    hash_hub.sha256_many([b"x"] * 8, lane=hash_hub.LANE_VERIFY)
    spans = trace.RECORDER.dump(subsystem="hash")
    assert len(spans) == before + 1
    last = spans[-1]
    assert last["name"] == "batch"
    assert last["attrs"]["n"] == 8
    assert last["attrs"]["lane"] == "verify"
    assert last["attrs"]["route"] in ("cpu", "tpu")
    hash_hub.reset_stats()


def test_narrow_batch_emits_no_span():
    # a span per microseconds-scale merkle level would flood the ring
    before = len(trace.RECORDER.dump(subsystem="hash"))
    hash_hub.sha256_many([b"x"] * 4)
    assert len(trace.RECORDER.dump(subsystem="hash")) == before
    hash_hub.reset_stats()


# ---------------------------------------------------------------------------
# /metrics folding


def test_metrics_fold_hashhub():
    from tendermint_tpu.libs.metrics import NodeMetrics

    hash_hub.reset_stats()
    hash_hub.sha256_many([b"a", b"b", b"c"], lane=hash_hub.LANE_VERIFY)
    hash_hub.sha256_one(b"d")
    rendered = NodeMetrics().render()
    assert "tendermint_tpu_hashhub_batches 1" in rendered
    assert "tendermint_tpu_hashhub_messages 3" in rendered
    assert "tendermint_tpu_hashhub_singles 1" in rendered
    assert "tendermint_tpu_hashhub_batch_occupancy 3" in rendered
    assert 'tendermint_tpu_hashhub_lane_batches{lane="verify"} 1' in rendered
    assert "tendermint_tpu_hashhub_breaker_skips 0" in rendered
    hash_hub.reset_stats()


# ---------------------------------------------------------------------------
# redundant-rehash fixes: part-set leaf cache, header/txs memoization


def test_part_leaf_hash_cached_and_correct():
    from tendermint_tpu.types.part_set import PartSet

    ps = PartSet.from_data(b"\x01\x02" * 40000, part_size=65536)
    part = ps.get_part(0)
    expect = hashlib.sha256(merkle.LEAF_PREFIX + part.bytes_).digest()
    first = part.leaf_hash()
    assert first == expect == part.proof.leaf_hash
    assert part.leaf_hash() is first  # cached, not re-derived


def test_from_data_parts_pass_receive_side_verification():
    from tendermint_tpu.types.part_set import Part, PartSet

    data = bytes(range(256)) * 1024  # 4 parts at 64 KiB
    ps = PartSet.from_data(data, part_size=65536)
    assert ps.is_complete() and ps.assemble() == data
    # a receiver reassembling from gossip runs the verifying add_part
    # path over the same parts (fresh Part objects: no cached hash)
    ps2 = PartSet(ps.header)
    for i in range(ps.header.total):
        p = ps.get_part(i)
        assert ps2.add_part(Part(p.index, p.bytes_, p.proof))
    assert ps2.assemble() == data
    # and a corrupted payload still fails against the cached-hash path
    bad = Part(0, b"evil" + ps.get_part(0).bytes_[4:], ps.get_part(0).proof)
    with pytest.raises(ValueError):
        PartSet(ps.header).add_part(bad)


def test_partset_root_matches_scalar_builder():
    from tendermint_tpu.types.part_set import PartSet

    data = b"\x07" * 200000
    was = merkle.hashhub_active()
    try:
        merkle.use_hashhub(True)
        root_b = PartSet.from_data(data, part_size=65536).header.hash
        merkle.use_hashhub(False)
        root_s = PartSet.from_data(data, part_size=65536).header.hash
    finally:
        merkle.use_hashhub(was)
    assert root_b == root_s


def test_header_hash_memoized():
    import dataclasses

    from tendermint_tpu.types.block import Header, txs_hash

    hdr = Header(
        chain_id="memo-chain",
        height=7,
        time_ns=1,
        data_hash=txs_hash((b"tx1", b"tx2")),
        validators_hash=b"\x11" * 32,
        next_validators_hash=b"\x11" * 32,
        proposer_address=b"\x22" * 20,
    )
    h1 = hdr.hash()
    assert hdr.hash() is h1  # second call returns the cached object
    # replace() builds a fresh instance — no stale memo rides along
    hdr2 = dataclasses.replace(hdr, height=8)
    assert hdr2.hash() != h1
    assert dataclasses.replace(hdr).hash() == h1


def test_block_txs_hash_memoized():
    from tendermint_tpu.types.block import (
        Block, BlockID, Commit, Header, txs_hash,
    )

    txs = (b"a", b"bb", b"ccc")
    blk = Block(
        header=Header(
            chain_id="memo-chain",
            height=1,
            time_ns=1,
            data_hash=txs_hash(txs),
            validators_hash=b"\x11" * 32,
            next_validators_hash=b"\x11" * 32,
            proposer_address=b"\x22" * 20,
        ),
        txs=txs,
        last_commit=Commit(height=0, round=0, block_id=BlockID(), signatures=()),
    )
    t1 = blk.txs_hash()
    assert t1 == txs_hash(txs)
    assert blk.txs_hash() is t1
    blk.validate_basic()  # consumes the memo, still validates
