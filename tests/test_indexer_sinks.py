"""Event sink differential tests: the kv sink (reference indexer/sink/kv)
and the relational sink (reference indexer/sink/psql, DB-API port) must
answer identically for the same indexed history."""

import pytest

from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.state.indexer import KVSink, TxResult
from tendermint_tpu.state.sql_sink import SQLEventSink
from tendermint_tpu.store.db import MemDB


def _sinks():
    return [KVSink(MemDB()), SQLEventSink.sqlite(":memory:", chain_id="t")]


def _populate(sink):
    sink.index_tx(
        TxResult(1, 0, b"alpha=1", 0, b"", "", {"kv.key": ["alpha"]})
    )
    sink.index_tx(
        TxResult(2, 0, b"beta=2", 0, b"", "", {"kv.key": ["beta"]})
    )
    sink.index_tx(
        TxResult(2, 1, b"alpha=3", 0, b"", "", {"kv.key": ["alpha"]})
    )
    sink.index_block(1, {"block.proposer": ["aa"]})
    sink.index_block(2, {"block.proposer": ["bb"]})


@pytest.mark.parametrize("sink", _sinks(), ids=["kv", "sql"])
def test_get_tx_roundtrip(sink):
    _populate(sink)
    res = TxResult(1, 0, b"alpha=1", 0, b"", "", {"kv.key": ["alpha"]})
    got = sink.get_tx(res.hash)
    assert got is not None and got.tx == b"alpha=1" and got.height == 1


@pytest.mark.parametrize("sink", _sinks(), ids=["kv", "sql"])
def test_search_by_event_attribute(sink):
    _populate(sink)
    out = sink.search_txs(Query.parse("kv.key = 'alpha'"))
    assert [(r.height, r.index) for r in out] == [(1, 0), (2, 1)]


@pytest.mark.parametrize("sink", _sinks(), ids=["kv", "sql"])
def test_search_by_height(sink):
    _populate(sink)
    out = sink.search_txs(Query.parse("tx.height = 2"))
    assert [(r.height, r.index) for r in out] == [(2, 0), (2, 1)]


@pytest.mark.parametrize("sink", _sinks(), ids=["kv", "sql"])
def test_search_blocks(sink):
    _populate(sink)
    assert sink.search_blocks(Query.parse("block.proposer = 'bb'")) == [2]


def test_postgres_constructor_gated():
    with pytest.raises(RuntimeError, match="psycopg2"):
        SQLEventSink.postgres("dbname=x")
