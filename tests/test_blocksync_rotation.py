"""Blocksync range verification across a mid-window validator rotation
(the correctness backstop of the range-batching design —
blocksync/reactor.py stale-set guard + sequential fallback; the reference
verifies one block at a time so this failure mode cannot exist there).

A chain is built whose validator set CHANGES at a rotation height via a
kvstore `val:` tx; a fresh node block-syncs it through the real reactor
with a window spanning the rotation, so the batched verify (pinned to the
pre-rotation set) fails mid-range and the reactor must recover via its
per-block re-verify / sequential fallback — applying every block without
punishing any peer."""

import asyncio

import pytest

from tendermint_tpu import testing as tt
from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.blocksync import BLOCKSYNC_CHANNEL
from tendermint_tpu.blocksync import messages as bsm
from tendermint_tpu.blocksync.reactor import BlockSyncReactor
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.mempool.pool import PriorityMempool
from tendermint_tpu.p2p.peermanager import PeerStatus, PeerUpdate
from tendermint_tpu.p2p.router import Channel
from tendermint_tpu.p2p.types import Envelope
from tendermint_tpu.proxy import AppConns
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.blockstore import BlockStore
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.testing import det_priv_keys
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN = "rotation-chain"
N_BLOCKS = 24
ROTATE_AT = 10  # join height of the new validator (inside the window)


def _genesis(keys):
    return GenesisDoc(
        chain_id=CHAIN,
        initial_height=1,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(k.pub_key(), 10, f"v{i}") for i, k in enumerate(keys)
        ],
    )


async def _build_rotating_chain(genesis, all_keys, new_key):
    """Chain where `new_key` joins the validator set via a val: tx
    committed at ROTATE_AT (effective two heights later)."""
    by_addr = {k.pub_key().address(): k for k in all_keys}
    app = KVStoreApp()
    conns = AppConns.local(app)
    await conns.start()
    bstore, sstore = BlockStore(MemDB()), StateStore(MemDB())
    state = await Handshaker(
        sstore, state_from_genesis(genesis), bstore, genesis
    ).handshake(conns)
    sstore.save(state)
    mempool = PriorityMempool(MempoolConfig(), conns.mempool, height=0)
    ex = BlockExecutor(sstore, conns.consensus, mempool=mempool, block_store=bstore)
    commit = None
    rotated = False
    for h in range(1, N_BLOCKS + 1):
        if h == ROTATE_AT:
            await mempool.check_tx(
                b"val:" + new_key.pub_key().bytes().hex().encode() + b"!10"
            )
        block, parts = ex.create_proposal_block(
            h, state, commit, state.validators.get_proposer().address
        )
        bid = block.block_id(parts.header)
        state, _ = await ex.apply_block(state, bid, block)
        if len(state.validators) > len(genesis.validators):
            rotated = True
        commit = tt.make_commit(
            CHAIN, h, 0, bid, state.last_validators, by_addr,
            timestamp_ns=block.header.time_ns + 1,
        )
        bstore.save_block(block, parts, commit)
    assert rotated, "validator set never rotated — test is vacuous"
    await conns.stop()
    return bstore


@pytest.mark.asyncio
async def test_range_sync_through_validator_rotation():
    keys = det_priv_keys(3)
    new_key = det_priv_keys(1, seed=b"joiner")[0]
    genesis = _genesis(keys)
    src_store = await _build_rotating_chain(genesis, keys + [new_key], new_key)

    # target: fresh node, real reactor, window spanning the rotation
    app = KVStoreApp()
    conns = AppConns.local(app)
    await conns.start()
    bstore, sstore = BlockStore(MemDB()), StateStore(MemDB())
    state = await Handshaker(
        sstore, state_from_genesis(genesis), bstore, genesis
    ).handshake(conns)
    sstore.save(state)
    ex = BlockExecutor(sstore, conns.consensus, block_store=bstore)
    ch = Channel(BLOCKSYNC_CHANNEL, "bs", 5, bsm.encode_message, bsm.decode_message)
    peer_q: asyncio.Queue = asyncio.Queue()
    reactor = BlockSyncReactor(
        state, ex, bstore, ch, peer_q, window=N_BLOCKS, active=True
    )
    punished = []

    async def serve():
        while True:
            env = await ch.out_q.get()
            msg = env.message
            if isinstance(msg, bsm.StatusRequest):
                await ch.in_q.put(
                    Envelope(
                        BLOCKSYNC_CHANNEL,
                        bsm.StatusResponse(src_store.height(), src_store.base()),
                        from_="peer0",
                    )
                )
            elif isinstance(msg, bsm.BlockRequest):
                blk = src_store.load_block(msg.height)
                if blk is not None:
                    await ch.in_q.put(
                        Envelope(BLOCKSYNC_CHANNEL, bsm.BlockResponse(blk), from_="peer0")
                    )

    async def watch_errors():
        while True:
            punished.append(await ch.err_q.get())

    server = asyncio.get_running_loop().create_task(serve())
    watcher = asyncio.get_running_loop().create_task(watch_errors())
    await peer_q.put(PeerUpdate("peer0", PeerStatus.UP))
    await reactor.start()
    try:
        await asyncio.wait_for(reactor.synced.wait(), timeout=120)
    finally:
        server.cancel()
        watcher.cancel()
        await reactor.stop()
        await conns.stop()

    # the whole chain applied, through the rotation
    assert bstore.height() >= N_BLOCKS - 1
    # the new validator is in the synced node's set
    final_vals = sstore.load_validators(bstore.height())
    assert final_vals is not None and len(final_vals) == 4
    # an honest rotation must punish nobody
    assert punished == [], [str(p) for p in punished]
    assert reactor.metrics["blocks_applied"] >= N_BLOCKS - 1
