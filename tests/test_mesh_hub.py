"""Mesh-aware VerifyHub scheduling + backend mesh telemetry + tooling.

The kernel-level sharding equivalence lives in test_sharded_verify.py;
this file covers the scheduler half of the tentpole: the hub scaling its
micro-batch window/capacity by the active device count (and shrinking
again on degrade), surviving an 8→7→CPU breaker cascade without
wedging, the compile-cache hit/miss classification, the new backend_*
metric families, and tracectl's --per-device table.
"""

from __future__ import annotations

import pytest

from tendermint_tpu.crypto import backend_telemetry as bt
from tendermint_tpu.crypto.verify_hub import VerifyHub


@pytest.fixture
def fresh_bt():
    bt.reset()
    yield
    bt.reset()


@pytest.fixture
def fresh_mesh():
    from tendermint_tpu.crypto.tpu import mesh

    mesh.reset()
    yield mesh
    mesh.reset()


# ---------------------------------------------------------------------------
# hub mesh-occupancy-aware window


def test_hub_scales_capacity_by_mesh(monkeypatch):
    """max_batch is per-chip: the pack capacity and the adaptive-window
    ramp both scale with the active device count, and shrink back the
    moment the mesh degrades."""
    from tendermint_tpu.crypto import batch as B

    hub = VerifyHub(max_batch=16, window_ms=4.0, cache_size=0)
    monkeypatch.setattr(B, "mesh_parallelism", lambda: 8)
    assert hub._refresh_mesh() == 8
    assert hub._effective_max() == 128
    ceiling = hub.window_s  # unchanged by the mesh
    # the ramp needs 8x the occupancy to reach the full window now:
    # occupancy that saturates a single chip is 1/8 of the mesh ramp
    hub._ewma_occupancy = 9.0  # full-window occupancy for one chip
    w_mesh = hub._window()
    monkeypatch.setattr(B, "mesh_parallelism", lambda: 1)
    hub._refresh_mesh()
    assert hub._effective_max() == 16
    w_single = hub._window()
    assert w_single == ceiling  # saturated ramp on one chip
    assert w_mesh == pytest.approx(ceiling * (9.0 - 1.0) / (128 / 8.0))
    assert w_mesh < w_single

    # degraded mesh (breaker trip 8 -> 5) shrinks the same refresh
    monkeypatch.setattr(B, "mesh_parallelism", lambda: 5)
    assert hub._refresh_mesh() == 5
    assert hub._effective_max() == 80


def test_hub_mesh_scale_knob(monkeypatch):
    """mesh_scale=False (config or TMTPU_MESH_SCALE=0) pins single-chip
    sizing regardless of the mesh."""
    from tendermint_tpu.crypto import batch as B

    monkeypatch.setattr(B, "mesh_parallelism", lambda: 8)
    hub = VerifyHub(max_batch=16, mesh_scale=False)
    assert hub._refresh_mesh() == 1 and hub._effective_max() == 16

    monkeypatch.setenv("TMTPU_MESH_SCALE", "0")
    hub = VerifyHub(max_batch=16, mesh_scale=True)
    assert not hub.mesh_scale

    monkeypatch.delenv("TMTPU_MESH_SCALE")
    hub = VerifyHub(max_batch=16)
    assert hub.mesh_scale  # config default


def test_hub_stats_carry_mesh_fields(monkeypatch):
    from tendermint_tpu.crypto import batch as B

    monkeypatch.setattr(B, "mesh_parallelism", lambda: 4)
    hub = VerifyHub(max_batch=32)
    hub._refresh_mesh()
    s = hub.stats()
    assert s["mesh_devices"] == 4.0
    assert s["effective_max_batch"] == 128.0


def test_hub_survives_degrade_cascade_8_7_cpu(fresh_mesh, monkeypatch):
    """Acceptance: a per-device breaker trip mid-dispatch (8→7), then a
    whole-mesh death (→CPU), and the hub keeps resolving futures with
    correct verdicts — degradation costs throughput, never wedges."""
    import secrets

    import jax
    import numpy as np

    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.tpu import verify as V
    from tendermint_tpu.libs.retry import CircuitBreaker

    ids = [d.id for d in jax.devices()]
    calls = {"stub7": 0}

    def boom(*args, **kw):
        raise RuntimeError("chip died")

    def stub7(ua, r, ga, rd, zs, sv, gidx):
        calls["stub7"] += 1
        return np.asarray(sv), np.array(True)

    monkeypatch.setenv("TMTPU_FORCE_SHARDED", "1")
    monkeypatch.setitem(V._sharded_kernels, tuple(ids), (boom, boom))
    monkeypatch.setitem(V._sharded_kernels, tuple(ids[:7]), (stub7, boom))
    monkeypatch.setattr(B, "_tpu_available", True)
    monkeypatch.setattr(B, "MIN_TPU_BATCH", 2)
    monkeypatch.setattr(
        B, "_tpu_breaker",
        CircuitBreaker(failure_threshold=1, reset_timeout=60, name="t"),
    )
    fresh_mesh.force_fail(ids[7])

    def signed(n, tag):
        out = []
        for i in range(n):
            priv = ed25519.Ed25519PrivKey(secrets.token_bytes(32))
            msg = tag + b"-%d" % i
            out.append((priv.pub_key(), msg, priv.sign(msg)))
        return out

    hub = VerifyHub(max_batch=64, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        # stage 1: chip 7 dies mid-dispatch -> re-verified on 7 devices
        assert all(hub.verify_many(signed(8, b"stage1"), timeout=30.0))
        assert calls["stub7"] >= 1
        assert fresh_mesh.active_count() == 7
        assert hub.stats()["verify_errors"] == 0  # degrade, not error

        # stage 2: the rest of the mesh dies too -> CPU fallback
        for i in ids[:7]:
            fresh_mesh.force_fail(i)
        monkeypatch.setitem(V._sharded_kernels, tuple(ids[:7]), (boom, boom))
        monkeypatch.setattr(V, "_get_kernel_eq", boom)
        monkeypatch.setattr(V, "_get_kernel", boom)
        assert all(hub.verify_many(signed(8, b"stage2"), timeout=30.0))
        assert fresh_mesh.active_count() == 0
        assert hub.is_running
        # and the hub still answers after the cascade
        pk, msg, sig = signed(1, b"after")[0]
        assert hub.verify_sync(pk, msg, sig, timeout=30.0)
    finally:
        hub.stop()


# ---------------------------------------------------------------------------
# telemetry + metrics


def test_compile_cache_classification(fresh_bt):
    """compile_ms ≈ 0 -> persistent-cache hit; a real compile -> miss.
    Both countable and carried per-shape in the snapshot."""
    bt.record_compile("floor", 0.02)
    bt.record_compile("max", 12.5)
    bt.record_compile("probe", 0.4, cache_hit=False)  # explicit override
    snap = bt.snapshot()
    assert snap["compile_cache"] == {
        "floor": "hit", "max": "miss", "probe": "miss",
    }
    assert snap["compile_cache_hits"] == 1.0
    assert snap["compile_cache_misses"] == 2.0


def test_mesh_telemetry_and_metrics_render(fresh_bt):
    from tendermint_tpu.libs.metrics import NodeMetrics

    bt.record_mesh(8, 8)
    bt.record_degrade(8, 7, "probe failed on [7]")
    bt.record_shard_dispatch([0, 1, 2], [64, 64, 22])
    bt.record_compile("floor", 0.01)
    snap = bt.snapshot()
    assert snap["mesh"]["devices_total"] == 8.0
    assert snap["mesh"]["devices_active"] == 7.0
    assert snap["mesh"]["degrade_transitions"] == 1.0
    assert snap["shard_sigs"] == {"0": 64.0, "1": 64.0, "2": 22.0}

    out = NodeMetrics().render()
    assert 'backend_mesh_devices{state="total"} 8' in out
    assert 'backend_mesh_devices{state="active"} 7' in out
    assert "backend_mesh_degrades 1" in out
    assert 'backend_shard_sigs{device="2"} 22' in out
    assert "backend_compile_cache_hits 1" in out
    assert "backend_compile_cache_misses 0" in out


def test_mesh_max_devices_cap(fresh_mesh, fresh_bt, monkeypatch):
    """TMTPU_MESH_MAX_DEVICES caps the dispatch mesh; telemetry keeps
    one definition — total = visible, active = dispatchable."""
    monkeypatch.setenv("TMTPU_MESH_MAX_DEVICES", "2")
    assert fresh_mesh.active_count() == 2
    assert bt.MESH["devices_total"] == 8.0
    assert bt.MESH["devices_active"] == 2.0


def test_degrade_recovery_reenters_mesh(fresh_mesh, fresh_bt, monkeypatch):
    """A tripped device re-joins through the breaker's half-open window
    once its recovery probe passes — recorded as an upward transition."""
    import jax

    ids = [d.id for d in jax.devices()]
    fresh_mesh.force_fail(ids[3])
    assert fresh_mesh.on_dispatch_failure(RuntimeError("x"))
    assert fresh_mesh.active_count() == 7

    # heal the chip and let the breaker's reset window elapse
    fresh_mesh.force_fail(ids[3], fail=False)
    br = fresh_mesh._breakers[ids[3]]
    monkeypatch.setattr(br, "clock", lambda: br._opened_at + 1e9)
    assert fresh_mesh.active_count() == 8
    assert bt.MESH["devices_active"] == 8.0
    assert bt.MESH["degrade_transitions"] == 2.0  # down, then up


# ---------------------------------------------------------------------------
# tracectl --per-device


def _load_tracectl():
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "tracectl", os.path.join(repo, "scripts", "tracectl.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tracectl_per_device_table(tmp_path, capsys):
    import json

    tracectl = _load_tracectl()

    spans = [
        {
            "subsystem": "hub", "name": "dispatch", "duration_ms": 3.0,
            "attrs": {
                "sigs": 140, "route": "tpu",
                "devices": [0, 1, 2, 3], "shards": [64, 64, 12, 0],
            },
        },
        {
            "subsystem": "hub", "name": "dispatch", "duration_ms": 2.0,
            "attrs": {
                "sigs": 60, "route": "tpu",
                "devices": [0, 1, 2, 3], "shards": [32, 28, 0, 0],
            },
        },
        # non-sharded dispatches and other spans are ignored
        {"subsystem": "hub", "name": "dispatch",
         "attrs": {"sigs": 5, "route": "cpu"}, "duration_ms": 1.0},
        {"subsystem": "p2p", "name": "receive", "duration_ms": 0.2},
    ]
    p = tmp_path / "dump.json"
    p.write_text(json.dumps({"spans": spans}))
    assert tracectl.main([str(p), "--per-device"]) == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert "device" in lines[0] and "share" in lines[0]
    row0 = lines[2].split()
    assert row0[0] == "0" and row0[1] == "2" and row0[2] == "96"
    assert "48.0%" in lines[2]  # 96 of 200 total sigs

    # no sharded spans -> explicit message, not an empty table
    p2 = tmp_path / "cpu.json"
    p2.write_text(json.dumps([{"subsystem": "hub", "name": "dispatch",
                               "attrs": {"route": "cpu"}}]))
    assert tracectl.main([str(p2), "--per-device"]) == 0
    assert "no sharded hub.dispatch" in capsys.readouterr().out


def test_hub_dispatch_span_carries_shards(monkeypatch):
    """The hub stamps devices/shards from the verifier's last sharded
    dispatch onto hub.dispatch spans (the tracectl --per-device feed)."""
    import secrets

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto import verify_hub as vh
    from tendermint_tpu.libs import trace

    class FakeBV:
        last_route = "tpu"
        last_dispatch = {"devices": [0, 1], "shards": [5, 3]}

        def __init__(self):
            self._items = []

        def add(self, pk, msg, sig):
            self._items.append((pk, msg, sig))

        def verify(self):
            return True, [True] * len(self._items)

    monkeypatch.setattr(vh, "create_batch_verifier", lambda pk: FakeBV())
    old = trace.RECORDER.enabled
    trace.RECORDER.enabled = True
    trace.RECORDER.clear()
    try:
        hub = VerifyHub(max_batch=8, window_ms=0.5, cache_size=0)
        hub.start()
        try:
            items = []
            for i in range(4):
                priv = ed25519.Ed25519PrivKey(secrets.token_bytes(32))
                msg = b"span-%d" % i
                items.append((priv.pub_key(), msg, priv.sign(msg)))
            assert all(hub.verify_many(items, timeout=30.0))
        finally:
            hub.stop()
        spans = [
            s for s in trace.RECORDER.dump()
            if s["subsystem"] == "hub" and s["name"] == "dispatch"
        ]
    finally:
        trace.RECORDER.enabled = old
    assert spans, "no hub.dispatch span recorded"
    attrs = spans[-1]["attrs"]
    assert attrs["devices"] == [0, 1] and attrs["shards"] == [5, 3]
    assert attrs["route"] == "tpu"
