"""tmtlint v2 — the tree-wide passes (ProjectContext, interprocedural
rules, wire-schema lockfile).

Fixture seam: `lint_tree({rel: source, ...})` builds a real
ProjectContext over an in-memory tree, so every test here sees exactly
what a full scan would — import resolution (absolute AND relative),
call-graph edges, chain-breaking pragmas, lockfile diffing.

The acceptance pins live here too: the 2-hop blocking fixture that the
per-file rule PROVABLY misses (asserted both ways), the renumbered
fixture copy of consensus/messages.py failing with old/new field
numbers in the message, and the real-tree lockfile completeness check.
"""

from __future__ import annotations

import copy
import json
import os
import subprocess
import sys
import textwrap

from tendermint_tpu.tools.lint import (
    ALL_RULES,
    DEFAULT_ALLOWLIST,
    RULES_BY_ID,
    Allowlist,
    FileContext,
    ProjectContext,
    lint_source,
    lint_tree,
)
from tendermint_tpu.tools.lint.framework import _parse_context
from tendermint_tpu.tools.lint.rules.wire_rules import (
    LOCKFILE,
    WireSchema,
    extract_wire_schema,
    file_uses_protoenc,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALLOW = Allowlist.load(DEFAULT_ALLOWLIST)


def dedent_tree(sources: dict[str, str]) -> dict[str, str]:
    return {rel: textwrap.dedent(src) for rel, src in sources.items()}


def run_tree(sources: dict[str, str], rule_id: str | None = None, **kw):
    out = lint_tree(dedent_tree(sources), ALL_RULES, ALLOW, **kw)
    if rule_id is not None:
        out = [f for f in out if f.rule == rule_id]
    return out


def make_pctx(sources: dict[str, str], full_tree: bool = True) -> ProjectContext:
    files = {}
    for rel, src in dedent_tree(sources).items():
        ctx = _parse_context(src, rel)
        assert isinstance(ctx, FileContext), f"fixture does not parse: {rel}"
        files[rel] = ctx
    pctx = ProjectContext(files, full_tree=full_tree)
    pctx.allowlist = ALLOW
    return pctx


# ---------------------------------------------------------------------------
# transitive-blocking — THE acceptance fixture


TWO_HOP = {
    "tendermint_tpu/consensus/somefile.py": """
    from ..libs import helpers

    async def handle_vote(self, vote):
        helpers.normalize(vote)
        return vote
    """,
    "tendermint_tpu/libs/helpers.py": """
    import time

    def normalize(vote):
        _settle(vote)
        return vote

    def _settle(vote):
        time.sleep(0.5)
    """,
}


def test_two_hop_blocking_chain_missed_by_per_file_rule():
    """The acceptance pin, both directions: the per-file rule passes
    this fixture (each file alone holds its invariant — no blocking
    call is lexically inside an async def), the project rule fails it
    at the coroutine with the whole chain in the message."""
    # old rule, file by file: provably clean
    for rel, src in dedent_tree(TWO_HOP).items():
        per_file = lint_source(
            src, rel, [RULES_BY_ID["blocking-in-async"]], ALLOW
        )
        assert per_file == [], (rel, [f.render() for f in per_file])
    # new pass: one finding, at the coroutine's call line
    fs = run_tree(TWO_HOP, "transitive-blocking")
    assert len(fs) == 1
    f = fs[0]
    assert f.path == "tendermint_tpu/consensus/somefile.py"
    assert f.line == 5  # the helpers.normalize(vote) call
    assert "handle_vote" in f.message
    assert "time.sleep" in f.message
    # the chain names BOTH hops with their files
    assert "normalize" in f.message and "_settle" in f.message
    assert "tendermint_tpu/libs/helpers.py" in f.message
    assert "2 hop(s)" in f.message


def test_intermediate_pragma_breaks_the_chain():
    """A reasoned pragma on the PRIMITIVE line (the audited boundary)
    suppresses the chain for every caller above it."""
    fixed = copy.deepcopy(TWO_HOP)
    fixed["tendermint_tpu/libs/helpers.py"] = """
    import time

    def normalize(vote):
        _settle(vote)
        return vote

    def _settle(vote):
        time.sleep(0.5)  # tmtlint: allow[blocking-in-async] -- fixture: measured sub-ms stub
    """
    assert run_tree(fixed, "transitive-blocking") == []
    # ... and a pragma on the intermediate EDGE works the same
    fixed["tendermint_tpu/libs/helpers.py"] = """
    import time

    def normalize(vote):
        _settle(vote)  # tmtlint: allow[transitive-blocking] -- fixture: cold path only
        return vote

    def _settle(vote):
        time.sleep(0.5)
    """
    assert run_tree(fixed, "transitive-blocking") == []


def test_pragma_at_the_coroutine_call_site_suppresses():
    fixed = copy.deepcopy(TWO_HOP)
    fixed["tendermint_tpu/consensus/somefile.py"] = """
    from ..libs import helpers

    async def handle_vote(self, vote):
        helpers.normalize(vote)  # tmtlint: allow[transitive-blocking] -- fixture: startup only
        return vote
    """
    assert run_tree(fixed, "transitive-blocking") == []


def test_three_hop_chain_and_self_method_resolution():
    """Chains propagate through `self.` method calls and `from x import
    f` bindings alike."""
    tree = {
        "tendermint_tpu/consensus/deep.py": """
        from ..libs.helpers import normalize

        class Reactor:
            async def on_frame(self, frame):
                self._apply(frame)

            def _apply(self, frame):
                normalize(frame)
        """,
        "tendermint_tpu/libs/helpers.py": """
        import subprocess

        def normalize(frame):
            _shell(frame)

        def _shell(frame):
            subprocess.run(["true"])
        """,
    }
    fs = run_tree(tree, "transitive-blocking")
    assert len(fs) == 1
    assert fs[0].line == 6  # the self._apply call inside the coroutine
    assert "subprocess.run" in fs[0].message
    assert "_apply" in fs[0].message and "_shell" in fs[0].message


def test_async_callees_and_to_thread_do_not_propagate():
    tree = {
        "tendermint_tpu/consensus/ok.py": """
        import asyncio
        from ..libs import helpers

        async def fine(self):
            await helpers.awaitable()          # async callee: not a sync chain
            await asyncio.to_thread(helpers.heavy)  # the FIX, not a finding
        """,
        "tendermint_tpu/libs/helpers.py": """
        import time, asyncio

        async def awaitable():
            await asyncio.sleep(0)

        def heavy():
            time.sleep(1.0)
        """,
    }
    assert run_tree(tree, "transitive-blocking") == []


def test_tests_profile_coroutines_exempt():
    tree = {
        "tests/test_x.py": """
        from tendermint_tpu.libs import helpers

        async def helper():
            helpers.normalize(1)
        """,
        "tendermint_tpu/libs/helpers.py": """
        import time

        def normalize(x):
            time.sleep(0.1)
        """,
    }
    assert run_tree(tree, "transitive-blocking") == []


def test_cycle_in_call_graph_terminates():
    tree = {
        "tendermint_tpu/consensus/cyc.py": """
        async def outer(self):
            a()

        def a():
            b()

        def b():
            a()
        """,
    }
    assert run_tree(tree, "transitive-blocking") == []


def test_cycle_truncated_search_does_not_poison_the_memo():
    """Review-pass regression: exploring x while y is on the DFS stack
    prunes x->y as a cycle; that TRUNCATED negative must not be cached,
    or a later query entering at x (whose real witness runs x->y->z->
    sleep) silently comes back clean — a false negative in every chain
    rule. Both coroutines must be flagged."""
    tree = {
        "tendermint_tpu/consensus/cycmemo.py": """
        import time

        async def c1(self):
            y()

        async def c2(self):
            x()

        def y():
            x()
            z()

        def x():
            y()

        def z():
            time.sleep(1)
        """,
    }
    fs = run_tree(tree, "transitive-blocking")
    assert len(fs) == 2, [f.render() for f in fs]
    assert {f.line for f in fs} == {5, 8}  # both coroutines' call sites
    assert all("time.sleep" in f.message for f in fs)


def test_restrict_to_filters_per_file_but_never_project_findings(tmp_path):
    """Review-pass regression (--changed contract): editing ONLY the
    helper must still surface the transitive finding that lands at the
    untouched coroutine, while per-file findings in untouched files
    stay filtered (pre-existing debt is the full gate's business)."""
    from tendermint_tpu.tools.lint import lint_paths

    repo = tmp_path
    (repo / "tendermint_tpu" / "consensus").mkdir(parents=True)
    (repo / "tendermint_tpu" / "libs").mkdir(parents=True)
    (repo / "tendermint_tpu" / "consensus" / "x.py").write_text(
        textwrap.dedent(
            """
            import time
            from ..libs.h import helper

            async def on_msg(self):
                helper()

            async def untouched_direct(self):
                time.sleep(1)  # per-file finding in an UNCHANGED file
            """
        )
    )
    (repo / "tendermint_tpu" / "libs" / "h.py").write_text(
        textwrap.dedent(
            """
            import time

            def helper():
                time.sleep(1)
            """
        )
    )
    rules = [RULES_BY_ID["blocking-in-async"], RULES_BY_ID["transitive-blocking"]]
    # pretend only the helper changed
    findings, n = lint_paths(
        ["tendermint_tpu"],
        rules,
        ALLOW,
        repo=str(repo),
        report_pragma_errors=False,
        restrict_to=["tendermint_tpu/libs/h.py"],
    )
    assert n == 2
    by_rule = {f.rule for f in findings}
    # the cross-file consequence IS reported, at the untouched coroutine
    assert "transitive-blocking" in by_rule
    assert any(
        f.rule == "transitive-blocking"
        and f.path == "tendermint_tpu/consensus/x.py"
        for f in findings
    )
    # the unrelated per-file finding in the untouched file is filtered
    assert "blocking-in-async" not in by_rule
    # ... and unfiltered without the restriction
    findings_full, _ = lint_paths(
        ["tendermint_tpu"], rules, ALLOW, repo=str(repo),
        report_pragma_errors=False,
    )
    assert any(f.rule == "blocking-in-async" for f in findings_full)


# ---------------------------------------------------------------------------
# transitive-verify


def test_coroutine_reaching_sync_facade_through_helper_flagged():
    """The helper's verify_sync is legal standing alone (sync contexts
    may block) — the call FROM a consensus coroutine is the defect, and
    only the call graph sees it."""
    tree = {
        "tendermint_tpu/consensus/ingest2.py": """
        from ..types.validation import check_commit

        async def on_commit(self, commit):
            check_commit(self.hub, commit)
        """,
        "tendermint_tpu/types/validation.py": """
        def check_commit(hub, commit):
            return hub.verify_sync(commit.pk, commit.msg, commit.sig)
        """,
    }
    # per-file: clean (validation.py is sync, outside ASYNC_SCOPES)
    for rel, src in dedent_tree(tree).items():
        assert lint_source(src, rel, [RULES_BY_ID["verify-chokepoint"]], ALLOW) == []
    fs = run_tree(tree, "transitive-verify")
    assert len(fs) == 1
    assert fs[0].path == "tendermint_tpu/consensus/ingest2.py"
    assert "verify_sync" in fs[0].message and "check_commit" in fs[0].message


def test_chain_into_crypto_is_a_legal_sink():
    """crypto/ IS the chokepoint: a chain that enters an allowlisted
    file stops — calling the hub's own machinery is the blessed path,
    not a bypass."""
    tree = {
        "tendermint_tpu/consensus/ingest3.py": """
        from ..crypto.verify_hub import hub_helper

        async def on_commit(self, commit):
            hub_helper(commit)
        """,
        "tendermint_tpu/crypto/verify_hub.py": """
        def hub_helper(commit):
            return commit.pk.verify_signature(commit.msg, commit.sig)
        """,
    }
    assert run_tree(tree, "transitive-verify") == []


def test_verify_signature_through_helper_flagged_outside_async_scope_helpers():
    tree = {
        "tendermint_tpu/blocksync/pool2.py": """
        from ..types.util import raw_check

        async def verify_block(self, b):
            raw_check(b)
        """,
        "tendermint_tpu/types/util.py": """
        def raw_check(b):
            return b.pk.verify_signature(b.msg, b.sig)
        """,
    }
    fs = run_tree(tree, "transitive-verify")
    assert len(fs) == 1 and "verify_signature" in fs[0].message


# ---------------------------------------------------------------------------
# transitive-fs


def test_storage_path_reaching_raw_write_through_libs_helper_flagged():
    tree = {
        "tendermint_tpu/consensus/wal.py": """
        from ..libs.diskutil import atomic_write

        class WAL:
            def flush(self, path, data):
                atomic_write(path, data)
        """,
        "tendermint_tpu/libs/diskutil.py": """
        import os

        def atomic_write(path, data):
            with open(path + ".tmp", "wb") as f:
                f.write(data)
            os.replace(path + ".tmp", path)
        """,
    }
    # per-file: clean — libs/ is outside the fs-discipline scope and
    # wal.py itself holds no raw write
    for rel, src in dedent_tree(tree).items():
        assert lint_source(src, rel, [RULES_BY_ID["fs-discipline"]], ALLOW) == []
    fs = run_tree(tree, "transitive-fs")
    assert len(fs) == 1
    assert fs[0].path == "tendermint_tpu/consensus/wal.py"
    assert "atomic_write" in fs[0].message
    assert "chaos" in fs[0].message


def test_fs_chain_into_allowlisted_db_is_legal():
    tree = {
        "tendermint_tpu/store/blockstore2.py": """
        from .db import persist

        class Store:
            def save(self, k, v):
                persist(k, v)
        """,
        "tendermint_tpu/store/db.py": """
        import os

        def persist(k, v):
            os.replace(k, v)
        """,
    }
    assert run_tree(tree, "transitive-fs") == []


# ---------------------------------------------------------------------------
# transitive-cleanup


def test_cleanup_await_reaching_unshielded_wait_for_flagged():
    tree = {
        "tendermint_tpu/libs/svc2.py": """
        import asyncio

        class Svc:
            async def stop(self):
                try:
                    await self.run()
                finally:
                    await self._drain()

            async def _drain(self):
                await asyncio.wait_for(self._flush(), 1.0)

            async def _flush(self):
                pass
        """,
    }
    # per-file absorbed-cancellation: clean — the wait_for is NOT
    # lexically in a cleanup context
    src = dedent_tree(tree)["tendermint_tpu/libs/svc2.py"]
    assert (
        lint_source(src, "tendermint_tpu/libs/svc2.py",
                    [RULES_BY_ID["absorbed-cancellation"]], ALLOW)
        == []
    )
    fs = run_tree(tree, "transitive-cleanup")
    assert len(fs) == 1
    assert "_drain" in fs[0].message and "wait_for" in fs[0].message


def test_shielded_wait_for_in_helper_clean():
    tree = {
        "tendermint_tpu/libs/svc3.py": """
        import asyncio

        class Svc:
            async def stop(self):
                try:
                    await self.run()
                finally:
                    await self._drain()

            async def _drain(self):
                await asyncio.wait_for(asyncio.shield(self._flush()), 1.0)

            async def _flush(self):
                pass
        """,
    }
    assert run_tree(tree, "transitive-cleanup") == []


# ---------------------------------------------------------------------------
# wire-bounds (per-file — fixtures ride lint_source like the others)


WIRE_BOUNDS_POS = """
from ..libs import protoenc as pe

def decode_things(data):
    r = pe.Reader(data)
    out = []
    while not r.eof():
        f, wt = r.read_tag()
        if f == 1:
            out.append(r.read_bytes())
        else:
            r.skip(wt)
    return out
"""


def test_unbounded_decode_growth_flagged():
    fs = lint_source(
        textwrap.dedent(WIRE_BOUNDS_POS),
        "tendermint_tpu/types/somewire.py",
        [RULES_BY_ID["wire-bounds"]],
        ALLOW,
    )
    assert len(fs) == 1 and "MAX_" in fs[0].message


def test_bounded_decode_growth_clean():
    src = """
    from ..libs import protoenc as pe

    MAX_THINGS = 1024

    def decode_things(data):
        r = pe.Reader(data)
        out = []
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                out.append(r.read_bytes())
                if len(out) > MAX_THINGS:
                    raise ValueError("too many things")
            else:
                r.skip(wt)
        return out
    """
    assert (
        lint_source(textwrap.dedent(src), "tendermint_tpu/types/somewire.py",
                    [RULES_BY_ID["wire-bounds"]], ALLOW)
        == []
    )


def test_decoded_count_range_flagged_and_checker_call_counts_as_clamp():
    bad = """
    from ..libs import protoenc as pe

    def decode_n(data):
        r = pe.Reader(data)
        out = []
        while not r.eof():
            f, wt = r.read_tag()
            for _ in range(r.read_uvarint()):
                out.append(f)
        return out
    """
    fs = lint_source(textwrap.dedent(bad), "tendermint_tpu/types/w2.py",
                     [RULES_BY_ID["wire-bounds"]], ALLOW)
    assert any("range" in f.message for f in fs)
    good = """
    from ..libs import protoenc as pe

    MAX_N = 64

    def _chk(lst, bound, what):
        if len(lst) > bound:
            raise ValueError(what)

    def decode_things(data):
        r = pe.Reader(data)
        out = []
        while not r.eof():
            f, wt = r.read_tag()
            if f == 1:
                out.append(r.read_bytes())
                _chk(out, MAX_N, "things")
            else:
                r.skip(wt)
        return out
    """
    assert (
        lint_source(textwrap.dedent(good), "tendermint_tpu/types/w2.py",
                    [RULES_BY_ID["wire-bounds"]], ALLOW)
        == []
    )


def test_wire_bounds_relaxed_for_tests_profile():
    assert (
        lint_source(textwrap.dedent(WIRE_BOUNDS_POS), "tests/test_w.py",
                    [RULES_BY_ID["wire-bounds"]], ALLOW)
        == []
    )


# ---------------------------------------------------------------------------
# wire-schema — lockfile mutation matrix


WIRE_TREE = {
    "tendermint_tpu/proto1/messages.py": """
    from ..libs import protoenc as pe

    T_PING = 1
    T_PONG = 2
    MAX_ITEMS = 64
    PROTO1_CHANNEL = 0x70

    def encode_ping(seq, payload):
        body = pe.varint_field(1, seq) + pe.bytes_field(2, payload)
        return pe.message_field(T_PING, body)

    def decode_frame(data):
        r = pe.Reader(data)
        f, wt = r.read_tag()
        body = r.read_bytes()
        items = []
        if f == T_PING:
            br = pe.Reader(body)
            while not br.eof():
                bf, bwt = br.read_tag()
                if bf == 1:
                    seq = br.read_uvarint()
                elif bf == 2:
                    items.append(br.read_bytes())
                    if len(items) > MAX_ITEMS:
                        raise ValueError("too many")
                else:
                    br.skip(bwt)
        return items
    """,
}


def wire_lock(tree: dict[str, str]) -> dict:
    return extract_wire_schema(make_pctx(tree))


def run_wire(tree: dict[str, str], lock: dict, full_tree: bool = True):
    rules = [r for r in ALL_RULES if r.id != "wire-schema"]
    rules.append(WireSchema(lock=lock))
    fs = lint_tree(dedent_tree(tree), rules, ALLOW, full_tree=full_tree)
    return [f for f in fs if f.rule == "wire-schema"]


def test_update_lock_round_trips_clean():
    lock = wire_lock(WIRE_TREE)
    assert run_wire(WIRE_TREE, lock) == []


def test_renumbered_field_fails_with_old_and_new_numbers():
    lock = wire_lock(WIRE_TREE)
    mutated = {
        "tendermint_tpu/proto1/messages.py": WIRE_TREE[
            "tendermint_tpu/proto1/messages.py"
        ].replace("pe.varint_field(1, seq)", "pe.varint_field(6, seq)")
    }
    fs = run_wire(mutated, lock)
    assert len(fs) == 1
    # old AND new numbers in the message — the reviewable diff
    assert "1:varint" in fs[0].message and "6:varint" in fs[0].message
    assert "encode_ping" in fs[0].message


def test_widened_wire_type_fails():
    lock = wire_lock(WIRE_TREE)
    mutated = {
        "tendermint_tpu/proto1/messages.py": WIRE_TREE[
            "tendermint_tpu/proto1/messages.py"
        ].replace("pe.varint_field(1, seq)", "pe.bytes_field(1, seq)")
    }
    fs = run_wire(mutated, lock)
    assert len(fs) == 1
    assert "1:varint" in fs[0].message and "1:bytes" in fs[0].message


def test_dropped_decode_bound_fails():
    lock = wire_lock(WIRE_TREE)
    src = WIRE_TREE["tendermint_tpu/proto1/messages.py"]
    # the named bound degrades to a magic number — the guard still
    # "works" today, but the schema lost its governing MAX_* constant
    src = src.replace(
        "if len(items) > MAX_ITEMS:", "if len(items) > 1073741824:"
    )
    assert "MAX_ITEMS:" not in src
    mutated = {"tendermint_tpu/proto1/messages.py": src}
    fs = run_wire(mutated, lock)
    assert any("DROPPED" in f.message and "MAX_ITEMS=64" in f.message for f in fs)


def test_reused_frame_tag_fails_without_lockfile_involvement():
    mutated = {
        "tendermint_tpu/proto1/messages.py": WIRE_TREE[
            "tendermint_tpu/proto1/messages.py"
        ]
        .replace("T_PONG = 2", "T_PONG = 1")
        .replace(
            "return pe.message_field(T_PING, body)",
            "return pe.message_field(T_PING, body)"
            ' + pe.message_field(T_PONG, b"")',
        )
    }
    # even a FRESH lock of the mutated tree cannot bless tag reuse
    lock = wire_lock(mutated)
    fs = run_wire(mutated, lock)
    assert any(
        "claimed by 2 constants" in f.message
        and "T_PING" in f.message
        and "T_PONG" in f.message
        for f in fs
    )


def test_channel_collision_across_files_fails():
    tree = dict(WIRE_TREE)
    tree["tendermint_tpu/proto2/messages.py"] = """
    from ..libs import protoenc as pe

    PROTO2_CHANNEL = 0x70

    def encode_x(v):
        return pe.varint_field(1, v)
    """
    lock = wire_lock(tree)
    fs = run_wire(tree, lock)
    assert any(
        "channel id 0x70" in f.message
        and "PROTO1_CHANNEL" in f.message
        and "PROTO2_CHANNEL" in f.message
        for f in fs
    )


def test_new_protoenc_file_without_lock_entry_is_a_finding():
    lock = wire_lock(WIRE_TREE)
    tree = dict(WIRE_TREE)
    tree["tendermint_tpu/proto3/fresh.py"] = """
    from ..libs import protoenc as pe

    def encode_y(v):
        return pe.varint_field(1, v)
    """
    fs = run_wire(tree, lock)
    assert any(
        f.path == "tendermint_tpu/proto3/fresh.py"
        and "no entry" in f.message
        for f in fs
    )


def test_stale_lock_entry_is_a_finding_only_on_full_tree():
    lock = wire_lock(WIRE_TREE)
    lock["files"]["tendermint_tpu/gone/old.py"] = {
        "encoders": {}, "decoders": {}, "bounds": []
    }
    fs = run_wire(WIRE_TREE, lock, full_tree=True)
    assert any("stale" in f.message for f in fs)
    # partial scans must not cry stale about files they did not look at
    assert run_wire(WIRE_TREE, lock, full_tree=False) == []


def test_channel_renumber_without_lock_update_fails():
    lock = wire_lock(WIRE_TREE)
    mutated = {
        "tendermint_tpu/proto1/messages.py": WIRE_TREE[
            "tendermint_tpu/proto1/messages.py"
        ].replace("PROTO1_CHANNEL = 0x70", "PROTO1_CHANNEL = 0x71")
    }
    fs = run_wire(mutated, lock)
    assert any("0x70 -> 0x71" in f.message for f in fs)


# ---------------------------------------------------------------------------
# the real tree: completeness + the messages.py renumber acceptance


def _real_tree_pctx() -> ProjectContext:
    from tendermint_tpu.tools.lint.cli import build_project_context

    return build_project_context(["tendermint_tpu"])


def test_lockfile_covers_every_protoenc_frame_family_in_the_tree():
    """Acceptance: a protoenc call site in a file absent from the
    lockfile is itself a finding (pinned by the fixture above), and the
    CHECKED-IN lockfile actually covers the tree at HEAD."""
    with open(LOCKFILE, encoding="utf-8") as f:
        lock = json.load(f)
    pctx = _real_tree_pctx()
    extracted = extract_wire_schema(pctx)
    missing = sorted(set(extracted["files"]) - set(lock.get("files", {})))
    assert missing == [], f"protoenc files not locked: {missing}"
    stale = sorted(set(lock.get("files", {})) - set(extracted["files"]))
    assert stale == [], f"stale lock entries: {stale}"
    # the frame families the tree grew over PRs 1-13 are all present
    for rel in (
        "tendermint_tpu/consensus/messages.py",
        "tendermint_tpu/consensus/wal.py",
        "tendermint_tpu/types/vote.py",
        "tendermint_tpu/types/block.py",
        "tendermint_tpu/types/evidence.py",
        "tendermint_tpu/types/part_set.py",
        "tendermint_tpu/types/params.py",
        "tendermint_tpu/types/validator_set.py",
        "tendermint_tpu/types/canonical.py",
        "tendermint_tpu/p2p/types.py",
        "tendermint_tpu/p2p/pex.py",
        "tendermint_tpu/p2p/secret.py",
        "tendermint_tpu/mempool/ingress.py",
        "tendermint_tpu/mempool/reactor.py",
        "tendermint_tpu/crypto/verifyd.py",
        "tendermint_tpu/light/fleet.py",
        "tendermint_tpu/abci/types.py",
        "tendermint_tpu/blocksync/messages.py",
        "tendermint_tpu/statesync/messages.py",
    ):
        assert rel in lock["files"], f"{rel} missing from lockfile"
        assert file_uses_protoenc(pctx, rel)


def test_renumbered_field_in_real_messages_py_fails_lint():
    """Acceptance: a one-line renumber in a fixture copy of the REAL
    consensus/messages.py fails against the REAL checked-in lockfile,
    with the old and new numbers in the message."""
    rel = "tendermint_tpu/consensus/messages.py"
    with open(os.path.join(REPO, rel), encoding="utf-8") as f:
        source = f.read()
    needle = "pe.varint_field(2, msg.round + 1)"  # NewRoundStep.round
    assert needle in source
    mutated = source.replace(needle, "pe.varint_field(6, msg.round + 1)", 1)
    with open(LOCKFILE, encoding="utf-8") as f:
        lock = json.load(f)
    rules = [WireSchema(lock=lock)]
    fs = [
        f
        for f in lint_tree({rel: mutated}, rules, ALLOW, full_tree=False)
        if f.rule == "wire-schema"
    ]
    assert len(fs) == 1, [f.render() for f in fs]
    assert "2:varint" in fs[0].message and "6:varint" in fs[0].message
    assert "encode_message" in fs[0].message
    # and the unmutated copy is clean against the same lock
    assert [
        f
        for f in lint_tree({rel: source}, rules, ALLOW, full_tree=False)
        if f.rule == "wire-schema"
    ] == []


def test_real_tree_has_no_unpragmad_transitive_findings():
    """Acceptance: the full-tree scan is clean at HEAD for the
    interprocedural passes specifically (the whole-battery gate lives
    in test_lint.py; this pins the new rules with their own message)."""
    from tendermint_tpu.tools.lint import lint_paths

    findings, n = lint_paths(
        ["tendermint_tpu", "scripts"],
        [
            RULES_BY_ID["transitive-blocking"],
            RULES_BY_ID["transitive-verify"],
            RULES_BY_ID["transitive-fs"],
            RULES_BY_ID["transitive-cleanup"],
        ],
        ALLOW,
        report_pragma_errors=False,
    )
    assert n > 100
    assert findings == [], [f.render() for f in findings]


# ---------------------------------------------------------------------------
# CLI: --update-lock round-trip through the real entrypoint


def test_cli_update_lock_round_trip(tmp_path):
    """--update-lock writes a lockfile that the very next run is clean
    against (the blessing workflow), via the real entrypoint."""
    lock = tmp_path / "wire.lock.json"

    def tmtlint(*args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "tmtlint"), *args],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )

    out = tmtlint("--update-lock", "--lock", str(lock))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "wire schema locked" in out.stdout
    written = json.loads(lock.read_text())
    assert written["files"] and written["channels"]
    out = tmtlint("--json", "--rule", "wire-schema", "--lock", str(lock),
                  "tendermint_tpu")
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert payload["per_rule"] == {"wire-schema": 0}
    # the tmp lock matches the checked-in one: --update-lock is
    # deterministic, so the blessing step never produces diff noise
    with open(LOCKFILE, encoding="utf-8") as f:
        assert written == json.load(f)


def test_wall_budget_for_project_passes():
    """The tree-wide passes (call graph + wire extraction) must stay a
    rounding error in the tier-1 budget — asserted via the same JSON
    the gate reads."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tmtlint"), "--json"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert payload["elapsed_s"] < 10.0, f"lint too slow: {payload['elapsed_s']}s"
