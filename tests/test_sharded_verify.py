"""Multi-device batch verification on the virtual 8-device CPU mesh
(conftest provisions --xla_force_host_platform_device_count=8).

These exercise the PRODUCTION sharded path — the same code
`verify_resolved` selects on a real multi-chip topology (reference
crypto/crypto.go:46-54: one BatchVerifier interface regardless of
topology) — not just the dryrun demo: bad-signature attribution
fallback, sr25519/mixed batches, and batch sizes that do not divide the
mesh."""

import secrets

import numpy as np
import pytest

import jax

from tendermint_tpu.crypto import ed25519


def _signed_items(n, tag=b"shard"):
    items = []
    for i in range(n):
        priv = ed25519.Ed25519PrivKey(secrets.token_bytes(32))
        msg = tag + b"-%d" % i
        items.append((priv.pub_key().bytes(), msg, priv.sign(msg)))
    return items


@pytest.fixture
def force_sharded(monkeypatch):
    """Route verify_resolved through the sharded kernels regardless of
    batch size (the size gate exists to keep tiny production batches on
    one device)."""
    monkeypatch.setenv("TMTPU_FORCE_SHARDED", "1")


def test_mesh_is_multi_device():
    assert len(jax.devices()) == 8


def test_sharded_selected_for_large_batches(monkeypatch):
    """The production selector picks the sharded path for range-batch
    sized workloads without any env override."""
    from tendermint_tpu.crypto.tpu import verify as V

    monkeypatch.delenv("TMTPU_FORCE_SHARDED", raising=False)
    monkeypatch.delenv("TMTPU_NO_SHARDED", raising=False)
    n_dev = V._shard_device_count()
    assert n_dev == 8
    items = _signed_items(V._MIN_BUCKET * n_dev, b"big")
    out = V.verify_batch_eq(items)
    assert out.all() and len(out) == len(items)
    assert n_dev in V._sharded_kernels  # the production cache was used


def test_sharded_all_valid_non_divisible(force_sharded):
    """81 signatures over 8 devices: padding must round the bucket up to
    a mesh-divisible size and padded rows must stay inert."""
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    items = _signed_items(81, b"nd")
    out = verify_batch_eq(items)
    assert out.all() and len(out) == 81


def test_sharded_bad_signature_attribution(force_sharded):
    """A corrupted signature fails the batch equation; the SHARDED
    per-signature fallback kernel recovers exact attribution."""
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    items = _signed_items(24, b"bad")
    p, m, s = items[17]
    items[17] = (p, m, s[:40] + bytes([s[40] ^ 0x10]) + s[41:])
    out = verify_batch_eq(items)
    assert not out[17] and out.sum() == 23


def test_sharded_mixed_sr25519(force_sharded):
    """ed25519 and sr25519 resolve to the same Edwards-form check and ride
    one sharded MSM together; malformed entries stay False."""
    from tendermint_tpu.crypto import sr25519 as sr
    from tendermint_tpu.crypto.tpu.verify import (
        resolve_ed25519,
        resolve_sr25519,
        verify_resolved,
    )

    entries = []
    for i in range(5):
        priv = ed25519.Ed25519PrivKey(secrets.token_bytes(32))
        msg = b"mix-ed-%d" % i
        entries.append(resolve_ed25519(priv.pub_key().bytes(), msg, priv.sign(msg)))
    for i in range(5):
        priv = sr.Sr25519PrivKey(bytes([0x60 + i]) * 32)
        msg = b"mix-sr-%d" % i
        entries.append(
            resolve_sr25519(priv.pub_key().bytes(), msg, priv.sign(msg))
        )
    entries.append(None)  # malformed (e.g. wrong-size key) stays False
    out = verify_resolved(entries)
    assert out[:10].all() and not out[10]

    # tamper one sr25519 -> sharded per-sig fallback attributes it
    priv = sr.Sr25519PrivKey(b"\x71" * 32)
    sig = bytearray(priv.sign(b"y"))
    sig[5] ^= 1
    entries[7] = resolve_sr25519(priv.pub_key().bytes(), b"y", bytes(sig))
    out = verify_resolved(entries)
    assert not out[7] and not out[10] and out.sum() == 9


def test_sharded_matches_single_device(force_sharded, monkeypatch):
    """Sharded and single-device kernels agree bit-for-bit on the same
    batch (including a corrupted row)."""
    from tendermint_tpu.crypto.tpu import verify as V

    items = _signed_items(16, b"agree")
    p, m, s = items[3]
    items[3] = (p, m, s[:10] + bytes([s[10] ^ 1]) + s[11:])

    sharded = V.verify_batch_eq(items)
    monkeypatch.setenv("TMTPU_NO_SHARDED", "1")
    monkeypatch.delenv("TMTPU_FORCE_SHARDED", raising=False)
    single = V.verify_batch_eq(items)
    assert np.array_equal(sharded, single)
    assert not sharded[3] and sharded.sum() == 15
