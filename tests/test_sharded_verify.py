"""Multi-device batch verification on the virtual 8-device CPU mesh
(conftest provisions --xla_force_host_platform_device_count=8).

These exercise the PRODUCTION sharded path — the same code
`verify_resolved` selects on a real multi-chip topology (reference
crypto/crypto.go:46-54: one BatchVerifier interface regardless of
topology) — not just the dryrun demo: bad-signature attribution
fallback, sr25519/mixed batches, and batch sizes that do not divide the
mesh."""

import secrets

import numpy as np
import pytest

import jax

from tendermint_tpu.crypto import ed25519


def _signed_items(n, tag=b"shard"):
    items = []
    for i in range(n):
        priv = ed25519.Ed25519PrivKey(secrets.token_bytes(32))
        msg = tag + b"-%d" % i
        items.append((priv.pub_key().bytes(), msg, priv.sign(msg)))
    return items


@pytest.fixture
def force_sharded(monkeypatch):
    """Route verify_resolved through the sharded kernels regardless of
    batch size (the size gate exists to keep tiny production batches on
    one device)."""
    monkeypatch.setenv("TMTPU_FORCE_SHARDED", "1")


@pytest.fixture
def fresh_mesh():
    """Pristine per-device health registry before AND after: degrade
    tests trip breakers that would otherwise leak into later tests."""
    from tendermint_tpu.crypto import backend_telemetry as bt
    from tendermint_tpu.crypto.tpu import mesh

    mesh.reset()
    yield mesh
    mesh.reset()
    bt.reset()


def test_mesh_is_multi_device():
    assert len(jax.devices()) == 8


def test_sharded_selected_for_large_batches(monkeypatch):
    """The production selector picks the sharded path for range-batch
    sized workloads without any env override."""
    from tendermint_tpu.crypto.tpu import verify as V

    monkeypatch.delenv("TMTPU_FORCE_SHARDED", raising=False)
    monkeypatch.delenv("TMTPU_NO_SHARDED", raising=False)
    n_dev = V._shard_device_count()
    assert n_dev == 8
    items = _signed_items(V._MIN_BUCKET * n_dev, b"big")
    out = V.verify_batch_eq(items)
    assert out.all() and len(out) == len(items)
    # the production cache was used, keyed by the exact device set
    assert any(len(key) == n_dev for key in V._sharded_kernels)


def test_sharded_all_valid_non_divisible(force_sharded):
    """81 signatures over 8 devices: padding must round the bucket up to
    a mesh-divisible size and padded rows must stay inert."""
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    items = _signed_items(81, b"nd")
    out = verify_batch_eq(items)
    assert out.all() and len(out) == 81


def test_sharded_bad_signature_attribution(force_sharded):
    """A corrupted signature fails the batch equation; the SHARDED
    per-signature fallback kernel recovers exact attribution."""
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    items = _signed_items(24, b"bad")
    p, m, s = items[17]
    items[17] = (p, m, s[:40] + bytes([s[40] ^ 0x10]) + s[41:])
    out = verify_batch_eq(items)
    assert not out[17] and out.sum() == 23


def test_sharded_mixed_sr25519(force_sharded):
    """ed25519 and sr25519 resolve to the same Edwards-form check and ride
    one sharded MSM together; malformed entries stay False."""
    from tendermint_tpu.crypto import sr25519 as sr
    from tendermint_tpu.crypto.tpu.verify import (
        resolve_ed25519,
        resolve_sr25519,
        verify_resolved,
    )

    entries = []
    for i in range(5):
        priv = ed25519.Ed25519PrivKey(secrets.token_bytes(32))
        msg = b"mix-ed-%d" % i
        entries.append(resolve_ed25519(priv.pub_key().bytes(), msg, priv.sign(msg)))
    for i in range(5):
        priv = sr.Sr25519PrivKey(bytes([0x60 + i]) * 32)
        msg = b"mix-sr-%d" % i
        entries.append(
            resolve_sr25519(priv.pub_key().bytes(), msg, priv.sign(msg))
        )
    entries.append(None)  # malformed (e.g. wrong-size key) stays False
    out = verify_resolved(entries)
    assert out[:10].all() and not out[10]

    # tamper one sr25519 -> sharded per-sig fallback attributes it
    priv = sr.Sr25519PrivKey(b"\x71" * 32)
    sig = bytearray(priv.sign(b"y"))
    sig[5] ^= 1
    entries[7] = resolve_sr25519(priv.pub_key().bytes(), b"y", bytes(sig))
    out = verify_resolved(entries)
    assert not out[7] and not out[10] and out.sum() == 9


def test_sharded_matches_single_device(force_sharded, monkeypatch):
    """Sharded and single-device kernels agree bit-for-bit on the same
    batch (including a corrupted row)."""
    from tendermint_tpu.crypto.tpu import verify as V

    items = _signed_items(16, b"agree")
    p, m, s = items[3]
    items[3] = (p, m, s[:10] + bytes([s[10] ^ 1]) + s[11:])

    sharded = V.verify_batch_eq(items)
    monkeypatch.setenv("TMTPU_NO_SHARDED", "1")
    monkeypatch.delenv("TMTPU_FORCE_SHARDED", raising=False)
    single = V.verify_batch_eq(items)
    assert np.array_equal(sharded, single)
    assert not sharded[3] and sharded.sum() == 15


def test_sharded_same_seed_determinism(force_sharded):
    """Sharding ON, same mixed valid/invalid batch verified twice ->
    bit-identical verdict bitmaps (the chaos suite's reproducibility
    contract must survive the mesh)."""
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    items = _signed_items(24, b"det")
    p, m, s = items[5]
    items[5] = (p, m, s[:20] + bytes([s[20] ^ 0x40]) + s[21:])
    out1 = verify_batch_eq(items)
    out2 = verify_batch_eq(items)
    assert np.array_equal(out1, out2)
    assert not out1[5] and out1.sum() == 23


def test_shard_fill_and_dispatch_telemetry(force_sharded, fresh_mesh):
    """A sharded dispatch records per-device real-signature counts
    (padding excluded) into backend_telemetry and the thread's
    last-dispatch info (the hub's span attrs)."""
    from tendermint_tpu.crypto import backend_telemetry as bt
    from tendermint_tpu.crypto.tpu import verify as V

    bt.reset()
    items = _signed_items(20, b"fill")
    out = V.verify_batch_eq(items)
    assert out.all()
    info = V.last_dispatch_info()
    assert info is not None and len(info["devices"]) == 8
    assert sum(info["shards"]) == 20  # real rows only, padding excluded
    assert sum(bt.SHARD_SIGS.values()) == 20.0
    # contiguous shards: fill is front-loaded, never interleaved
    assert info["shards"] == V._shard_fill(20, 64, 8)


def test_per_device_breaker_degrade_plumbing(force_sharded, fresh_mesh, monkeypatch):
    """A chip failing its shard trips ITS breaker and the batch
    re-verifies on the N−1 survivors (kernel stubbed: the real degraded
    mesh compile is the slow test below). Telemetry records the
    transition."""
    import jax

    from tendermint_tpu.crypto import backend_telemetry as bt
    from tendermint_tpu.crypto.tpu import verify as V

    bt.reset()
    ids = [d.id for d in jax.devices()]
    calls = {}

    def boom(*args):
        raise RuntimeError("chip 7 died mid-MSM")

    def stub7(ua, r, ga, rd, zs, sv, gidx):
        calls["stub7"] = True
        return np.asarray(sv), np.array(True)

    monkeypatch.setitem(V._sharded_kernels, tuple(ids), (boom, boom))
    monkeypatch.setitem(V._sharded_kernels, tuple(ids[:7]), (stub7, boom))
    fresh_mesh.force_fail(ids[7])

    entries = [V.resolve_ed25519(*it) for it in _signed_items(12, b"deg")]
    out = V.verify_resolved(entries)
    assert out.all() and len(out) == 12
    assert calls.get("stub7"), "degraded re-dispatch did not use the 7-dev mesh"
    assert fresh_mesh.active_count() == 7
    assert bt.MESH["devices_active"] == 7.0
    assert bt.MESH["degrade_transitions"] == 1.0
    # the dispatch info reflects the SURVIVING mesh the batch actually
    # ran on, not the stale 8-device selection
    info = V.last_dispatch_info()
    assert info and len(info["devices"]) == 7


def test_degrade_retry_without_new_breaker_trip(
    force_sharded, fresh_mesh, monkeypatch
):
    """Multi-chunk batches launch every chunk against the same selection
    before any is collected: a LATER failed chunk finds the dead chip's
    breaker already tripped (probes all pass) and must still retry on
    the survivors — only a genuinely unchanged mesh re-raises to CPU."""
    import jax

    from tendermint_tpu.crypto.tpu import verify as V

    ids = [d.id for d in jax.devices()]
    calls = {}

    def boom(*args):
        raise RuntimeError("x")

    def stub7(ua, r, ga, rd, zs, sv, gidx):
        calls["stub7"] = True
        return np.asarray(sv), np.array(True)

    entries = [V.resolve_ed25519(*it) for it in _signed_items(12, b"late")]
    sel8 = V._select_kernels(12, 1)
    assert sel8.devices is not None and len(sel8.devices) == 8

    # unchanged mesh + passing probes -> re-raise (CPU fallback's turn)
    with pytest.raises(RuntimeError, match="transient"):
        V._degrade_and_retry(entries, 1, RuntimeError("transient"), sel8)

    # an earlier chunk already tripped chip 7: no NEW trip to find, but
    # the active set no longer matches the pinned selection -> retry
    fresh_mesh._breakers[ids[7]].record_failure()
    monkeypatch.setitem(V._sharded_kernels, tuple(ids[:7]), (stub7, boom))
    out = V._degrade_and_retry(entries, 1, RuntimeError("late chunk"), sel8)
    assert out.all() and len(out) == 12 and calls.get("stub7")


def test_whole_mesh_dead_falls_back_to_cpu(fresh_mesh, monkeypatch):
    """8→7→…→CPU: when every device (including the single-device path)
    is dead, AdaptiveBatchVerifier returns the identical CPU verdicts —
    callers never see the device error."""
    import jax

    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto.ed25519 import Ed25519PubKey
    from tendermint_tpu.crypto.tpu import verify as V
    from tendermint_tpu.libs.retry import CircuitBreaker

    ids = [d.id for d in jax.devices()]
    for i in ids:
        fresh_mesh.force_fail(i)

    def boom(*args, **kw):
        raise RuntimeError("mesh dead")

    monkeypatch.setenv("TMTPU_FORCE_SHARDED", "1")
    monkeypatch.setitem(V._sharded_kernels, tuple(ids), (boom, boom))
    monkeypatch.setattr(V, "_get_kernel_eq", boom)
    monkeypatch.setattr(V, "_get_kernel", boom)
    monkeypatch.setattr(B, "_tpu_available", True)
    monkeypatch.setattr(B, "MIN_TPU_BATCH", 1)
    monkeypatch.setattr(
        B, "_tpu_breaker",
        CircuitBreaker(failure_threshold=1, reset_timeout=30, name="t"),
    )

    items = _signed_items(8, b"dead")
    p, m, s = items[2]
    items[2] = (p, m, s[:1] + bytes([s[1] ^ 1]) + s[2:])
    bv = B.AdaptiveBatchVerifier()
    for pub, msg, sig in items:
        bv.add(Ed25519PubKey(pub), msg, sig)
    ok, bitmap = bv.verify()
    assert not ok and not bitmap[2] and sum(bitmap) == 7
    assert bv.last_route == "cpu-fallback"
    assert fresh_mesh.active_count() == 0  # every breaker tripped


def test_bucket_guard():
    """Dispatch shapes must come off the bucket ladder — anything else
    would be an inline cold XLA compile on the hot path."""
    from tendermint_tpu.crypto.tpu import verify as V

    assert V._is_warm_bucket(64)
    assert V._is_warm_bucket(128)
    assert V._is_warm_bucket(8192)
    assert V._is_warm_bucket(64, 8)  # 8-device mesh floor
    assert V._is_warm_bucket(70, 7)  # degraded 7-device mesh floor
    assert not V._is_warm_bucket(65)
    assert not V._is_warm_bucket(100)
    assert not V._is_warm_bucket(32)  # below the floor
    assert not V._is_warm_bucket(96, 8)  # not a rounded power of two
    # the ladder itself always satisfies the guard
    for n in (1, 63, 64, 65, 81, 150, 8100, 8192):
        for mult in (1, 7, 8):
            assert V._is_warm_bucket(V._bucket(n, mult), mult), (n, mult)


def test_dispatch_asserts_bucket_shape(monkeypatch):
    """A selection that escapes the bucket ladder trips the runtime
    guard (and therefore the CPU fallback) instead of compiling cold."""
    from tendermint_tpu.crypto.tpu import verify as V

    bad = V._Selection(lambda *a: None, lambda *a: None, 100, 1, None)
    monkeypatch.setattr(V, "_select_kernels", lambda n, m: bad)
    entries = [V.resolve_ed25519(*it) for it in _signed_items(4, b"guard")]
    with pytest.raises(AssertionError, match="not a bucket"):
        V.verify_resolved(entries)


@pytest.mark.slow
def test_degrade_8_to_7_real_kernel(force_sharded, fresh_mesh):
    """The full degraded-mesh path with REAL kernels: device 7 dies, the
    batch re-verifies on a 7-device mesh (non-power-of-two shards, fresh
    compile shape) with bit-identical verdicts. Slow: first run compiles
    the 7-device kernel (~100 s cold on the virtual CPU mesh)."""
    import jax

    from tendermint_tpu.crypto import backend_telemetry as bt
    from tendermint_tpu.crypto.tpu import verify as V

    bt.reset()
    items = _signed_items(20, b"real-deg")
    p, m, s = items[9]
    items[9] = (p, m, s[:40] + bytes([s[40] ^ 2]) + s[41:])
    want = V.verify_batch_eq(items)  # healthy 8-device mesh

    ids = [d.id for d in jax.devices()]
    fresh_mesh.force_fail(ids[7])
    assert fresh_mesh.on_dispatch_failure(RuntimeError("injected"))
    assert fresh_mesh.active_count() == 7

    got = V.verify_batch_eq(items)  # real 7-device mesh
    assert np.array_equal(want, got)
    assert not got[9] and got.sum() == 19
    info = V.last_dispatch_info()
    assert info and len(info["devices"]) == 7
    assert bt.MESH["devices_active"] == 7.0
