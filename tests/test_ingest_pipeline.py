"""Pipelined consensus ingest (consensus/ingest.py) + VerifyHub lane
tests: ordering equivalence against the sequential facade, equivocation
detection when conflicting votes verify out of order, drain-on-stop
with verifications in flight, live-lane packing priority, the backfill
starvation guard (live p50 within 2x of unloaded), lane promotion, and
the metrics render fold for the new verifyhub_lane_* /
consensus_ingest_* series."""

import asyncio
import statistics
import time

import pytest

from tendermint_tpu.consensus.harness import LocalNetwork, Node, fast_config, make_genesis
from tendermint_tpu.crypto import verify_hub as vh
from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
from tendermint_tpu.crypto.verify_hub import LANE_BACKFILL, LANE_LIVE, VerifyHub
from tendermint_tpu.types.keys import SignedMsgType
from tendermint_tpu.types.vote import Vote


def _items(n, tag=b"lane", priv=None):
    priv = priv or Ed25519PrivKey(b"\x21" * 32)
    pub = priv.pub_key()
    out = []
    for i in range(n):
        msg = tag + b"-%d" % i
        out.append((pub, msg, priv.sign(msg)))
    return out


async def _observer(*, pipeline: bool, n_vals: int = 4):
    """One non-validator ConsensusState at height 1 plus the signing
    material of its validator set."""
    genesis, keys = make_genesis(n_vals)
    cfg = fast_config()
    cfg.ingest_pipeline = pipeline
    # park the SM: the observer should tally, not drive rounds
    cfg.timeout_propose_ns = 3_600 * 10**9
    cfg.timeout_commit_ns = 0
    node = Node(genesis, None, config=cfg)
    await node.start()
    vals = node.cs.rs.validators
    by_index = {}
    for k in keys:
        idx, val = vals.get_by_address(k.pub_key().address())
        assert val is not None
        by_index[idx] = k
    return node, by_index


def _signed_vote(cs, key, idx, *, round_=0, type_=SignedMsgType.PREVOTE,
                 block_id=None, tweak=0):
    from tendermint_tpu.types.block import NIL_BLOCK_ID

    bid = block_id or NIL_BLOCK_ID
    vote = Vote(
        type=type_,
        height=cs.rs.height,
        round=round_,
        block_id=bid,
        timestamp_ns=1_700_000_000_000_000_000 + tweak,
        validator_address=key.pub_key().address(),
        validator_index=idx,
        signature=b"",
    )
    sig = key.sign(vote.sign_bytes(cs.state.chain_id))
    return Vote(**{**vote.__dict__, "signature": sig})


async def _drain(cs, timeout=10.0):
    """Wait until the ingest pipeline (if any) and the input queue are
    quiescent."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        busy = not cs.msg_queue.empty()
        if cs.ingest is not None:
            busy = busy or cs.ingest.inflight > 0
        if not busy:
            await asyncio.sleep(0.05)  # one more beat for the apply
            if cs.msg_queue.empty() and (
                cs.ingest is None or cs.ingest.inflight == 0
            ):
                return
        await asyncio.sleep(0.01)
    raise TimeoutError("ingest did not drain")


def _prevote_state(cs, round_=0):
    vs = cs.rs.votes.prevotes(round_)
    return [
        (v.validator_index, v.block_id.key(), v.signature) if v else None
        for v in vs.votes
    ]


class TestOrderingEquivalence:
    @pytest.mark.asyncio
    async def test_pipelined_tally_equals_sequential(self):
        """The same scripted vote sequence — duplicates, an invalid
        signature, votes from every validator — produces an identical
        vote-set through the pipeline and through the sequential
        facade, with the pipeline never re-verifying at apply time."""
        from tendermint_tpu import testing as tt

        hub = vh.acquire_hub(max_batch=64, window_ms=1.0)
        states = {}
        try:
            for pipeline in (False, True):
                node, by_index = await _observer(pipeline=pipeline)
                cs = node.cs
                bid = tt.make_block_id(b"ord-eq")
                votes = []
                for idx, key in sorted(by_index.items()):
                    votes.append(_signed_vote(cs, key, idx, block_id=bid))
                # an invalid signature from validator 2 for a DIFFERENT
                # block: must be rejected, not tallied, on both paths
                bad = _signed_vote(cs, by_index[2], 2, block_id=tt.make_block_id(b"x"))
                bad = Vote(**{**bad.__dict__, "signature": b"\x01" * 64})
                votes.append(bad)
                for v in votes:
                    await cs.add_vote(v, "peerA")
                await _drain(cs)
                # gossip duplicate of an already-APPLIED vote: the
                # pipeline drops it against the vote-set pre-verify
                await cs.add_vote(votes[0], "peerB")
                await _drain(cs)
                states[pipeline] = _prevote_state(cs)
                if pipeline:
                    s = cs.ingest.stats
                    assert s["pre_verified"] >= 4, s
                    assert s["dedup_drops"] >= 1, s
                    assert s["sig_invalid"] == 1, s
                await node.stop()
            assert states[True] == states[False]
            assert sum(1 for v in states[True] if v) == 4
        finally:
            vh.release_hub()

    @pytest.mark.asyncio
    async def test_pipelined_commit_equals_sequential(self):
        """Catch-up-shaped input (decided precommits + block parts)
        commits the identical block through both ingest paths."""
        src = LocalNetwork(4, config=fast_config())
        await src.start()
        try:
            await src.wait_for_height(1, 30)
        finally:
            await src.stop()
        donor = src.nodes[0]
        commit = donor.block_store.load_block_commit(
            1
        ) or donor.block_store.load_seen_commit(1)
        meta = donor.block_store.load_block_meta(1)
        want = donor.block_store.load_block(1).hash()
        assert commit is not None and meta is not None

        hashes = {}
        for pipeline in (False, True):
            node, _ = await _observer(pipeline=pipeline)
            cs = node.cs
            cs.rs.votes.set_peer_maj23(
                commit.round, SignedMsgType.PRECOMMIT, "relay"
            )
            for idx, cs_sig in enumerate(commit.signatures):
                if cs_sig.is_absent():
                    continue
                vote = Vote(
                    type=SignedMsgType.PRECOMMIT,
                    height=commit.height,
                    round=commit.round,
                    block_id=cs_sig.block_id(commit.block_id),
                    timestamp_ns=cs_sig.timestamp_ns,
                    validator_address=cs_sig.validator_address,
                    validator_index=idx,
                    signature=cs_sig.signature,
                )
                await cs.add_vote(vote, "relay")
            for idx in range(meta.block_id.part_set_header.total):
                part = donor.block_store.load_block_part(1, idx)
                await cs.add_block_part(1, commit.round, part, "relay")
            await cs.wait_for_height(1, 20)
            hashes[pipeline] = node.block_store.load_block(1).hash()
            await node.stop()
        assert hashes[True] == hashes[False] == want


class TestEquivocation:
    @pytest.mark.asyncio
    async def test_conflict_detected_when_votes_verify_out_of_order(self):
        """Two conflicting votes from one validator are submitted
        back-to-back: stage 1 verifies them CONCURRENTLY, but in-order
        apply still sees first-arrival as `existing` and the second as
        `new`, so the evidence pair is deterministic."""
        from tendermint_tpu import testing as tt

        hub = vh.acquire_hub(max_batch=64, window_ms=1.0)
        try:
            node, by_index = await _observer(pipeline=True)
            cs = node.cs
            pairs = []
            cs.evidence_pool.report_conflicting_votes = (
                lambda a, b: pairs.append((a, b))
            )
            a = _signed_vote(cs, by_index[1], 1, block_id=tt.make_block_id(b"A"))
            b = _signed_vote(cs, by_index[1], 1, block_id=tt.make_block_id(b"B"))
            await cs.add_vote(a, "p1")
            await cs.add_vote(b, "p2")
            await _drain(cs)
            assert len(pairs) == 1
            existing, new = pairs[0]
            assert existing.block_id == a.block_id
            assert new.block_id == b.block_id
            await node.stop()
        finally:
            vh.release_hub()


class TestDrainOnStop:
    @pytest.mark.asyncio
    async def test_stop_with_verifications_in_flight(self):
        """stop() with a long hub window (many verdicts pending) must
        return promptly and leak no ingest tasks."""
        from tendermint_tpu import testing as tt

        hub = vh.acquire_hub(max_batch=512, window_ms=2_000.0)
        try:
            node, by_index = await _observer(pipeline=True)
            cs = node.cs
            for round_ in range(6):
                for idx, key in sorted(by_index.items()):
                    v = _signed_vote(
                        cs, key, idx, round_=round_,
                        block_id=tt.make_block_id(b"drain-%d" % round_),
                    )
                    await cs.add_vote(v, "p")
            t0 = time.monotonic()
            await node.stop()
            assert time.monotonic() - t0 < 10.0, "stop did not drain promptly"
            leaked = [
                t
                for t in asyncio.all_tasks()
                if not t.done() and (t.get_name() or "").startswith("cs.ingest")
            ]
            assert not leaked, leaked
        finally:
            vh.release_hub()


class TestBackpressure:
    @pytest.mark.asyncio
    async def test_cancelled_submit_does_not_wedge_the_sequence(self):
        """A caller cancelled while blocked in submit() (backpressure:
        every in-flight permit held) consumes no sequence number —
        later messages still release in order."""
        from tendermint_tpu import testing as tt

        genesis, keys = make_genesis(4)
        cfg = fast_config()
        cfg.ingest_max_inflight = 1  # one permit: trivially saturated
        cfg.timeout_propose_ns = 3_600 * 10**9
        cfg.timeout_commit_ns = 0
        node = Node(genesis, None, config=cfg)
        await node.start()
        cs = node.cs
        idx, key = next(
            (cs.rs.validators.get_by_address(k.pub_key().address())[0], k)
            for k in keys
        )
        bid = tt.make_block_id(b"cancel")
        # deterministically park the single permit inside stage 1: the
        # first message's classify blocks on the gate, so the NEXT
        # submitter is guaranteed stuck on the backpressure edge
        gate = asyncio.Event()
        orig_classify = cs.ingest._classify

        async def gated(mi, ctx=None):
            await gate.wait()
            return await orig_classify(mi, ctx)

        cs.ingest._classify = gated
        loop = asyncio.get_running_loop()
        holder = loop.create_task(
            cs.add_vote(_signed_vote(cs, key, idx, tweak=0, block_id=bid), "p")
        )
        await asyncio.sleep(0)  # holder takes the permit, worker parks
        victim = loop.create_task(
            cs.add_vote(_signed_vote(cs, key, idx, tweak=1, block_id=bid), "p")
        )
        for _ in range(5):
            await asyncio.sleep(0)
        assert holder.done() and not victim.done()
        seq_before = cs.ingest._next_submit
        victim.cancel()
        await asyncio.gather(victim, return_exceptions=True)
        # no seq was consumed: the permit is acquired before the seq,
        # so the cancellation leaves no hole for the release loop
        assert cs.ingest._next_submit == seq_before
        gate.set()
        # a fresh message after the cancellation still gets applied —
        # the release sequence did not wedge
        await cs.add_vote(
            _signed_vote(cs, key, idx, round_=1, block_id=bid), "p"
        )
        await _drain(cs)
        vs = cs.rs.votes.prevotes(1)
        assert vs is not None and vs.get_vote(idx) is not None
        await node.stop()

    @pytest.mark.asyncio
    async def test_unwanted_round_votes_are_not_verified(self):
        """A flood of far-future-round votes for the current height must
        not reach the hub from stage 1 — the sequential path dropped
        them before any signature work (HeightVoteSet's unwanted-round
        DoS guard), and pipelining must not reopen that hole."""
        vh.acquire_hub(max_batch=16, window_ms=1.0, cache_size=256)
        try:
            node, by_index = await _observer(pipeline=True)
            cs = node.cs
            try:
                idx, key = next(iter(by_index.items()))
                for i in range(5):
                    await cs.add_vote(
                        _signed_vote(cs, key, idx, round_=9_000 + i), "flooder"
                    )
                # control: a wanted-round vote IS pre-verified, proving
                # the hub path is live in this test
                await cs.add_vote(_signed_vote(cs, key, idx), "p")
                await _drain(cs)
                stats = cs.ingest.stats
                assert stats["pre_verified"] == 1  # the control only
                assert stats["sig_invalid"] == 0
                assert stats["unverified"] == 5  # deferred, dropped free at apply
                # none of the junk-round votes tallied
                assert cs.rs.votes.prevotes(9_000) is None
            finally:
                await node.stop()
        finally:
            vh.release_hub()

    @pytest.mark.asyncio
    async def test_stopped_pipeline_leaves_metrics_registry(self):
        """aggregate() must stop folding a pipeline once its node
        stopped — stale counters from dead nodes would inflate the
        consensus_ingest_* series forever."""
        from tendermint_tpu.consensus import ingest as ingest_mod

        node, by_index = await _observer(pipeline=True)
        cs = node.cs
        idx, key = next(iter(by_index.items()))
        await cs.add_vote(_signed_vote(cs, key, idx), "p")
        await _drain(cs)
        assert cs.ingest in set(ingest_mod._pipelines)
        await node.stop()
        assert not cs.ingest.started
        assert cs.ingest not in set(ingest_mod._pipelines)


class TestLanes:
    def test_live_packed_ahead_of_backfill(self):
        """With 6 backfill + 2 live queued and max_batch=4, the first
        dispatch must carry BOTH live entries (and only 2 backfill);
        the rest of the backfill follows."""
        h = VerifyHub(max_batch=4, window_ms=5_000.0, cache_size=64, adaptive=False)
        batches = []
        orig = h._verify_batch

        def record(batch):
            batches.append([p.lane for p in batch])
            return orig(batch)

        h._verify_batch = record
        h.start()
        try:
            # hold both double-buffer slots: the dispatcher blocks at its
            # pack-at-last-moment acquire until every submission is
            # queued. Without this, 6 queued backfill (>= max_batch)
            # short-circuits the window wait and the packer can fire
            # between the two live submits under machine load.
            h._slots.acquire()
            h._slots.acquire()
            futs = [
                h.submit_nowait(pk, m, s, lane=LANE_BACKFILL)
                for pk, m, s in _items(6, b"bf")
            ]
            futs += [
                h.submit_nowait(pk, m, s, lane=LANE_LIVE)
                for pk, m, s in _items(2, b"live")
            ]
            h._slots.release()
            h._slots.release()
            h.flush()
            for f in futs:
                assert f.result(10.0) is True
        finally:
            h.stop()
        assert batches[0] == ["live", "live", "backfill", "backfill"], batches
        assert batches[1] == ["backfill"] * 4, batches
        s = h.stats()
        assert s["lane_live_dispatched"] == 2
        assert s["lane_backfill_dispatched"] == 6

    def test_unknown_lane_rejected(self):
        h = VerifyHub(max_batch=4, window_ms=1.0, cache_size=4)
        h.start()
        try:
            (pk, m, s), = _items(1, b"badlane")
            with pytest.raises(ValueError, match="unknown verify lane"):
                h.submit_nowait(pk, m, s, lane="backfil")
        finally:
            h.stop()

    def test_live_coalesce_promotes_backfill_entry(self):
        h = VerifyHub(max_batch=64, window_ms=5_000.0, cache_size=64, adaptive=False)
        h.start()
        try:
            (pk, m, s), = _items(1, b"promote")
            f1 = h.submit_nowait(pk, m, s, lane=LANE_BACKFILL)
            f2 = h.submit_nowait(pk, m, s, lane=LANE_LIVE)
            st = h.stats()
            assert st["lane_promotions"] == 1
            assert st["lane_live_queued"] == 1
            assert st["lane_backfill_queued"] == 0
            h.flush()
            assert f1.result(10.0) is True and f2.result(10.0) is True
            # the single dispatched sig is accounted to the LIVE lane
            assert h.stats()["lane_live_dispatched"] == 1
        finally:
            h.stop()

    def test_backfill_saturation_does_not_starve_live(self):
        """Acceptance: with block-sync backfill saturating the hub (a
        deep pending backlog), live verify p50 stays within 2x of its
        unloaded value (plus a small epsilon for thread-handoff noise
        on loaded CI machines) — live entries pack ahead of backfill in
        every dispatch instead of queueing FIFO behind thousands of
        catch-up signatures."""

        def live_p50(h, samples, tag):
            lat = []
            for pk, m, s in _items(samples, tag):
                t0 = time.perf_counter()
                assert h.verify_sync(pk, m, s, lane=LANE_LIVE) is True
                lat.append(time.perf_counter() - t0)
            return statistics.median(lat)

        h = VerifyHub(max_batch=64, window_ms=1.0, cache_size=0)
        # deterministic device service time: this is a SCHEDULER test
        # (queueing, lane packing, slot depth), so host-crypto variance
        # must not decide it — every batch costs a fixed 3ms
        h._verify_batch = lambda batch: (time.sleep(0.003), [True] * len(batch))[1]
        h.start()
        try:
            p50_unloaded = live_p50(h, 30, b"unloaded")

            # saturation: a deep backlog of pending backfill
            # verifications (the block-sync range-replay shape)
            pub = Ed25519PrivKey(b"\x31" * 32).pub_key()
            for i in range(20_000):
                h.submit_nowait(
                    pub, b"sat-%d" % i, b"\x00" * 64, lane=LANE_BACKFILL
                )
            p50_loaded = live_p50(h, 30, b"loaded")
            s = h.stats()
            assert s["lane_backfill_queued"] > 0, (
                "backfill backlog drained before the measurement ended — "
                "not a saturation test; raise the backlog size"
            )
            assert s["lane_backfill_dispatched"] > 0, s
            assert p50_loaded <= 2 * p50_unloaded + 0.005, (
                f"live p50 {p50_loaded*1e3:.2f}ms vs unloaded "
                f"{p50_unloaded*1e3:.2f}ms under backfill saturation"
            )
        finally:
            h.stop()


class TestMetricsFold:
    @pytest.mark.asyncio
    async def test_lane_and_ingest_series_fold_at_render(self):
        from tendermint_tpu import testing as tt
        from tendermint_tpu.libs.metrics import NodeMetrics

        hub = vh.acquire_hub(max_batch=64, window_ms=1.0)
        try:
            node, by_index = await _observer(pipeline=True)
            cs = node.cs
            bid = tt.make_block_id(b"metrics")
            for idx, key in sorted(by_index.items()):
                await cs.add_vote(_signed_vote(cs, key, idx, block_id=bid), "p")
            await _drain(cs)
            # duplicate of an applied vote -> dedup drop; and one
            # backfill submission for the lane mix
            await cs.add_vote(_signed_vote(cs, by_index[0], 0, block_id=bid), "p")
            await _drain(cs)
            (pk, m, s), = _items(1, b"bf-metric")
            assert hub.verify_sync(pk, m, s, lane=LANE_BACKFILL) is True

            out = NodeMetrics().render()
            def series(name):
                for line in out.splitlines():
                    if line.startswith(name + "{") or line.startswith(name + " "):
                        yield line
            live = [l for l in series("tendermint_tpu_verifyhub_lane_sigs_dispatched") if 'lane="live"' in l]
            backfill = [l for l in series("tendermint_tpu_verifyhub_lane_sigs_dispatched") if 'lane="backfill"' in l]
            assert live and float(live[0].split()[-1]) >= 4, live
            assert backfill and float(backfill[0].split()[-1]) >= 1, backfill
            assert 'tendermint_tpu_verifyhub_lane_submitted{lane="live"}' in out
            sub = [l for l in series("tendermint_tpu_consensus_ingest_submitted")]
            assert sub and float(sub[0].split()[-1]) >= 5, sub
            dd = [l for l in series("tendermint_tpu_consensus_ingest_dedup_drops")]
            assert dd and float(dd[0].split()[-1]) >= 1, dd
            pv = [l for l in series("tendermint_tpu_consensus_ingest_pre_verified")]
            assert pv and float(pv[0].split()[-1]) >= 4, pv
            assert "tendermint_tpu_consensus_ingest_verify_latency_seconds_count" in out
            assert "tendermint_tpu_consensus_ingest_reorder_wait_seconds_count" in out
            await node.stop()
        finally:
            vh.release_hub()
