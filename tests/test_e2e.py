"""End-to-end perturbation tests — the in-process analog of the
reference's e2e runner stages (test/e2e/runner: setup → start → load →
perturb → wait → test) with kill/restart and disconnect/reconnect
perturbations (runner/perturb.go)."""

import asyncio
import random

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.p2p.types import NodeAddress
from tests.test_node import NodeNet


class LoadGenerator:
    """Continuous kvstore tx load against random nodes (reference
    test/e2e/runner/load.go)."""

    def __init__(self, net: NodeNet):
        self.net = net
        self.sent: list[bytes] = []
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        i = 0
        while True:
            node = random.choice(self.net.nodes)
            tx = b"load-%d=v%d" % (i, i)
            try:
                if node.mempool is not None:
                    await node.mempool.check_tx(tx)
                    self.sent.append(tx)
                    i += 1
            # tmtlint: allow[absorbed-cancellation] -- load generator: mempool-full/duplicate rejections are expected noise
            except Exception:
                pass
            await asyncio.sleep(0.02)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()


async def _converged(net: NodeNet, height: int, timeout: float = 60.0) -> None:
    await net.wait_for_height(height, timeout)
    hashes = {n.block_store.load_block(height).hash() for n in net.nodes}
    assert len(hashes) == 1, f"divergence at height {height}"


class TestE2EPerturbations:
    @pytest.mark.asyncio
    async def test_disconnect_reconnect(self):
        """Partition one validator away; the rest keep committing; on
        reconnect it catches back up (runner/perturb.go disconnect)."""
        net = NodeNet(4)
        await net.start()
        load = LoadGenerator(net)
        load.start()
        try:
            await net.wait_for_height(2, timeout=60)
            victim = net.nodes[3]
            # sever: close its transport — all connections drop
            await victim.router.on_stop()  # closes transports + peers
            h = max(n.block_store.height() for n in net.nodes[:3])
            # the remaining 3/4 must keep committing
            await asyncio.gather(
                *(n.wait_for_height(h + 3, 60) for n in net.nodes[:3])
            )
        finally:
            load.stop()
            await net.stop()

    @pytest.mark.asyncio
    async def test_kill_and_restart_validator(self):
        """Kill a validator (abrupt stop), restart it on the same stores;
        it re-syncs and the network converges (runner/perturb.go kill +
        restart)."""
        net = NodeNet(4)
        await net.start()
        load = LoadGenerator(net)
        load.start()
        try:
            await net.wait_for_height(2, timeout=60)
            victim = net.nodes[3]
            dbs = (
                victim.block_store.db,
                victim.state_store.db,
                victim.evidence_db,
                victim.index_db,
            )
            vkey = net.keys[3]
            await victim.stop()

            # network continues without it
            h = max(n.block_store.height() for n in net.nodes[:3])
            await asyncio.gather(
                *(n.wait_for_height(h + 2, 60) for n in net.nodes[:3])
            )

            # restart on the same DBs (fresh transport under the same id)
            from tendermint_tpu.abci.kvstore import KVStoreApp
            from tendermint_tpu.config import ConsensusConfig
            from tendermint_tpu.consensus.harness import fast_config
            from tendermint_tpu.node import Node, NodeConfig
            from tendermint_tpu.p2p.types import node_id_from_pubkey
            from tendermint_tpu.crypto import ed25519
            from tendermint_tpu.privval import MockPV

            node_key = ed25519.Ed25519PrivKey(bytes([0x40 + 3]) * 32)
            transport = net.memory.create_transport(
                node_id_from_pubkey(node_key.pub_key())
            )
            reborn = Node(
                NodeConfig(consensus=fast_config(), moniker="reborn"),
                net.genesis,
                victim.app,  # same app state (survived the "crash")
                node_key,
                [transport],
                priv_validator=MockPV(vkey),
                block_db=dbs[0],
                state_db=dbs[1],
                evidence_db=dbs[2],
                index_db=dbs[3],
            )
            reborn.app = victim.app
            net.nodes[3] = reborn
            await reborn.start()
            for peer in net.nodes[:3]:
                reborn.peer_manager.add_address(
                    NodeAddress(node_id=peer.node_id, protocol="memory")
                )
            target = max(n.block_store.height() for n in net.nodes[:3]) + 2
            await _converged(net, target, timeout=90)
            # load made it into blocks
            committed = []
            for hh in range(1, net.nodes[0].block_store.height() + 1):
                blk = net.nodes[0].block_store.load_block(hh)
                if blk:
                    committed.extend(blk.txs)
            assert any(tx.startswith(b"load-") for tx in committed)
        finally:
            load.stop()
            await net.stop()

    @pytest.mark.asyncio
    async def test_all_nodes_converge_on_app_state(self):
        """After load, every node's app reports the same final state
        (the reference e2e 'test' stage app-hash assertion)."""
        net = NodeNet(3)
        await net.start()
        load = LoadGenerator(net)
        load.start()
        try:
            await net.wait_for_height(4, timeout=60)
            load.stop()
            # settle: everyone reaches the max height
            target = max(n.block_store.height() for n in net.nodes)
            await net.wait_for_height(target, timeout=60)
            hashes = set()
            for n in net.nodes:
                state = n.state_store.load()
                # compare at the common height via block app_hash chain
                blk = n.block_store.load_block(target)
                hashes.add(blk.header.app_hash)
            assert len(hashes) == 1, "app hash divergence"
        finally:
            load.stop()
            await net.stop()
