"""P2P stack tests (modeled on reference internal/p2p/router_test.go,
conn/secret_connection_test.go, peermanager_test.go)."""

import asyncio

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p.memory import MemoryNetwork
from tendermint_tpu.p2p.peermanager import PeerManager, PeerStatus
from tendermint_tpu.p2p.secret import SecretStream
from tendermint_tpu.p2p.tcp import TCPTransport
from tendermint_tpu.p2p.testing import TestNetwork
from tendermint_tpu.p2p.types import (
    Envelope,
    NodeAddress,
    NodeInfo,
    PeerError,
    node_id_from_pubkey,
)


class TestNodeAddress:
    def test_parse_roundtrip(self):
        a = NodeAddress.parse("tcp://abcd1234@127.0.0.1:26656")
        assert a.node_id == "abcd1234"
        assert a.host == "127.0.0.1" and a.port == 26656
        assert NodeAddress.parse(str(a)) == a
        m = NodeAddress.parse("memory:ff00")
        assert m.protocol == "memory" and m.node_id == "ff00"

    def test_node_info_roundtrip(self):
        ni = NodeInfo(
            node_id="ab" * 20, network="chain-x", listen_addr="tcp://1.2.3.4:1",
            channels=bytes([0x20, 0x30]), moniker="m",
        )
        assert NodeInfo.decode(ni.encode()) == ni
        other = NodeInfo(node_id="cd" * 20, network="chain-y")
        assert ni.compatible_with(other) is not None


class TestSecretConnection:
    @pytest.mark.asyncio
    async def test_handshake_and_transfer(self):
        """Full STS handshake over a real socketpair; large messages span
        many sealed frames."""
        server_priv = ed25519.Ed25519PrivKey.generate()
        client_priv = ed25519.Ed25519PrivKey.generate()
        results = {}

        async def on_client(reader, writer):
            s = SecretStream(reader, writer)
            peer = await s.handshake(server_priv)
            results["server_saw"] = peer.bytes()
            data = await s.read_exactly(5000)
            await s.write_all(data[::-1])

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        s = SecretStream(reader, writer)
        peer = await s.handshake(client_priv)
        assert peer.bytes() == server_priv.pub_key().bytes()
        payload = bytes(range(256)) * 20  # 5120... use exactly 5000
        payload = payload[:5000]
        await s.write_all(payload)
        echoed = await s.read_exactly(5000)
        assert echoed == payload[::-1]
        assert results["server_saw"] == client_priv.pub_key().bytes()
        s.close()
        server.close()

    @pytest.mark.asyncio
    async def test_tampered_frame_rejected(self):
        server_priv = ed25519.Ed25519PrivKey.generate()
        client_priv = ed25519.Ed25519PrivKey.generate()

        async def on_client(reader, writer):
            s = SecretStream(reader, writer)
            await s.handshake(server_priv)
            # send a frame, then corrupt the next one at the raw socket
            await s.write_all(b"ok")
            writer.write(b"\x00" * 1042)  # garbage sealed frame
            await writer.drain()

        server = await asyncio.start_server(on_client, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        s = SecretStream(reader, writer)
        await s.handshake(client_priv)
        assert await s.read_exactly(2) == b"ok"
        with pytest.raises(Exception):
            await s.read_exactly(1)
        s.close()
        server.close()


class TestTCPTransport:
    @pytest.mark.asyncio
    async def test_dial_handshake_exchange(self):
        priv_a, priv_b = (ed25519.Ed25519PrivKey.generate() for _ in range(2))
        id_a = node_id_from_pubkey(priv_a.pub_key())
        id_b = node_id_from_pubkey(priv_b.pub_key())
        info_a = NodeInfo(node_id=id_a, network="c")
        info_b = NodeInfo(node_id=id_b, network="c")

        ta, tb = TCPTransport(), TCPTransport()
        await tb.listen("127.0.0.1:0")
        host, port = tb.endpoint().rsplit(":", 1)

        async def server():
            conn = await tb.accept()
            peer = await conn.handshake(info_b, priv_b)
            assert peer.node_id == id_a
            ch, data = await conn.receive_message()
            await conn.send_message(ch, data.upper())
            return conn

        stask = asyncio.create_task(server())
        conn = await ta.dial(
            NodeAddress(node_id=id_b, host=host, port=int(port))
        )
        peer = await conn.handshake(info_a, priv_a)
        assert peer.node_id == id_b
        await conn.send_message(0x42, b"hello")
        ch, data = await conn.receive_message()
        assert (ch, data) == (0x42, b"HELLO")
        sconn = await stask
        await conn.close()
        await sconn.close()
        await ta.close()
        await tb.close()


class TestPeerManager:
    def test_dial_retry_backoff(self):
        pm = PeerManager("self", min_retry_time=10.0)
        addr = NodeAddress(node_id="peer1", protocol="memory")
        pm.add_address(addr)
        assert pm.try_dial_next() == addr
        pm.dial_failed(addr)
        assert pm.try_dial_next() is None  # backoff
        assert pm.addresses("peer1") == [addr]

    def test_connected_limits_and_updates(self):
        pm = PeerManager("self", max_connected=1, max_connected_upper=2)
        sub = pm.subscribe()
        assert pm.connected("p1", inbound=True)
        assert not pm.connected("p1", inbound=True)  # duplicate
        assert pm.connected("p2", inbound=True)  # surplus allowed
        assert not pm.connected("p3", inbound=True)  # over upper
        assert pm.evict_candidate() is not None
        up = sub.get_nowait()
        assert up.status == PeerStatus.UP
        pm.disconnected("p1")
        assert pm.connected_peers() == ["p2"]

    def test_error_scoring(self):
        pm = PeerManager("self")
        pm.connected("p1", inbound=True)
        pm.errored(PeerError("p1", "bad vote"))
        assert pm._peers["p1"].score < 1

    def test_ban_promotion_quarantines_dialing(self, monkeypatch):
        """A ban-flagged PeerError (blocksync repeated-timeout bans)
        promotes into a dial quarantine with escalating cooldown — the
        peer is neither redialed nor re-accepted until it expires."""
        import time as _time

        now = {"t": 1000.0}
        monkeypatch.setattr(_time, "monotonic", lambda: now["t"])
        pm = PeerManager("self")
        addr = NodeAddress(node_id="badpeer", protocol="memory")
        pm.add_address(addr)
        assert pm.try_dial_next() == addr

        pm.connected("badpeer", inbound=False)
        pm.errored(PeerError("badpeer", "blocksync: repeated request timeouts", ban=True))
        pm.disconnected("badpeer")
        assert pm.is_banned("badpeer")
        assert pm.try_dial_next() is None  # quarantined: no redial
        assert not pm.connected("badpeer", inbound=True)  # nor inbound
        info = pm._peers["badpeer"]
        # connected() granted +1 before the ban's -20 landed
        assert info.bans == 1 and info.score <= 1 - PeerManager.BAN_SCORE_PENALTY

        # cooldown expires -> dialable again
        now["t"] += PeerManager.BAN_BASE_COOLDOWN + 1
        assert not pm.is_banned("badpeer")
        assert pm.try_dial_next() == addr

        # second ban doubles the quarantine
        pm.connected("badpeer", inbound=False)
        pm.errored(PeerError("badpeer", "again", ban=True))
        pm.disconnected("badpeer")
        now["t"] += PeerManager.BAN_BASE_COOLDOWN + 1
        assert pm.is_banned("badpeer")  # 2x cooldown still running
        now["t"] += PeerManager.BAN_BASE_COOLDOWN
        assert not pm.is_banned("badpeer")

    def test_non_ban_error_does_not_quarantine(self):
        pm = PeerManager("self")
        pm.add_address(NodeAddress(node_id="p1", protocol="memory"))
        pm.errored(PeerError("p1", "malformed message"))
        assert not pm.is_banned("p1")
        assert pm.try_dial_next() is not None


class TestRouterNetwork:
    @pytest.mark.asyncio
    async def test_broadcast_and_point_to_point(self):
        net = TestNetwork(3)
        chans = net.open_channel(0x77, name="test")
        await net.start()
        try:
            a, b, c = net.nodes
            # broadcast from a reaches b and c
            await chans[a.node_id].send(
                Envelope(channel_id=0x77, message=b"hi-all", broadcast=True)
            )
            for node in (b, c):
                env = await asyncio.wait_for(chans[node.node_id].receive(), 5)
                assert env.message == b"hi-all"
                assert env.from_ == a.node_id
            # direct message b -> c only
            await chans[b.node_id].send(
                Envelope(channel_id=0x77, message=b"direct", to=c.node_id)
            )
            env = await asyncio.wait_for(chans[c.node_id].receive(), 5)
            assert env.message == b"direct" and env.from_ == b.node_id
            assert chans[a.node_id].in_q.empty()
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_peer_error_disconnects(self):
        net = TestNetwork(2)
        chans = net.open_channel(0x77, name="test")
        await net.start()
        try:
            a, b = net.nodes
            sub = a.peer_manager.subscribe()
            await chans[a.node_id].error(PeerError(b.node_id, "misbehaved"))
            upd = await asyncio.wait_for(sub.get(), 5)
            assert upd.status == PeerStatus.DOWN
            assert b.node_id not in a.peer_manager.connected_peers()
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_codec_and_malformed_message(self):
        import json

        net = TestNetwork(2)
        chans = net.open_channel(
            0x50,
            name="json",
            encode=lambda m: json.dumps(m).encode(),
            decode=lambda b: json.loads(b.decode()),
        )
        await net.start()
        try:
            a, b = net.nodes
            await chans[a.node_id].send(
                Envelope(channel_id=0x50, message={"x": 1}, broadcast=True)
            )
            env = await asyncio.wait_for(chans[b.node_id].receive(), 5)
            assert env.message == {"x": 1}
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_reconnect_after_disconnect(self):
        """Dropped peers are redialed (peer manager retry loop)."""
        net = TestNetwork(2)
        net.open_channel(0x77, name="test")
        await net.start()
        try:
            a, b = net.nodes
            sub = a.peer_manager.subscribe()
            # force-disconnect from a's side
            await a.router._disconnect_peer(b.node_id)
            # a should redial b (it has its address) and come back up
            deadline = asyncio.get_running_loop().time() + 10
            while b.node_id not in a.peer_manager.connected_peers():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
        finally:
            await net.stop()


class TestPex:
    @pytest.mark.asyncio
    async def test_address_discovery(self):
        """Node C knows only A; PEX teaches it B's address and the mesh
        completes (reference pex/reactor_test.go flavor), using full
        nodes so the pex reactor is wired."""
        from tests.test_node import NodeNet
        from tendermint_tpu.p2p import pex as pexmod

        orig = pexmod.REQUEST_INTERVAL
        pexmod.REQUEST_INTERVAL = 0.2
        try:
            net = NodeNet(3)
            await net.start(connect=False)
            a, b, c = net.nodes
            # A knows B; C knows only A
            a.peer_manager.add_address(
                NodeAddress(node_id=b.node_id, protocol="memory")
            )
            c.peer_manager.add_address(
                NodeAddress(node_id=a.node_id, protocol="memory")
            )
            deadline = asyncio.get_running_loop().time() + 20
            want = {a.node_id, b.node_id}
            while set(c.peer_manager.connected_peers()) != want:
                assert asyncio.get_running_loop().time() < deadline, (
                    f"pex discovery incomplete: {c.peer_manager.connected_peers()}"
                )
                await asyncio.sleep(0.1)
        finally:
            pexmod.REQUEST_INTERVAL = orig
            await net.stop()

    def test_pex_codec(self):
        from tendermint_tpu.p2p import pex as pexmod

        req = pexmod.PexRequest()
        assert pexmod.decode_message(pexmod.encode_message(req)) == req
        res = pexmod.PexResponse(("memory:aabb", "tcp://cc@1.2.3.4:5"))
        assert pexmod.decode_message(pexmod.encode_message(res)) == res
