"""Differential tests: JAX field/curve/verify kernel vs the pure-Python
ed25519 oracle (crypto/ed25519_math.py). Runs on the CPU backend in CI; the
same code compiles for TPU unchanged."""

import secrets

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519, ed25519_math as em
from tendermint_tpu.crypto.tpu import field as F
from tendermint_tpu.crypto.tpu import curve as C
from tendermint_tpu.crypto.tpu.verify import prepare_batch, verify_batch

import jax.numpy as jnp


def rand_fe(n=4):
    return [secrets.randbelow(F.P_INT) for _ in range(n)]


def to_batch(vals):
    return jnp.asarray(np.stack([F.int_to_limbs(v) for v in vals]))


def test_field_mul_matches_bigint():
    a_vals, b_vals = rand_fe(8), rand_fe(8)
    out = F.mul(to_batch(a_vals), to_batch(b_vals))
    out = np.asarray(out)
    for i in range(8):
        assert F.limbs_to_int(out[i]) == a_vals[i] * b_vals[i] % F.P_INT
        assert out[i].max() < 2**9  # carry bound invariant


def test_field_chained_ops():
    a_vals, b_vals = rand_fe(4), rand_fe(4)
    a, b = to_batch(a_vals), to_batch(b_vals)
    # (a-b)*(a+b) == a^2 - b^2
    lhs = F.mul(F.sub(a, b), F.add(a, b))
    rhs = F.sub(F.square(a), F.square(b))
    assert bool(F.eq(lhs, rhs).all())
    for i in range(4):
        expect = (a_vals[i] ** 2 - b_vals[i] ** 2) % F.P_INT
        assert F.limbs_to_int(np.asarray(lhs)[i]) == expect


def test_field_canonical():
    vals = [0, 1, 19, F.P_INT - 1, F.P_INT, F.P_INT + 5, 2**255 - 1]
    # feed NON-canonical limb forms: add p again via limb arithmetic
    arrs = []
    for v in vals:
        limbs = F.int_to_limbs(v % F.P_INT).astype(np.int32)
        arrs.append(limbs + F.P_LIMBS)  # limbs ≤ 510, value v + p
    out = np.asarray(F.canonical(jnp.asarray(np.stack(arrs))))
    for i, v in enumerate(vals):
        assert F.limbs_to_int(out[i]) == v % F.P_INT
        assert (out[i] == F.int_to_limbs(v % F.P_INT)).all()


def test_field_is_zero_and_parity():
    a = to_batch([0, 1, F.P_INT - 1, 2])
    z = np.asarray(F.is_zero(a))
    assert list(z) == [True, False, False, False]
    par = np.asarray(F.parity(a))
    assert list(par) == [0, 1, (F.P_INT - 1) & 1, 0]


def test_pow22523():
    vals = rand_fe(2)
    out = np.asarray(F.pow22523(to_batch(vals)))
    e = (F.P_INT - 5) // 8  # 2^252 - 3
    for i, v in enumerate(vals):
        assert F.limbs_to_int(out[i]) == pow(v, e, F.P_INT)


def _point_to_ints(p, i):
    x = F.limbs_to_int(np.asarray(p.x)[i])
    y = F.limbs_to_int(np.asarray(p.y)[i])
    z = F.limbs_to_int(np.asarray(p.z)[i])
    zi = pow(z, F.P_INT - 2, F.P_INT)
    return x * zi % F.P_INT, y * zi % F.P_INT


def test_point_add_double_vs_oracle():
    ks = [1, 2, 5, 12345]
    pts = [em.BASE.scalar_mul(k) for k in ks]
    xs = to_batch([p.X * pow(p.Z, F.P_INT - 2, F.P_INT) % F.P_INT for p in pts])
    ys = to_batch([p.Y * pow(p.Z, F.P_INT - 2, F.P_INT) % F.P_INT for p in pts])
    P = C.Point(xs, ys, jnp.broadcast_to(jnp.asarray(F.ONE), xs.shape), F.mul(xs, ys))
    D = C.point_double(P)
    S = C.point_add(P, C.base_point((4,)))
    for i, k in enumerate(ks):
        expect_d = em.BASE.scalar_mul(2 * k)
        ex, ey = _point_to_ints(D, i)
        assert (ex, ey) == (
            expect_d.X * pow(expect_d.Z, F.P_INT - 2, F.P_INT) % F.P_INT,
            expect_d.Y * pow(expect_d.Z, F.P_INT - 2, F.P_INT) % F.P_INT,
        )
        expect_s = em.BASE.scalar_mul(k + 1)
        sx, sy = _point_to_ints(S, i)
        zi = pow(expect_s.Z, F.P_INT - 2, F.P_INT)
        assert (sx, sy) == (expect_s.X * zi % F.P_INT, expect_s.Y * zi % F.P_INT)


def test_point_add_identity_complete():
    idp = C.identity((2,))
    bp = C.base_point((2,))
    out = C.point_add(idp, bp)
    assert bool(C.point_eq(out, bp).all())
    assert bool(C.is_identity(C.point_add(idp, idp)).all())


def test_decompress_vs_oracle():
    ks = [1, 2, 7, 99, 123456789]
    encs = [em.BASE.scalar_mul(k).compress() for k in ks]
    # add one invalid encoding (y with no square root) and the identity
    encs.append((1).to_bytes(32, "little"))  # identity
    bad = bytearray(32)
    bad[0] = 2  # y=2 — happens to be off-curve for ed25519
    encs.append(bytes(bad))
    arr = jnp.asarray(
        np.stack([np.frombuffer(e, np.uint8).astype(np.int32) for e in encs])
    )
    pt, valid = C.decompress(arr)
    valid = np.asarray(valid)
    for i, k in enumerate(ks):
        assert valid[i]
        ex, ey = _point_to_ints(pt, i)
        oracle = em.Point.decompress(encs[i])
        zi = pow(oracle.Z, F.P_INT - 2, F.P_INT)
        assert (ex, ey) == (oracle.X * zi % F.P_INT, oracle.Y * zi % F.P_INT)
    assert valid[len(ks)]  # identity decompresses
    oracle_bad = em.Point.decompress(encs[-1])
    assert bool(valid[-1]) == (oracle_bad is not None)


def test_verify_batch_valid_and_invalid():
    n = 16
    keys = [ed25519.Ed25519PrivKey.generate() for _ in range(n)]
    msgs = [secrets.token_bytes(40 + i) for i in range(n)]
    items = []
    expected = []
    for i, (k, m) in enumerate(zip(keys, msgs)):
        sig = k.sign(m)
        if i % 5 == 1:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])  # corrupt s
            expected.append(False)
        elif i % 5 == 3:
            m = m + b"tampered"
            expected.append(False)
        else:
            expected.append(True)
        items.append((k.pub_key().bytes(), m, sig))
    bitmap = verify_batch(items)
    assert list(bitmap) == expected


def test_verify_batch_noncanonical_s_rejected():
    k = ed25519.Ed25519PrivKey.generate()
    m = b"msg"
    sig = bytearray(k.sign(m))
    s = int.from_bytes(sig[32:], "little")
    sig[32:] = (s + em.L).to_bytes(32, "little")
    bitmap = verify_batch([(k.pub_key().bytes(), m, bytes(sig))])
    assert not bitmap[0]


def test_verify_batch_zip215_edge_cases():
    # identity pubkey (small-order) with s=0, R=identity: 0*B == R + k*A holds
    # for any k iff R and k*A cancel; with A=R=identity and s=0 the cofactored
    # equation holds — ZIP-215 accepts.
    ident = (1).to_bytes(32, "little")
    sig = ident + (0).to_bytes(32, "little")
    bitmap = verify_batch([(ident, b"anything", sig)])
    assert em.verify_zip215(ident, b"anything", sig)
    assert bitmap[0]


def test_tpu_batch_verifier_interface():
    from tendermint_tpu.crypto.tpu.verify import TPUBatchVerifier
    from tendermint_tpu.crypto import secp256k1

    bv = TPUBatchVerifier()
    eds = [ed25519.Ed25519PrivKey.generate() for _ in range(3)]
    sec = secp256k1.Secp256k1PrivKey.generate()
    for i, k in enumerate(eds):
        m = f"m{i}".encode()
        bv.add(k.pub_key(), m, k.sign(m))
    bv.add(sec.pub_key(), b"sm", sec.sign(b"sm"))
    ok, bits = bv.verify()
    assert ok and bits == [True] * 4

    bv2 = TPUBatchVerifier()
    bv2.add(eds[0].pub_key(), b"a", eds[0].sign(b"b"))
    ok, bits = bv2.verify()
    assert not ok and bits == [False]


# -- batch-equation (MSM) kernel ---------------------------------------------


def _signed_items(n, n_vals=8):
    from tendermint_tpu import testing as tt

    chain_id = "eq-chain"
    vals, keys = tt.make_validator_set(n_vals)
    items = []
    h = 1
    while len(items) < n:
        bid = tt.make_block_id(b"eq%d" % h)
        c = tt.make_commit(chain_id, h, 0, bid, vals, keys)
        for i, cs in enumerate(c.signatures):
            if len(items) >= n:
                break
            items.append(
                (
                    vals.validators[i].pub_key.bytes(),
                    c.vote_sign_bytes(chain_id, i),
                    cs.signature,
                )
            )
        h += 1
    return items


def test_msm_matches_oracle():
    """MSM over random points/scalars vs the integer oracle."""
    import numpy as np
    import tendermint_tpu.crypto.ed25519_math as em
    from tendermint_tpu.crypto.tpu import curve, field as F, msm

    rng = np.random.default_rng(7)
    n = 5
    pts_int = [em.BASE.scalar_mul(int(k)) for k in rng.integers(1, 2**30, n)]
    scalars = [int.from_bytes(rng.bytes(32), "little") % em.L for _ in range(n)]

    # oracle
    want = em.Point.identity()
    for p, s in zip(pts_int, scalars):
        want = want.add(p.scalar_mul(s))

    # device: build affine limb points + digit rows
    import jax.numpy as jnp

    def to_limb_point(p):
        zinv = pow(p.Z, em.P - 2, em.P)
        x, y = p.X * zinv % em.P, p.Y * zinv % em.P
        return (
            F.int_to_limbs(x),
            F.int_to_limbs(y),
            F.int_to_limbs(1),
            F.int_to_limbs(x * y % em.P),
        )

    comps = list(zip(*(to_limb_point(p) for p in pts_int)))
    points = curve.Point(*(jnp.asarray(np.stack(c)) for c in comps))
    sc_bytes = np.stack(
        [
            np.frombuffer(s.to_bytes(32, "little"), np.uint8).astype(np.int32)
            for s in scalars
        ]
    )
    digit_rows = jnp.asarray(np.ascontiguousarray(sc_bytes.T))
    got = msm.msm(points, digit_rows)
    gx, gy, gz = (
        F.limbs_to_int(np.asarray(c)) for c in (got.x, got.y, got.z)
    )
    zinv = pow(gz, em.P - 2, em.P)
    wzinv = pow(want.Z, em.P - 2, em.P)
    assert gx * zinv % em.P == want.X * wzinv % em.P
    assert gy * zinv % em.P == want.Y * wzinv % em.P


def test_verify_batch_eq_happy_and_fallback():
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    items = _signed_items(20)
    out = verify_batch_eq(items)
    assert out.all() and len(out) == 20

    bad = list(items)
    p, m, s = bad[11]
    bad[11] = (p, m, s[:20] + bytes([s[20] ^ 1]) + s[21:])
    out = verify_batch_eq(bad)
    assert not out[11] and out.sum() == 19


def test_verify_batch_eq_malformed_entries():
    from tendermint_tpu.crypto.tpu.verify import L as ELL, verify_batch_eq

    items = _signed_items(8)
    items[2] = (items[2][0], items[2][1], items[2][2][:32] + (ELL + 9).to_bytes(32, "little"))
    items[5] = (b"\x01" * 31, items[5][1], items[5][2])  # short pubkey
    out = verify_batch_eq(items)
    assert not out[2] and not out[5] and out.sum() == 6


def test_verify_batch_eq_bad_shared_pubkey():
    """A-side grouping: one undecompressable pubkey shared by several
    signatures must fail exactly those rows (the bitmap gathers the
    per-GROUP decompression verdict through gidx)."""
    from tendermint_tpu.crypto.tpu.verify import verify_batch_eq

    from tendermint_tpu.crypto.ed25519_math import Point as IntPoint

    items = _signed_items(20, n_vals=4)  # each key signs ~5 times
    # find a y with no curve point (oracle-checked, deterministic)
    bad_key = next(
        k
        for b0 in range(256)
        for k in [bytes([b0]) + b"\x02" * 31]
        if IntPoint.decompress(k) is None
    )
    bad_rows = [i for i, it in enumerate(items) if it[0] == items[1][0]]
    items = [
        (bad_key, m, s) if p == items[1][0] else (p, m, s)
        for (p, m, s) in items
    ]
    out = verify_batch_eq(items)
    assert len(bad_rows) >= 2
    for i in range(20):
        assert out[i] == (i not in bad_rows)


def test_verify_resolved_sr25519():
    """sr25519 signatures route through the same MSM kernel."""
    from tendermint_tpu.crypto import sr25519 as sr
    from tendermint_tpu.crypto.tpu.verify import resolve_sr25519, verify_resolved

    entries = []
    for i in range(6):
        priv = sr.Sr25519PrivKey(bytes([0x30 + i]) * 32)
        msg = b"sr-batch-%d" % i
        sig = priv.sign(msg)
        entries.append(resolve_sr25519(priv.pub_key().bytes(), msg, sig))
    out = verify_resolved(entries)
    assert out.all()

    # tamper one -> per-sig fallback pinpoints it
    priv = sr.Sr25519PrivKey(b"\x55" * 32)
    sig = bytearray(priv.sign(b"x"))
    sig[3] ^= 1
    entries[4] = resolve_sr25519(priv.pub_key().bytes(), b"x", bytes(sig))
    out = verify_resolved(entries)
    assert not out[4] and out.sum() == 5


def test_pallas_field_mul_matches_gemm():
    """The Pallas VMEM convolution kernel (interpret mode on CPU) agrees
    with the GEMM formulation across random partially-reduced inputs."""
    import numpy as np

    from tendermint_tpu.crypto.tpu import field as F
    from tendermint_tpu.crypto.tpu import pallas_field as PF

    rng = np.random.default_rng(11)
    a = rng.integers(0, 512, (21, 32), dtype=np.int32)
    b = rng.integers(0, 512, (21, 32), dtype=np.int32)
    want = np.asarray(F.mul(a, b))
    got = np.asarray(PF.mul(a, b, interpret=True))
    for i in range(len(a)):
        assert F.limbs_to_int(want[i]) == F.limbs_to_int(got[i])


@pytest.mark.slow  # interpret-mode Pallas on CPU: a 254-multiply
# chain per element — minutes-to-hours on small hosts, far past the
# tier-1 budget. The on-device A/B probe cross-checks the same
# kernels against the XLA formulation on real TPU at startup.
def test_pallas_pow22523_matches_xla_chain():
    """The fused VMEM pow22523 kernel (interpret mode on CPU) agrees with
    the portable XLA addition chain — and with exact integer math."""
    import numpy as np

    from tendermint_tpu.crypto.tpu import field as F
    from tendermint_tpu.crypto.tpu import pallas_field as PF

    rng = np.random.default_rng(13)
    z = rng.integers(0, 256, (9, 32), dtype=np.int32)
    want = np.asarray(F._pow22523_chain(z))
    got = np.asarray(PF.pow22523(z, interpret=True))
    for i in range(len(z)):
        zi = F.limbs_to_int(z[i])
        expect = pow(zi, 2**252 - 3, F.P_INT)
        assert F.limbs_to_int(want[i]) == expect
        assert F.limbs_to_int(got[i]) == expect
    assert got.max() < 512  # module invariant preserved

    # extreme-bound exactness (511 everywhere — the f32 worst case)
    am = np.full((5, 32), 511, np.int32)
    w = np.asarray(F.mul(am, am))
    g = np.asarray(PF.mul(am, am, interpret=True))
    for i in range(5):
        assert F.limbs_to_int(w[i]) == F.limbs_to_int(g[i])


def test_verify_resolved_chunked(monkeypatch):
    """Batches above _MAX_BUCKET split into pipelined chunks; a bad
    signature triggers the per-signature fallback ONLY for its chunk."""
    from tendermint_tpu.crypto.tpu import verify as V

    monkeypatch.setattr(V, "_MAX_BUCKET", 64)
    items = _signed_items(150, n_vals=8)
    p, m, s = items[100]  # chunk 2 (64..127)
    items[100] = (p, m, s[:63] + bytes([s[63] ^ 1]))
    out = V.verify_batch_eq(items)
    assert len(out) == 150
    assert not out[100] and out.sum() == 149


@pytest.mark.slow  # interpret-mode Pallas on CPU: a 254-multiply
# chain per element — minutes-to-hours on small hosts, far past the
# tier-1 budget. The on-device A/B probe cross-checks the same
# kernels against the XLA formulation on real TPU at startup.
def test_pallas_scan_blocks_matches_xla_scan():
    """The fused within-block prefix-scan kernel (interpret mode on CPU)
    is limb-exact with the lax.scan of curve.add_cached it replaces."""


    import jax.numpy as jnp
    import numpy as np

    from tendermint_tpu.crypto.tpu import curve as C
    from tendermint_tpu.crypto.tpu import msm as M
    from tendermint_tpu.crypto.tpu import pallas_field as PF

    rng = np.random.default_rng(17)
    # 4-step blocks: the kernel is length-generic (production uses
    # M._BLOCK=16); a short chain keeps interpret-mode tracing cheap
    g, blk = 8, 4
    coords = [rng.integers(0, 256, (g, blk, 32), dtype=np.int32) for _ in range(4)]
    pts = C.Point(*(jnp.asarray(c) for c in coords))

    first = C.Point(*(c[:, 0] for c in pts))
    rest = C.Point(*(jnp.moveaxis(c[:, 1:], 1, 0) for c in pts))
    rest_cached = C.to_cached(rest)

    def xla_scan():
        def step(acc, nxt):
            acc = C.add_cached(acc, nxt)
            return acc, acc

        last, tail = __import__("jax").lax.scan(step, first, rest_cached)
        within = C.Point(
            *(
                jnp.concatenate([f[:, None], jnp.moveaxis(t, 0, 1)], axis=1)
                for f, t in zip(first, tail)
            )
        )
        return within, last

    want_within, want_last = xla_scan()
    got = PF.scan_blocks(tuple(first), tuple(rest_cached), interpret=True, tile=8)
    for w, gp in zip(want_within, got):
        assert np.array_equal(np.asarray(w), np.asarray(gp))
    for w, gp in zip(want_last, got):
        assert np.array_equal(np.asarray(w), np.asarray(gp[:, -1]))
