"""ByzNet — Byzantine validator injection over real routers.

The "B" in BFT, demonstrated live: a traitor built from
`consensus/byzantine.py` rides the real `p2p.Router` byte path
(RouterNet, PR 11), honest nodes detect its equivocation, pool the
DuplicateVoteEvidence, gossip it over the evidence channel, COMMIT it
on chain, and surface it to the app through BeginBlock misbehavior —
while the cross-node safety auditor proves no two honest nodes ever
disagreed and the traitor paid for its forgeries.

Determinism construction for the pinned lifecycle test: frozen
ManualClock behind genesis (vote-time floor pins all stamps), generous
timeouts (commit round pinned at 0), the traitor is the HEIGHT-1
PROPOSER (so the height-2 proposer — the one that includes the
evidence — is honest and detected the equivocation locally), it
equivocates prevotes in ``both`` mode (every honest node receives the
conflicting pair back-to-back on a FIFO link → deterministic local
detection) and withholds ALL its precommits (every commit then needs
exactly the three honest precommits → pinned signer set). Two
same-seed runs produce bit-identical block bytes AND evidence bytes.

Tier-1 carries the 4-validator tests under explicit wall-time budgets
(the tmtlint budget-gate pattern); the 50-validator byz sweep and the
f-max soak are slow-marked."""

import asyncio
import json
import subprocess
import sys
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.consensus import scenarios as sc
from tendermint_tpu.consensus.byzantine import (
    ByzConfig,
    ByzantineNode,
    _decide,
    _fabricated_block_id,
    audit_net,
    byz_prepare_hook,
    committed_duplicate_vote_evidence,
)
from tendermint_tpu.consensus.harness import GENESIS_TIME_NS, make_genesis
from tendermint_tpu.consensus.reactor import ConsensusReactor, _CatchupBucket
from tendermint_tpu.consensus.routernet import RouterNet
from tendermint_tpu.evidence.pool import EvidencePool
from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork
from tendermint_tpu.libs.clock import ManualClock
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.keys import SignedMsgType

MS = 1_000_000

# the safety criterion: an equivocator's evidence must be ON CHAIN
# within K heights of the double-sign
K_HEIGHTS = 3


def frozen_clock() -> ManualClock:
    return ManualClock(GENESIS_TIME_NS - 500 * MS)


def generous_config():
    from tendermint_tpu.config import ConsensusConfig

    return ConsensusConfig(
        timeout_propose_ns=3000 * MS,
        timeout_propose_delta_ns=500 * MS,
        timeout_prevote_ns=2000 * MS,
        timeout_prevote_delta_ns=500 * MS,
        timeout_precommit_ns=2000 * MS,
        timeout_precommit_delta_ns=500 * MS,
        timeout_commit_ns=80 * MS,
        skip_timeout_commit=True,
    )


def height1_proposer_index(n_vals: int) -> int:
    """The validator index proposing height 1 in a RouterNet(n_vals)
    net — RouterNet derives the same genesis via make_genesis."""
    genesis, keys = make_genesis(n_vals)
    addr = state_from_genesis(genesis).validators.get_proposer().address
    return next(
        i for i, k in enumerate(keys) if k.pub_key().address() == addr
    )


class RecordingApp(KVStoreApp):
    """KVStore plus a tape of BeginBlock misbehavior reports — the ABCI
    surface the whole evidence lifecycle terminates at."""

    def __init__(self):
        super().__init__()
        self.misbehavior: list[tuple[int, tuple]] = []

    def begin_block(self, req):
        if req.byzantine_validators:
            self.misbehavior.append(
                (req.header.height, tuple(req.byzantine_validators))
            )
        return super().begin_block(req)


class TestUnits:
    def test_unknown_strategy_rejected(self):
        with pytest.raises(ValueError, match="unknown byzantine"):
            ByzConfig(("equivocate", "bribe_the_app"))

    def test_decisions_are_seed_deterministic(self):
        a = [_decide(7, "camp", 1, 0, "peer") for _ in range(3)]
        assert len(set(a)) == 1
        assert _decide(7, "camp", 1, 0, "peer") != _decide(8, "camp", 1, 0, "peer")
        assert 0.0 <= a[0] < 1.0

    def test_fabricated_block_id_is_complete_and_stable(self):
        b1 = _fabricated_block_id(3, "equiv", 1, 0, 2)
        b2 = _fabricated_block_id(3, "equiv", 1, 0, 2)
        assert b1 == b2 and b1.is_complete()
        assert b1 != _fabricated_block_id(4, "equiv", 1, 0, 2)

    def test_catchup_bucket_grant_semantics(self):
        b = _CatchupBucket(rate=10.0, burst=5, now=100.0)
        assert b.grant(3, 100.0) == 3  # burst available immediately
        assert b.grant(5, 100.0) == 2  # drained to the burst cap
        assert b.grant(5, 100.0) == 0  # empty, no time elapsed
        assert b.grant(5, 100.5) == 5  # 0.5s * 10/s = 5 tokens refilled
        assert b.grant(100, 200.0) == 5  # refill is capped at burst

    def test_byz_scenarios_registered_and_composable(self):
        names = set(sc.SCENARIOS)
        assert {
            "byz_equivocation",
            "byz_equivocation_partition",
            "byz_amnesia_skew",
            "byz_withhold",
            "byz_invalid_sig",
            "byz_flood_lies",
            "byz_full_taxonomy",
        } <= names
        # the byz axis composes with the existing fault taxonomy
        part = sc.SCENARIOS["byz_equivocation_partition"]
        assert part.byz and {e.action for e in part.events} >= {"oneway", "heal"}
        skew = sc.SCENARIOS["byz_amnesia_skew"]
        assert skew.byz and skew.chaos.clock_skew_ms > 0
        full = sc.SCENARIOS["byz_full_taxonomy"]
        assert full.byz_f_max is not None
        assert full.chaos.corrupt_rate > 0 and full.chaos.clock_skew_ms > 0


class TestDoubleSignLifecycle:
    @pytest.mark.asyncio
    async def test_full_lifecycle_bit_identical_across_same_seed_runs(self):
        """THE acceptance test: equivocating vote pair observed →
        DuplicateVoteEvidence in honest pools → gossiped on the
        evidence channel → committed in a block within K heights →
        surfaced to the app via BeginBlock misbehavior — and two
        same-seed runs produce bit-identical block bytes AND evidence
        bytes, over real routers."""
        t0 = time.perf_counter()
        n, target = 4, 4
        byz_idx = height1_proposer_index(n)
        observer = (byz_idx + 1) % n
        byz_addr = make_genesis(n)[1][byz_idx].pub_key().address()

        async def one_run(seed: int):
            plan = {
                byz_idx: ByzConfig(
                    ("equivocate", "withhold_precommits"),
                    seed=seed,
                    equiv_heights=(1,),
                    equiv_types=(SignedMsgType.PREVOTE,),
                )
            }
            registry: list = []
            apps: dict[int, RecordingApp] = {}

            def app_factory(i):
                if i == observer:
                    apps[i] = RecordingApp()
                    return apps[i]
                return None

            gossiped = []
            orig_add = EvidencePool.add_evidence

            def counting_add(self, ev, _orig=orig_add):
                gossiped.append(type(ev).__name__)
                return _orig(self, ev)

            EvidencePool.add_evidence = counting_add
            net = RouterNet(
                n,
                config=generous_config(),
                base_clock=frozen_clock(),
                prepare_hook=byz_prepare_hook(plan, registry),
                app_factory=app_factory,
            )
            try:
                await net.start()
                await net.wait_for_height(target, 90)
                # pools on every honest node saw the pair
                rep = audit_net(net, registry, k_heights=K_HEIGHTS)
                evidence = committed_duplicate_vote_evidence(
                    net.nodes[observer]
                )
                return {
                    "blocks": net.block_fingerprints(target, node=observer),
                    "apps": net.app_hash_chain(target, node=observer),
                    "audit": rep,
                    "evidence": evidence,
                    "gossiped": len(gossiped),
                    "misbehavior": list(apps[observer].misbehavior),
                    "byz": registry[0],
                }
            finally:
                EvidencePool.add_evidence = orig_add
                await net.stop()

        r1 = await one_run(seed=11)
        r2 = await one_run(seed=11)

        # -- lifecycle, stage by stage (on run 1) -----------------------
        byz: ByzantineNode = r1["byz"]
        assert (1, 0, SignedMsgType.PREVOTE) in byz.twins, (
            "the traitor never double-signed"
        )
        assert byz.action_counts.get("withhold_precommit", 0) > 0
        # detection + commitment: evidence for OUR traitor, within K
        assert byz_addr in r1["evidence"], "equivocation never reached chain"
        commit_h, ev = r1["evidence"][byz_addr]
        ev_bytes = ev.encode()
        assert isinstance(ev, DuplicateVoteEvidence) and ev.height == 1
        assert commit_h - 1 <= K_HEIGHTS, (
            f"evidence took {commit_h - 1} heights (K={K_HEIGHTS})"
        )
        # the wire: pending evidence moved on the evidence channel
        # (add_evidence is called ONLY by the evidence reactor's inbound)
        assert r1["gossiped"] > 0, "evidence never rode the evidence channel"
        # the ABCI surface: BeginBlock carried the misbehavior report
        assert r1["misbehavior"], "app never saw the misbehavior"
        mb_height, mbs = r1["misbehavior"][0]
        assert mb_height == commit_h
        assert mbs[0].type == "duplicate_vote"
        assert mbs[0].validator_address == byz_addr
        assert mbs[0].height == 1  # the equivocation height
        # the auditor: safety + accountability
        assert r1["audit"].ok, r1["audit"].as_dict()
        assert not r1["audit"].conflicting_commits
        assert r1["audit"].evidence_commit_heights == {
            byz_addr.hex(): commit_h
        }

        # -- bit-identity across same-seed runs -------------------------
        assert all(r1["blocks"]), "missing blocks in run 1"
        assert r1["blocks"] == r2["blocks"], (
            "block bytes diverged across same-seed byz runs"
        )
        assert r1["apps"] == r2["apps"], "app-hash chains diverged"
        assert ev_bytes == r2["evidence"][byz_addr][1].encode(), (
            "evidence bytes diverged across same-seed byz runs"
        )
        # the byzantine DECISIONS are bit-identical too: the signed twin
        # set is a pure function of the seed. (Per-send counters like
        # withhold_precommit are NOT compared — how many times gossip
        # re-offers a vote is wall-clock cadence, not a decision.)
        assert byz.twins.keys() == r2["byz"].twins.keys()
        assert [t.encode() for t in byz.twins.values()] == [
            t.encode() for t in r2["byz"].twins.values()
        ]
        assert (
            byz.action_counts["equivocate"]
            == r2["byz"].action_counts["equivocate"]
        )
        assert r2["byz"].action_counts.get("withhold_precommit", 0) > 0
        elapsed = time.perf_counter() - t0
        assert elapsed < 90.0, f"lifecycle test blew its budget: {elapsed:.1f}s"


class TestByzScenarios:
    @pytest.mark.asyncio
    async def test_equivocation_under_partition_split_mode(self):
        """Composition axis: split-mode equivocation (conflicting votes
        to disjoint, per-peer-stable camps) while node 0 is one-way
        partitioned — detection must come from honest relay gossip
        crossing the camp boundary. On a small fast net that crossing
        races the height advance, so evidence is best-effort here
        (audit_require_evidence=False) — but SAFETY is absolute, and
        any evidence that does commit must be prompt."""
        t0 = time.perf_counter()
        res = await sc.run_scenario(
            "byz_equivocation_partition",
            n_vals=4,
            target_height=4,
            seed=3,
            timeout_s=90.0,
            stall_s=30.0,
        )
        d = res.as_dict()
        assert res.ok, d
        assert d["audit"]["ok"], d["audit"]
        assert not d["audit"]["conflicting_commits"]
        assert not d["audit"]["app_hash_mismatches"]
        assert not d["audit"]["late_evidence"]
        # the traitor really ran split-mode equivocation on the wire
        assert d["byz_actions"][0]["counts"].get("equivocate", 0) > 0
        assert {"oneway", "heal"} <= set(res.events_applied)
        elapsed = time.perf_counter() - t0
        assert elapsed < 75.0, f"blew budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_invalid_sig_gossip_costs_the_peer(self):
        """Accountability for forgeries: stage-1 ingest disproves the
        garbage signature, the reactor files a PeerError, and every
        honest peer manager scores the traitor down."""
        t0 = time.perf_counter()
        res = await sc.run_scenario(
            "byz_invalid_sig",
            n_vals=4,
            target_height=4,
            seed=3,
            timeout_s=90.0,
            stall_s=30.0,
        )
        d = res.as_dict()
        assert res.ok, d
        assert d["audit"]["ok"], d["audit"]
        penalties = d["audit"]["peer_penalties"]
        assert penalties, "invalid-sig gossip cost the traitor nothing"
        assert all(
            score < 0
            for by_node in penalties.values()
            for score in by_node.values()
        )
        assert not d["audit"]["unpenalized"]
        elapsed = time.perf_counter() - t0
        assert elapsed < 75.0, f"blew budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_flood_and_lies_cannot_stall_honest_nodes(self):
        """future_round_flood + lying_frames: honest nodes must keep
        committing (the unwanted-round guard sheds the flood; the
        VoteSetBits/stall-refresh hardening heals the lying marks). A
        traitor that lies itself out of catch-up is ITS problem — the
        liveness gate covers correct nodes only."""
        t0 = time.perf_counter()
        res = await sc.run_scenario(
            "byz_flood_lies",
            n_vals=4,
            target_height=4,
            seed=3,
            timeout_s=90.0,
            stall_s=30.0,
        )
        d = res.as_dict()
        assert res.ok, d
        assert d["audit"]["ok"], d["audit"]
        honest_heights = [
            h for i, h in enumerate(res.heights) if i not in res.byz_indices
        ]
        assert all(h >= 4 for h in honest_heights), res.heights
        counts = d["byz_actions"][0]["counts"]
        assert counts.get("future_round_flood", 0) > 0
        assert counts.get("lie_round_step", 0) > 0
        elapsed = time.perf_counter() - t0
        assert elapsed < 75.0, f"blew budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_f_max_full_taxonomy_4val_smoke(self):
        """f = ⌊(n−1)/3⌋ = 1 of 4: the full strategy mix under network
        chaos — the tier-1 half of the acceptance criterion (the
        50-validator version is slow-marked below)."""
        t0 = time.perf_counter()
        res = await sc.run_scenario(
            "byz_full_taxonomy",
            n_vals=4,
            target_height=4,
            seed=7,
            timeout_s=120.0,
            stall_s=40.0,
        )
        d = res.as_dict()
        assert res.ok, d
        assert d["audit"]["ok"], d["audit"]
        assert len(res.byz_indices) == 1  # (4-1)//3
        assert d["audit"]["evidence_commit_heights"], (
            "equivocators escaped accountability"
        )
        elapsed = time.perf_counter() - t0
        assert elapsed < 100.0, f"blew budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_wedge_dump_carries_byz_action_log(self, tmp_path):
        """The watchdog contract, extended: a wedged byz run dumps the
        per-node byzantine action log next to the flight recorder and
        fault counters."""
        t0 = time.perf_counter()
        wedge = sc.Scenario(
            "byz_wedge_probe",
            "quorum-killing split with a traitor (watchdog self-test)",
            byz=((3, ByzConfig(("equivocate",))),),
            events=(sc.Event(0.4, "partition", groups=((0, 1), (2, 3))),),
        )
        res = await sc.run_scenario(
            wedge,
            n_vals=4,
            target_height=6,
            seed=5,
            timeout_s=30.0,
            stall_s=4.0,
            dump_dir=str(tmp_path),
        )
        assert res.wedged and res.dump_path
        payload = json.loads(open(res.dump_path).read())
        assert payload["byz"], "wedge dump lost the byz action log"
        assert payload["byz"][0]["index"] == 3
        assert "equivocate" in payload["byz"][0]["counts"]
        assert payload["audit"] is not None
        # a wedge is a liveness failure — safety must still hold
        assert not payload["audit"]["conflicting_commits"]
        elapsed = time.perf_counter() - t0
        assert elapsed < 60.0, f"blew budget: {elapsed:.1f}s"


class TestMaj23ConflictAdmission:
    """The reference SetPeerMaj23 machinery (vote_set.go votesByBlock),
    surfaced live by the byz matrix: a laggard whose precommit slot for
    an equivocator got the TWIN first (chaos reorder) re-rejected the
    committed majority's real vote as a conflict on every catch-up
    re-serve — one reordered twin wedged the node a height behind
    forever. With a peer's +2/3 claim for the committed block,
    conflicting votes for THAT block are admissible, and crossing +2/3
    adopts them into the canonical slots so make_commit materializes
    the real majority."""

    def _setup(self):
        from tendermint_tpu import testing as tt
        from tendermint_tpu.types.vote_set import VoteSet

        vals, keys = tt.make_validator_set(4)
        vs = VoteSet("test-chain", 2, 0, SignedMsgType.PRECOMMIT, vals)
        bid = tt.make_block_id()
        ordered = [keys[v.address] for v in vals.validators]
        return vs, vals, ordered, bid

    def test_conflicting_vote_for_claimed_block_admitted_to_quorum(self):
        from tendermint_tpu import testing as tt
        from tendermint_tpu.types.vote_set import ConflictingVoteError

        vs, vals, keys, bid = self._setup()
        twin_bid = _fabricated_block_id(1, "twin", 2, 0)
        # equivocator (index 3): the TWIN arrives first and takes the slot
        twin = tt.make_vote("test-chain", keys[3], 3, 2, 0,
                            SignedMsgType.PRECOMMIT, twin_bid)
        assert vs.add_vote(twin)
        # two honest votes for the real block: 30 of 40 — no +2/3 yet
        # (the node's own slot precommitted nil, the catch-up shape)
        for i in (0, 1):
            assert vs.add_vote(
                tt.make_vote("test-chain", keys[i], i, 2, 0,
                             SignedMsgType.PRECOMMIT, bid)
            )
        honest = tt.make_vote("test-chain", keys[3], 3, 2, 0,
                              SignedMsgType.PRECOMMIT, bid)
        # without a claim: the committed majority's vote is a conflict
        with pytest.raises(ConflictingVoteError):
            vs.add_vote(honest)
        assert vs.two_thirds_majority() is None
        # with the peer's +2/3 claim: admissible, crosses quorum,
        # canonical slot adopts the real vote
        vs.set_peer_maj23_block(bid)
        assert vs.add_vote(honest)
        assert vs.two_thirds_majority() == bid
        assert vs.get_vote(3).block_id == bid, "slot still holds the twin"
        commit = vs.make_commit()
        assert commit.block_id == bid
        assert sum(1 for s in commit.signatures if s.is_commit()) == 3
        # re-adding the same conflicting vote is a plain duplicate now
        assert vs.add_vote(honest) is False

    def test_crossing_via_normal_path_still_adopts_bucket_votes(self):
        """The crossing vote may arrive through the NORMAL add path
        (the conflict-admitted vote came earlier, before quorum):
        adoption must fire on the crossing itself, wherever it happens
        — otherwise make_commit materializes the twin and emits an
        under-quorum commit."""
        from tendermint_tpu import testing as tt

        vs, vals, keys, bid = self._setup()
        twin_bid = _fabricated_block_id(1, "twin", 2, 0)
        vs.set_peer_maj23_block(bid, "donor")
        # twin takes slot 3, then the REAL vote arrives before quorum
        # (admitted into the bucket, tally 10)
        assert vs.add_vote(
            tt.make_vote("test-chain", keys[3], 3, 2, 0,
                         SignedMsgType.PRECOMMIT, twin_bid)
        )
        assert vs.add_vote(
            tt.make_vote("test-chain", keys[3], 3, 2, 0,
                         SignedMsgType.PRECOMMIT, bid)
        )
        assert vs.two_thirds_majority() is None
        # honest votes cross +2/3 through the NORMAL path
        for i in (0, 1):
            assert vs.add_vote(
                tt.make_vote("test-chain", keys[i], i, 2, 0,
                             SignedMsgType.PRECOMMIT, bid)
            )
        assert vs.two_thirds_majority() == bid
        assert vs.get_vote(3).block_id == bid, "slot kept the twin"
        commit = vs.make_commit()
        assert sum(1 for s in commit.signatures if s.is_commit()) == 3

    def test_claim_table_bounded_per_peer_not_globally(self):
        """A lying peer burns only its OWN claim budget: spamming
        fabricated claims must not crowd out an honest donor's claim
        for the real committed block."""
        vs, vals, keys, bid = self._setup()
        for i in range(16):
            vs.set_peer_maj23_block(
                _fabricated_block_id(9, "spam", i, 0), "liar"
            )
        assert len(vs._maj23_claims_by_peer["liar"]) == 2
        # the honest donor's claim still lands
        vs.set_peer_maj23_block(bid, "donor")
        assert bid.key() in vs._peer_maj23_blocks

    def test_claim_for_unrelated_block_changes_nothing(self):
        from tendermint_tpu import testing as tt
        from tendermint_tpu.types.vote_set import ConflictingVoteError

        vs, vals, keys, bid = self._setup()
        assert vs.add_vote(
            tt.make_vote("test-chain", keys[3], 3, 2, 0,
                         SignedMsgType.PRECOMMIT, bid)
        )
        other = _fabricated_block_id(2, "other", 2, 0)
        vs.set_peer_maj23_block(other)
        # a conflict for a block nobody claimed still raises (evidence)
        conflicting = tt.make_vote(
            "test-chain", keys[3], 3, 2, 0, SignedMsgType.PRECOMMIT,
            _fabricated_block_id(3, "third", 2, 0),
        )
        with pytest.raises(ConflictingVoteError):
            vs.add_vote(conflicting)
        # nil and None claims are ignored
        from tendermint_tpu.types.block import NIL_BLOCK_ID

        before = len(vs._peer_maj23_blocks)
        vs.set_peer_maj23_block(NIL_BLOCK_ID)
        vs.set_peer_maj23_block(None)
        assert len(vs._peer_maj23_blocks) == before


class TestCatchupPacing:
    @pytest.mark.asyncio
    async def test_paced_catchup_still_recovers_laggard(self):
        """Pacing bounds each catch-up grant at the bucket burst and
        still recovers a one-way-partitioned laggard after heal — the
        donors' loop share is bounded, not the laggard's progress."""
        t0 = time.perf_counter()
        chaos = ChaosNetwork(ChaosConfig(seed=77))
        net = RouterNet(
            4,
            base_clock=frozen_clock(),
            chaos=chaos,
            catchup_rate=60.0,
            catchup_burst=2,
        )
        laggard = net.nodes[3]
        chaos.partition_oneway(
            {n.node_id for n in net.nodes[:3]}, {laggard.node_id}
        )
        grants: list[int] = []
        orig = ConsensusReactor._catchup_grant

        def spy(self, peer_id, want, _orig=orig):
            got = _orig(self, peer_id, want)
            if want > 0:
                grants.append(got)
            return got

        ConsensusReactor._catchup_grant = spy
        try:
            await net.start()
            await asyncio.gather(
                *(n.cs.wait_for_height(3, 60) for n in net.nodes[:3])
            )
            assert laggard.block_store.height() < 3
            chaos.heal()
            await laggard.cs.wait_for_height(3, 60)
        finally:
            ConsensusReactor._catchup_grant = orig
            await net.stop()
        assert grants, "catch-up never consulted the pacing bucket"
        assert max(grants) <= 2, f"a grant exceeded the burst: {max(grants)}"
        # pacing spread the service over multiple granted slices
        assert sum(1 for g in grants if g > 0) >= 2
        elapsed = time.perf_counter() - t0
        assert elapsed < 90.0, f"blew budget: {elapsed:.1f}s"

    def test_committee_nets_default_to_paced_catchup(self):
        paced = RouterNet(20, use_hub=False)
        assert paced.catchup_rate is not None and paced.catchup_rate > 0
        small = RouterNet(4, use_hub=False)
        assert small.catchup_rate is None  # small nets keep old behavior


class TestEvidenceReactorFutureBuffer:
    class _FakePool:
        def __init__(self, tip):
            class _S:
                last_block_height = tip

            self.state = _S()
            self.added = []
            self.reject = False

        def add_evidence(self, ev):
            if self.reject:
                from tendermint_tpu.evidence.pool import EvidenceError

                raise EvidenceError("bad evidence")
            self.added.append(ev)

        def pending_evidence(self, max_bytes):
            return [], 0

    class _FakeChannel:
        def __init__(self, envs):
            self._envs = list(envs)
            self.errors = []
            self.out_q = asyncio.Queue()

        async def error(self, err):
            self.errors.append(err)

        def __aiter__(self):
            return self

        async def __anext__(self):
            if self._envs:
                return self._envs.pop(0)
            await asyncio.Event().wait()  # block forever (reactor stop reaps)
            raise AssertionError("unreachable")

    class _Ev:
        def __init__(self, height):
            self.height = height

        def hash(self):
            return b"ev" + self.height.to_bytes(8, "big")

    @pytest.mark.asyncio
    async def test_future_evidence_parks_and_retries_without_peer_error(self):
        """Evidence for a height we haven't committed yet is honest
        timing, not a violation: no PeerError (the router would evict a
        correct peer), parked, and pooled once our tip advances."""
        from tendermint_tpu.evidence.reactor import EvidenceReactor
        from tendermint_tpu.p2p.types import Envelope

        pool = self._FakePool(tip=1)
        ch = self._FakeChannel(
            [Envelope(0x38, self._Ev(5), from_="peerA")]
        )
        r = EvidenceReactor(pool, ch, asyncio.Queue())
        await r.start()
        try:
            await asyncio.sleep(0.1)
            assert not ch.errors, "future evidence must not cost the peer"
            assert not pool.added and r._parked
            pool.state.last_block_height = 5  # tip advanced
            await asyncio.sleep(0.5)
            assert [e.height for e in pool.added] == [5]
            assert not r._parked
        finally:
            await r.stop()

    @pytest.mark.asyncio
    async def test_far_future_junk_cannot_squat_in_the_park(self):
        """Evidence claiming a height no live peer can have verified is
        junk: it must not occupy the bounded park forever (it never
        stops being 'future') and must not block honest near-future
        parking."""
        from tendermint_tpu.evidence.reactor import EvidenceReactor, PARK_WINDOW
        from tendermint_tpu.p2p.types import Envelope

        pool = self._FakePool(tip=1)
        envs = [Envelope(0x38, self._Ev(10**9), from_="junker")]
        envs.append(Envelope(0x38, self._Ev(3), from_="peerB"))
        r = EvidenceReactor(pool, self._FakeChannel(envs), asyncio.Queue())
        await r.start()
        try:
            await asyncio.sleep(0.1)
            parked = [e.height for e in r._parked.values()]
            assert parked == [3], parked  # junk dropped, honest parked
            assert 10**9 > 1 + PARK_WINDOW  # the junk was out-of-window
        finally:
            await r.stop()

    def test_conflict_redelivery_survives_transient_processing_failure(self):
        """A store hiccup while building the evidence must not consume
        the dedup key — the next gossip re-delivery of the pair has to
        be able to re-report it (finding: permanent evidence loss)."""
        from tendermint_tpu import testing as tt
        from tendermint_tpu.evidence.pool import EvidencePool
        from tendermint_tpu.store.db import MemDB

        class _Boom:
            def load_validators(self, h):
                raise RuntimeError("transient store failure")

            def load(self):
                return None

        class _State:
            last_block_height = 5

        pool = EvidencePool.__new__(EvidencePool)
        pool.db = MemDB()
        pool.state_store = _Boom()
        pool.block_store = None
        import logging as _l

        pool.logger = _l.getLogger("evtest")
        pool._consensus_buffer = []
        pool._conflict_keys = set()
        pool._version = 0
        pool._pending_cache = None
        pool.state = _State()
        vals, keys = tt.make_validator_set(4)
        ordered = [keys[v.address] for v in vals.validators]
        a = tt.make_vote("c", ordered[0], 0, 3, 0,
                         SignedMsgType.PREVOTE, tt.make_block_id(b"a"))
        b = tt.make_vote("c", ordered[0], 0, 3, 0,
                         SignedMsgType.PREVOTE, tt.make_block_id(b"b"))
        pool.report_conflicting_votes(a, b)
        assert len(pool._consensus_buffer) == 1
        pool.report_conflicting_votes(a, b)  # dedup holds while buffered
        assert len(pool._consensus_buffer) == 1
        pool._process_consensus_buffer(_State())  # store blows up
        assert not pool._consensus_buffer
        # the key was released: a re-delivery re-buffers the pair
        pool.report_conflicting_votes(a, b)
        assert len(pool._consensus_buffer) == 1

    @pytest.mark.asyncio
    async def test_genuinely_bad_evidence_still_costs_the_peer(self):
        from tendermint_tpu.evidence.reactor import EvidenceReactor
        from tendermint_tpu.p2p.types import Envelope

        pool = self._FakePool(tip=10)
        pool.reject = True
        ch = self._FakeChannel(
            [Envelope(0x38, self._Ev(5), from_="peerA")]
        )
        r = EvidenceReactor(pool, ch, asyncio.Queue())
        await r.start()
        try:
            await asyncio.sleep(0.1)
            assert len(ch.errors) == 1
            assert ch.errors[0].node_id == "peerA"
        finally:
            await r.stop()


class TestContainment:
    def test_production_import_graph_never_reaches_byzantine(self):
        """node.py and cli.py (the production wiring) must not import
        consensus/byzantine even transitively — checked on a FRESH
        interpreter so this session's harness imports can't mask it."""
        code = (
            "import sys\n"
            "import tendermint_tpu.node, tendermint_tpu.cli\n"
            "bad = [m for m in sys.modules if 'byzantine' in m]\n"
            "assert not bad, f'production wiring reaches {bad}'\n"
            "print('CONTAINED')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "CONTAINED" in out.stdout

    def test_harness_is_the_legal_importer(self):
        # the scenario harness DOES reach it — that is the design
        import tendermint_tpu.consensus.scenarios as s

        assert s.ByzConfig is ByzConfig


@pytest.mark.slow
class TestByzSweep50:
    @pytest.mark.asyncio
    async def test_byz_sweep_50_validators(self):
        """Byzantine strategies at committee scale: each byz scenario at
        50 validators over the degree-8 topology, every honest node
        progressing, the auditor green (evidence committed, no honest
        disagreement)."""
        names = [
            "byz_equivocation",
            "byz_equivocation_partition",
            "byz_amnesia_skew",
            "byz_withhold",
            "byz_invalid_sig",
        ]
        results = await sc.run_sweep(
            names,
            n_vals=50,
            target_height=4,
            seed=13,
            timeout_s=420.0,
            stall_s=120.0,
            time_scale=4.0,
            degree=8,
            audit_k=4,
        )
        failures = [
            r.as_dict()
            for r in results
            if not r.ok or not (r.audit or {}).get("ok")
        ]
        assert not failures, f"50-validator byz sweep failures: {failures}"

    @pytest.mark.asyncio
    async def test_f_max_full_soak_50_validators(self):
        """THE acceptance soak: f = ⌊(50−1)/3⌋ = 16 traitors running
        equivocation/amnesia/withholding/flood strategies composed with
        network chaos — zero conflicting honest commits, evidence for
        every equivocator committed within K heights."""
        res = await sc.run_scenario(
            "byz_full_taxonomy",
            n_vals=50,
            target_height=4,
            seed=29,
            timeout_s=900.0,
            stall_s=240.0,
            time_scale=8.0,
            degree=8,
            audit_k=6,
        )
        d = res.as_dict()
        assert res.ok, d
        assert len(res.byz_indices) == 16
        assert d["audit"]["ok"], d["audit"]
        assert not d["audit"]["conflicting_commits"]
        assert not d["audit"]["missing_evidence"]
        assert len(d["audit"]["evidence_commit_heights"]) >= 1
