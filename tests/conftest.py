"""Test configuration.

Sharding/mesh tests run on a virtual 8-device CPU topology: real multi-chip
TPU hardware is not available in CI, so `jax.sharding.Mesh` code paths are
validated with `--xla_force_host_platform_device_count=8` on the CPU backend
(the driver separately dry-runs the multichip path via __graft_entry__).
"""

import os
import sys

# force CPU: the ambient environment pins JAX_PLATFORMS=axon (the TPU
# tunnel) and its sitecustomize imports jax at interpreter start, so the
# env var alone is too late — update the live config too.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# route batch verification to the host in unit tests: the background
# TPU probe thread would otherwise still be compiling at interpreter
# exit (SIGABRT in XLA teardown). The TPU kernel itself is covered by
# tests/test_tpu_crypto.py, which calls it directly.
os.environ.setdefault("TMTPU_DISABLE_TPU", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# -- minimal async test support (pytest-asyncio is not in the image) --------

import asyncio  # noqa: E402
import gc  # noqa: E402
import inspect  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test on a fresh event loop")
    config.addinivalue_line("markers", "slow: long-running multi-process e2e tests")


def pytest_pyfunc_call(pyfuncitem):
    func = pyfuncitem.obj
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }

        async def _run_with_leak_check():
            await func(**kwargs)
            # Leak hygiene (the asyncio analog of the reference's leaktest,
            # internal/libs/sync/deadlock.go): cancel anything the test
            # left running and collect garbage WHILE the loop is alive, so
            # transport finalizers close their sockets on a live loop
            # instead of raising "Event loop is closed" at interpreter GC.
            leaked = [
                t
                for t in asyncio.all_tasks()
                if t is not asyncio.current_task() and not t.done()
            ]
            for t in leaked:
                t.cancel()
            if leaked:
                await asyncio.gather(*leaked, return_exceptions=True)
            await asyncio.sleep(0)
            gc.collect()
            await asyncio.sleep(0.01)  # let close callbacks run

        asyncio.run(_run_with_leak_check())
        return True
    return None
