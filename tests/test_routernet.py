"""RouterNet: the router-backed chaos consensus matrix.

Everything here runs over REAL p2p routers (`p2p.Router` +
`ChaosTransport` over the in-memory transport) with real
`ConsensusReactor` gossip — no broadcast-hook shortcuts and NO harness
catch-up relay: laggards recover exclusively through the reactor's own
`_send_catchup_commit_vote` / `_send_catchup_part` / catch-up
`VoteSetMaj23` path, which `LocalNetwork`'s relay used to stand in for.

Determinism construction (the acceptance criterion): a frozen
`ManualClock` parked behind genesis floors every vote timestamp to
`block_time + 1ms` (the voteTime rule), and THREE equal-power
validators make every commit require ALL precommits, pinning the commit
signer set; generous timeouts pin the commit round at 0 even while
corruption, an asymmetric partition, and clock skew are live on the
byte path. Two same-seed runs then produce bit-identical block BYTES
and app-hash chains.

Tier-1 carries only the 4-node smokes and the unit/guard tests, each
under an explicit wall-time budget (the tmtlint budget-gate pattern);
the 50-validator sweep and the 150-validator full-taxonomy soak are
slow-marked."""

import asyncio
import time

import pytest

from tendermint_tpu.config import ConsensusConfig
from tendermint_tpu.consensus import scenarios as sc
from tendermint_tpu.consensus.harness import (
    GENESIS_TIME_NS,
    LocalNetwork,
    fast_config,
)
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.routernet import RouterNet, topology_edges
from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork
from tendermint_tpu.libs.clock import ManualClock

MS = 1_000_000


def frozen_clock() -> ManualClock:
    """Parked behind genesis: the vote-time floor pins every timestamp."""
    return ManualClock(GENESIS_TIME_NS - 500 * MS)


def generous_config() -> ConsensusConfig:
    """Timeouts far above the chaos recovery latency (stall-refresh +
    re-gossip), so no round-0 prevote ever times out into nil and the
    commit round stays 0 — the round-determinism half of the
    bit-reproducibility construction."""
    return ConsensusConfig(
        timeout_propose_ns=3000 * MS,
        timeout_propose_delta_ns=500 * MS,
        timeout_prevote_ns=2000 * MS,
        timeout_prevote_delta_ns=500 * MS,
        timeout_precommit_ns=2000 * MS,
        timeout_precommit_delta_ns=500 * MS,
        timeout_commit_ns=80 * MS,
        skip_timeout_commit=True,
    )


class TestGuardsAndTopology:
    def test_localnetwork_rejects_byte_stream_faults(self):
        """Satellite guard: corrupt/bandwidth rates on the typed-hook
        harness would bump fault counters for injections that never
        happen — construction must fail loud."""
        for bad in (
            ChaosConfig(corrupt_rate=0.1),
            ChaosConfig(bandwidth_rate=1024.0),
            ChaosConfig(per_channel={0x22: ChaosConfig(corrupt_rate=0.5)}),
        ):
            with pytest.raises(ValueError, match="byte-stream"):
                LocalNetwork(3, chaos=ChaosNetwork(bad))
        # drop/delay/partition classes stay accepted
        LocalNetwork(3, chaos=ChaosNetwork(ChaosConfig(drop_rate=0.1)))

    def test_topology_deterministic_connected_bounded(self):
        e1 = topology_edges(150, 8, seed=3)
        e2 = topology_edges(150, 8, seed=3)
        assert e1 == e2, "topology must be a pure function of (n, degree, seed)"
        assert e1 != topology_edges(150, 8, seed=4)
        # connected: union-find over the edge set
        parent = list(range(150))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for a, b in e1:
            parent[find(a)] = find(b)
        assert len({find(i) for i in range(150)}) == 1
        # bounded size: ~n*degree/2 edges, not O(n^2)
        assert len(e1) <= 150 * 8
        # small nets are a full mesh
        assert topology_edges(4, 8) == [
            (0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3),
        ]

    def test_scenario_registry_covers_taxonomy(self):
        """The declarative registry names every fault class the ISSUE's
        taxonomy requires, and each scenario is runnable config."""
        names = set(sc.SCENARIOS)
        assert {
            "baseline",
            "lossy_links",
            "corrupt_wire",
            "asym_partition",
            "gray_failure",
            "bandwidth_crunch",
            "clock_skew",
            "crash_fs",
            "full_taxonomy",
        } <= names
        full = sc.SCENARIOS["full_taxonomy"]
        cfg = full.chaos
        assert cfg.corrupt_rate > 0 and cfg.bandwidth_rate > 0
        assert cfg.clock_skew_ms > 0 and cfg.clock_drift > 0
        assert full.fs is not None, "chaos-fs crash model missing"
        actions = {e.action for e in full.events}
        assert {"gray", "oneway", "crash", "restart", "heal"} <= actions


class TestWireHardening:
    """Corrupt-frame defenses + batched gossip codec, pinned directly."""

    def _vote(self, idx: int = 0):
        from tendermint_tpu.types.block import NIL_BLOCK_ID
        from tendermint_tpu.types.keys import SignedMsgType
        from tendermint_tpu.types.vote import Vote

        return Vote(
            type=SignedMsgType.PREVOTE,
            height=3,
            round=1,
            block_id=NIL_BLOCK_ID,
            timestamp_ns=123,
            validator_address=bytes([idx]) * 20,
            validator_index=idx,
            signature=b"s" * 64,
        )

    def test_vote_and_hasvote_batch_roundtrip(self):
        from tendermint_tpu.consensus import messages as m
        from tendermint_tpu.types.keys import SignedMsgType

        votes = tuple(self._vote(i) for i in range(5))
        rt = m.decode_message(m.encode_message(m.VoteBatchMessage(votes)))
        assert isinstance(rt, m.VoteBatchMessage) and rt.votes == votes
        entries = tuple(
            m.HasVoteMessage(3, 1, SignedMsgType.PREVOTE, i) for i in range(7)
        )
        rt2 = m.decode_message(
            m.encode_message(m.HasVoteBatchMessage(entries))
        )
        assert isinstance(rt2, m.HasVoteBatchMessage) and rt2.entries == entries

    def test_wire_bounds_reject_allocation_bombs(self):
        """A corrupt varint must raise (→ PeerError → disconnect), never
        allocate: bit-array sizes, has-vote indices, BitArray itself."""
        from tendermint_tpu.consensus import messages as m
        from tendermint_tpu.libs.bits import BitArray
        from tendermint_tpu.types.keys import SignedMsgType

        with pytest.raises(ValueError, match="MAX_SIZE"):
            BitArray(1 << 40)
        big_hv = m.encode_message(
            m.HasVoteMessage(1, 0, SignedMsgType.PREVOTE, (1 << 30))
        )
        with pytest.raises(ValueError, match="has-vote index"):
            m.decode_message(big_hv)
        # _decode_bits bound: craft a VoteSetBits whose bit count lies
        from tendermint_tpu.libs import protoenc as pe

        bits_body = pe.varint_field(1, 1 << 30) + pe.bytes_field(2, b"\x01")
        body = (
            pe.varint_field(1, 1)
            + pe.varint_field(2, 0)
            + pe.varint_field(3, int(SignedMsgType.PREVOTE))
            + pe.message_field(5, bits_body)
        )
        with pytest.raises(ValueError, match="wire bit array"):
            m.decode_message(pe.message_field(m.T_VOTE_SET_BITS, body))

    def test_vote_set_bits_reconciliation_clears_false_positives(self):
        """apply_vote_set_bits REPLACES the peer's bit view (reference
        ApplyVoteSetBitsMessage): a poisoned has-vote mark disappears on
        the next maj23/bits exchange instead of starving the peer of
        that vote forever."""
        from tendermint_tpu.consensus import messages as m
        from tendermint_tpu.consensus.peer_state import PeerState
        from tendermint_tpu.libs.bits import BitArray
        from tendermint_tpu.types.block import NIL_BLOCK_ID
        from tendermint_tpu.types.keys import SignedMsgType

        ps = PeerState("peer")
        ps.apply_new_round_step(
            m.NewRoundStepMessage(
                height=3, round=1, step=4,
                seconds_since_start_time=0, last_commit_round=0,
            )
        )
        # poisoned mark: we believe the peer has validator 2's prevote
        ps.set_has_vote(3, 1, SignedMsgType.PREVOTE, 2)
        assert ps.prs.prevotes[1].get(2)
        # authoritative reply: the peer actually holds only index 0
        actual = BitArray(4)
        actual.set(0, True)
        ps.apply_vote_set_bits(
            m.VoteSetBitsMessage(3, 1, SignedMsgType.PREVOTE, NIL_BLOCK_ID, actual),
            our_votes=None,
        )
        assert ps.prs.prevotes[1].get(0)
        assert not ps.prs.prevotes[1].get(2), "false positive survived"

    def test_reset_gossip_marks_keeps_round_state(self):
        from tendermint_tpu.consensus import messages as m
        from tendermint_tpu.consensus.peer_state import PeerState
        from tendermint_tpu.types.keys import SignedMsgType

        ps = PeerState("peer")
        ps.apply_new_round_step(
            m.NewRoundStepMessage(
                height=5, round=2, step=4,
                seconds_since_start_time=0, last_commit_round=0,
            )
        )
        ps.set_has_vote(5, 2, SignedMsgType.PREVOTE, 1)
        ps.ensure_catchup_commit(4, 0, 8)
        ps.reset_gossip_marks()
        assert ps.prs.height == 5 and ps.prs.round == 2, (
            "round state is the peer's claim, not a gossip mark"
        )
        assert not ps.prs.prevotes and not ps.prs.precommits
        assert ps.prs.catchup_commit_round == -1
        assert ps.prs.proposal_block_parts is None and not ps.prs.proposal

    def test_pick_votes_to_send_batches(self):
        from tendermint_tpu import testing as tt
        from tendermint_tpu.consensus import messages as m
        from tendermint_tpu.consensus.peer_state import PeerState
        from tendermint_tpu.types.keys import SignedMsgType
        from tendermint_tpu.types.vote_set import VoteSet

        vals, keys = tt.make_validator_set(8)
        vs = VoteSet("test-chain", 1, 0, SignedMsgType.PREVOTE, vals)
        bid = tt.make_block_id()
        ordered = [keys[v.address] for v in vals.validators]
        for i, k in enumerate(ordered):
            assert vs.add_vote(
                tt.make_vote(
                    "test-chain", k, i, 1, 0, SignedMsgType.PREVOTE, bid
                )
            )
        ps = PeerState("peer")
        ps.apply_new_round_step(
            m.NewRoundStepMessage(
                height=1, round=0, step=4,
                seconds_since_start_time=0, last_commit_round=-1,
            )
        )
        ps.set_has_vote(1, 0, SignedMsgType.PREVOTE, 3)
        picked = ps.pick_votes_to_send(vs, 32)
        assert [v.validator_index for v in picked] == [0, 1, 2, 4, 5, 6, 7]
        assert len(ps.pick_votes_to_send(vs, 2)) == 2


class TestRouterChaos4Node:
    """Tier-1 router-chaos smokes: 4 in-process nodes, full fault mix,
    bounded wall time."""

    @pytest.mark.asyncio
    async def test_router_chaos_smoke_full_taxonomy(self):
        """The 4-node tier-1 smoke: every fault class at once over real
        routers — lossy+corrupt+shaped links, skewed/drifting clocks, a
        gray peer, an asymmetric partition cycle, and a chaos-fs
        crash/restart mid-consensus — and every node still progresses
        past the target with per-height agreement."""
        t0 = time.perf_counter()
        res = await sc.run_scenario(
            "full_taxonomy",
            n_vals=4,
            target_height=3,
            seed=11,
            timeout_s=90.0,
            stall_s=30.0,
        )
        elapsed = time.perf_counter() - t0
        assert res.ok, f"wedged: {res.as_dict()}"
        assert not res.wedged and not res.error
        assert all(h >= 3 for h in res.heights), res.heights
        # the byte path really saw byte-stream faults (the counters the
        # hook harness could only lie about)
        assert res.faults.get("corrupt", 0) > 0, res.faults
        # 4 node clocks + 1 more handed to the crash-restarted node
        # (same node id -> same deterministic skew)
        assert res.faults.get("clock_skew", 0) >= 4
        # the whole event script fired mid-run: gray, half-open
        # partition, chaos-fs crash + restart, heal
        assert {"gray", "oneway", "crash", "restart", "heal"} <= set(
            res.events_applied
        ), res.events_applied
        assert res.fs_faults, "chaos-fs was not threaded under the WAL"
        assert res.recover_s is not None and res.recover_s >= 0.0
        assert res.blocks_per_s > 0
        # tier-1 wall budget (tmtlint budget-gate pattern)
        assert elapsed < 75.0, f"4-node smoke blew its tier-1 budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_same_seed_runs_bit_identical_over_real_routers(self):
        """THE acceptance criterion: two same-seed RouterNet runs with
        corruption + an asymmetric partition + clock skew enabled and
        tracing ON produce bit-identical block BYTES and app-hash
        chains — with zero harness-relay rescues, because RouterNet has
        no relay: catch-up is the reactor's own gossip."""
        from tendermint_tpu.libs import trace

        t0 = time.perf_counter()
        target = 3

        async def one_run(seed: int):
            chaos = ChaosNetwork(
                ChaosConfig(
                    seed=seed, corrupt_rate=0.015, delay_ms=2.0,
                    clock_skew_ms=80.0,
                )
            )
            net = RouterNet(
                3,
                config=generous_config(),
                chaos=chaos,
                base_clock=frozen_clock(),
                stall_refresh_s=0.3,
            )
            # structurally no relay: the only catch-up machinery is the
            # consensus reactor's (zero harness-relay rescues by
            # construction — there is nothing to count)
            assert not hasattr(net, "_catchup_relay")
            assert not hasattr(net, "catchup_rescues")
            # half-open link: node0 -> node1 severed for the WHOLE run;
            # node1 sees node0's traffic only via node2's relay gossip
            chaos.partition_oneway(
                {net.nodes[0].node_id}, {net.nodes[1].node_id}
            )
            await net.start()
            try:
                await net.wait_for_height(target, 90)
                assert net.hashes_agree(target)
                return (
                    net.block_fingerprints(target),
                    net.app_hash_chain(target),
                    dict(chaos.faults),
                )
            finally:
                await net.stop()

        prev = trace.RECORDER.enabled
        trace.configure(enabled=True, ring_size=8192)
        try:
            blocks1, apps1, faults1 = await one_run(seed=424)
            blocks2, apps2, faults2 = await one_run(seed=424)
        finally:
            trace.configure(enabled=prev)
        assert faults1["asym_drop"] > 0, "the partition never bit"
        assert faults1["corrupt"] > 0, "corruption never hit the byte path"
        assert faults1["clock_skew"] == 3
        assert len(blocks1) == target and all(blocks1)
        assert blocks1 == blocks2, "block bytes diverged across same-seed runs"
        assert apps1 == apps2, "app-hash chains diverged across same-seed runs"
        elapsed = time.perf_counter() - t0
        assert elapsed < 120.0, f"bit-repro smoke blew its budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_catchup_gossip_rescues_partitioned_laggard(self):
        """Satellite: the reactor's OWN catch-up gossip (not a harness
        relay) recovers a one-way-partitioned laggard. Node3 receives
        nothing while the other three keep committing (they retain >2/3
        power); on heal, donors serve stored commit precommits
        (`_send_catchup_commit_vote`), stored block parts
        (`_send_catchup_part`) and the catch-up `VoteSetMaj23` — counted
        here by instrumenting the real methods."""
        t0 = time.perf_counter()
        chaos = ChaosNetwork(ChaosConfig(seed=77))
        net = RouterNet(
            4, config=fast_config(), chaos=chaos, base_clock=frozen_clock()
        )
        laggard = net.nodes[3]
        chaos.partition_oneway(
            {n.node_id for n in net.nodes[:3]}, {laggard.node_id}
        )
        calls = {"commit_votes": 0, "parts": 0}
        orig_commit = ConsensusReactor._send_catchup_commit_vote
        orig_part = ConsensusReactor._send_catchup_part

        def count_commit(self, ps, commit):
            sent = orig_commit(self, ps, commit)
            if sent and ps.peer_id == laggard.node_id:
                calls["commit_votes"] += 1
            return sent

        def count_part(self, ps):
            sent = orig_part(self, ps)
            if sent and ps.peer_id == laggard.node_id:
                calls["parts"] += 1
            return sent

        ConsensusReactor._send_catchup_commit_vote = count_commit
        ConsensusReactor._send_catchup_part = count_part
        try:
            await net.start()
            # donors commit while the laggard is deaf
            await asyncio.gather(
                *(n.cs.wait_for_height(3, 60) for n in net.nodes[:3])
            )
            assert laggard.block_store.height() < 3, (
                "laggard was not actually partitioned"
            )
            chaos.heal()
            # recovery MUST come from reactor catch-up gossip: there is
            # no relay, no blocksync reactor in RouterNet
            await laggard.cs.wait_for_height(3, 60)
        finally:
            ConsensusReactor._send_catchup_commit_vote = orig_commit
            ConsensusReactor._send_catchup_part = orig_part
            await net.stop()
        assert calls["commit_votes"] > 0, "catch-up commit votes never flowed"
        assert calls["parts"] > 0, "catch-up block parts never flowed"
        elapsed = time.perf_counter() - t0
        assert elapsed < 90.0, f"catch-up test blew its budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_wedge_dumps_flight_recorder_and_fault_counters(self, tmp_path):
        """Watchdog contract: a genuinely wedged net (symmetric 2|2
        split of 4 validators — neither side retains +2/3) is detected,
        reported as a structured outcome, and auto-dumps the flight
        recorder + per-class chaos fault counters + per-node round
        states to disk."""
        import json

        from tendermint_tpu.libs import trace

        t0 = time.perf_counter()
        wedge = sc.Scenario(
            "wedge_probe",
            "deliberate quorum-killing split (watchdog self-test)",
            events=(sc.Event(0.4, "partition", groups=((0, 1), (2, 3))),),
        )
        prev = trace.RECORDER.enabled
        trace.configure(enabled=True, ring_size=2048)
        try:
            res = await sc.run_scenario(
                wedge,
                n_vals=4,
                target_height=6,
                seed=5,
                timeout_s=30.0,
                stall_s=4.0,
                dump_dir=str(tmp_path),
            )
        finally:
            trace.configure(enabled=prev)
        assert res.wedged and not res.ok
        assert res.dump_path, "wedge did not dump"
        payload = json.loads(open(res.dump_path).read())
        assert payload["scenario"] == "wedge_probe"
        assert payload["faults"].get("partition_drop", 0) > 0
        assert len(payload["nodes"]) == 4
        for entry in payload["nodes"]:
            assert {"height", "round", "step", "committed"} <= set(entry)
        elapsed = time.perf_counter() - t0
        assert elapsed < 60.0, f"wedge probe blew its budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_crash_fs_scenario_repairs_and_catches_up(self):
        """chaos-fs crash mid-consensus at 4 validators: the crashed
        node loses its un-fsynced WAL tail (torn), restarts on the same
        stores, repairs, and rejoins through catch-up gossip."""
        t0 = time.perf_counter()
        res = await sc.run_scenario(
            "crash_fs",
            n_vals=4,
            target_height=3,
            seed=23,
            timeout_s=60.0,
            stall_s=25.0,
        )
        assert res.ok, f"crash_fs wedged: {res.as_dict()}"
        assert all(h >= 3 for h in res.heights)
        # the crash + restart actually happened mid-run (completion is
        # gated on the event script having fired) and chaos-fs was
        # threaded under the crashed node's WAL
        assert res.events_applied.count("crash") == 1, res.events_applied
        assert res.events_applied.count("restart") == 1, res.events_applied
        assert "3" in res.fs_faults, res.fs_faults
        elapsed = time.perf_counter() - t0
        assert elapsed < 60.0, f"crash_fs smoke blew its budget: {elapsed:.1f}s"


@pytest.mark.slow
class TestScenarioSweep50:
    @pytest.mark.asyncio
    async def test_sweep_50_validators(self):
        """The 50-validator scenario sweep over real routers on a
        degree-8 topology: every named steady-rate scenario plus the
        partition/crash scripts, each bounded, each required to keep all
        50 nodes progressing."""
        names = [
            "baseline",
            "lossy_links",
            "corrupt_wire",
            "asym_partition",
            "gray_failure",
            "clock_skew",
            "crash_fs",
        ]
        results = await sc.run_sweep(
            names,
            n_vals=50,
            target_height=2,
            seed=13,
            timeout_s=300.0,
            stall_s=90.0,
            time_scale=4.0,
            degree=8,
        )
        failures = [r.as_dict() for r in results if not r.ok]
        assert not failures, f"50-validator sweep failures: {failures}"

    @pytest.mark.asyncio
    async def test_sweep_50_includes_bandwidth(self):
        """Bandwidth shaping at 50 validators: shaped links queue
        encoded bytes (the fault class the hook harness could never
        model) and consensus still completes."""
        res = await sc.run_scenario(
            "bandwidth_crunch",
            n_vals=50,
            target_height=2,
            seed=13,
            timeout_s=300.0,
            stall_s=90.0,
            time_scale=4.0,
            degree=8,
        )
        assert res.ok, res.as_dict()
        assert res.faults.get("shaped", 0) > 0, (
            "bandwidth shaping never queued a message"
        )


@pytest.mark.slow
class TestFullTaxonomySoak150:
    @pytest.mark.asyncio
    async def test_full_taxonomy_150_validators(self):
        """The 150-validator full-taxonomy soak (the committee scale the
        north-star metric and the EdDSA-vs-BLS literature are defined
        at): lossy + corrupt + shaped links, skew + drift, a gray peer,
        an asymmetric partition cycle, and a chaos-fs crash/restart —
        over real routers on a sparse seeded topology. Every node must
        progress past the target height; a wedge auto-dumps the flight
        recorder and the per-class fault counters (asserted by the
        wedge-probe test above)."""
        res = await sc.run_scenario(
            "full_taxonomy",
            n_vals=150,
            target_height=2,
            seed=29,
            timeout_s=1200.0,
            stall_s=240.0,
            time_scale=15.0,
            degree=6,
            gossip_sleep=0.4,
        )
        assert res.ok, f"150-validator soak wedged: {res.as_dict()}"
        assert len(res.heights) == 150
        assert all(h >= 2 for h in res.heights)
        # every byte-stream fault class really fired at this scale
        for cls in ("corrupt", "asym_drop", "gray_delay", "drop"):
            assert res.faults.get(cls, 0) > 0, (cls, res.faults)
        assert {"crash", "restart", "oneway", "heal"} <= set(
            res.events_applied
        ), res.events_applied
        assert res.fs_faults, "chaos-fs crash model missing from the soak"
