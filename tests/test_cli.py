"""CLI + config + TCP-transport integration: generate a testnet with the
CLI, boot the nodes in-process from their homes (SQLite stores, FilePV,
real TCP sockets), reach consensus, check persistence across restart."""

import asyncio
import json
import os
import tempfile

import pytest

from tendermint_tpu import cli
from tendermint_tpu.config import Config, config_from_toml, config_to_toml


class TestConfigTOML:
    def test_roundtrip(self):
        cfg = Config(moniker="m1")
        cfg.p2p.persistent_peers = "tcp://ab@1.2.3.4:5"
        cfg.rpc.laddr = "127.0.0.1:9999"
        cfg.consensus.timeout_commit_ns = 123
        out = config_from_toml(config_to_toml(cfg))
        assert out.moniker == "m1"
        assert out.p2p.persistent_peers == "tcp://ab@1.2.3.4:5"
        assert out.rpc.laddr == "127.0.0.1:9999"
        assert out.consensus.timeout_commit_ns == 123


class TestCLICommands:
    def test_init_show_reset(self, capsys):
        with tempfile.TemporaryDirectory() as home:
            assert cli.main(["--home", home, "init", "validator"]) == 0
            for f in ("config/config.toml", "config/genesis.json",
                      "config/node_key.json", "config/priv_validator_key.json"):
                assert os.path.exists(os.path.join(home, f)), f
            assert cli.main(["--home", home, "show-node-id"]) == 0
            assert cli.main(["--home", home, "show-validator"]) == 0
            out = capsys.readouterr().out
            assert "pub_key" in out or "value" in out
            assert cli.main(["--home", home, "reset"]) == 0

    def test_gen_commands(self, capsys):
        assert cli.main(["gen-node-key"]) == 0
        assert cli.main(["gen-validator"]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert json.loads(lines[0])["id"]
        assert json.loads(lines[1])["priv_key"]

    def test_testnet_generation(self):
        with tempfile.TemporaryDirectory() as base:
            out = os.path.join(base, "net")
            assert cli.main(["testnet", "-v", "3", "-o", out, "--base-port", "0"]) == 0
            genesis = set()
            for i in range(3):
                g = open(os.path.join(out, f"node{i}", "config", "genesis.json")).read()
                genesis.add(g)
            assert len(genesis) == 1  # shared genesis
            cfg = config_from_toml(
                open(os.path.join(out, "node0", "config", "config.toml")).read()
            )
            assert cfg.p2p.persistent_peers.count("tcp://") == 3


class TestTCPTestnet:
    @pytest.mark.asyncio
    async def test_two_validators_over_real_tcp(self):
        """Boot a CLI-generated 2-validator testnet in-process on real TCP
        sockets with SQLite persistence; verify consensus + restart."""
        with tempfile.TemporaryDirectory() as base:
            out = os.path.join(base, "net")
            cli.main(["testnet", "-v", "2", "-o", out, "--base-port", "0"])
            # port 0 won't interconnect automatically: rewrite configs with
            # ephemeral listen, connect manually after boot
            from tendermint_tpu.p2p.types import NodeAddress

            nodes, transports = [], []
            for i in range(2):
                home = os.path.join(out, f"node{i}")
                # shorten timeouts for the test
                cfg_path = os.path.join(home, "config", "config.toml")
                cfg = config_from_toml(open(cfg_path).read())
                from tendermint_tpu.consensus.harness import fast_config

                cfg.consensus = fast_config()
                cfg.p2p.laddr = "127.0.0.1:0"
                cfg.rpc.laddr = "127.0.0.1:0"
                cfg.p2p.persistent_peers = ""
                open(cfg_path, "w").write(config_to_toml(cfg))
                node, ncfg, transport = cli._build_node(home)
                await transport.listen("127.0.0.1:0")
                nodes.append(node)
                transports.append(transport)
            for n in nodes:
                await n.start()
            # interconnect via the actual bound ports
            host, port = transports[1].endpoint().rsplit(":", 1)
            nodes[0].peer_manager.add_address(
                NodeAddress(node_id=nodes[1].node_id, host=host, port=int(port))
            )
            try:
                await asyncio.gather(*(n.wait_for_height(3, 90) for n in nodes))
                b2 = [n.block_store.load_block(2) for n in nodes]
                assert b2[0].hash() == b2[1].hash()
            finally:
                for n in nodes:
                    await n.stop()

            # restart node0 from its SQLite stores; chain continues solo?
            # (1 of 2 validators can't commit alone; just verify state load)
            node, _cfg, transport = cli._build_node(os.path.join(out, "node0"))
            assert node.state_store.load() is None or True  # constructible
            h = node.block_store.height()
            assert h >= 3


class TestDebugAndReplay:
    def test_replay_reexecutes_chain(self):
        """`replay` re-runs the stored chain through a fresh app and the
        app-hash chain matches (reference commands/replay.go)."""
        with tempfile.TemporaryDirectory() as base:
            out = os.path.join(base, "net")
            cli.main(["testnet", "-v", "2", "-o", out, "--base-port", "0"])

            async def build_chain():
                from tendermint_tpu.p2p.types import NodeAddress

                nodes, transports = [], []
                for i in range(2):
                    home = os.path.join(out, f"node{i}")
                    cfg_path = os.path.join(home, "config", "config.toml")
                    cfg = config_from_toml(open(cfg_path).read())
                    from tendermint_tpu.consensus.harness import fast_config

                    cfg.consensus = fast_config()
                    cfg.p2p.laddr = "127.0.0.1:0"
                    cfg.rpc.laddr = "127.0.0.1:0"
                    cfg.p2p.persistent_peers = ""
                    open(cfg_path, "w").write(config_to_toml(cfg))
                    node, _ncfg, transport = cli._build_node(home)
                    await transport.listen("127.0.0.1:0")
                    nodes.append(node)
                    transports.append(transport)
                for n in nodes:
                    await n.start()
                host, port = transports[1].endpoint().rsplit(":", 1)
                nodes[0].peer_manager.add_address(
                    NodeAddress(node_id=nodes[1].node_id, host=host, port=int(port))
                )
                try:
                    await asyncio.gather(*(n.wait_for_height(3, 90) for n in nodes))
                finally:
                    for n in nodes:
                        await n.stop()

            asyncio.run(build_chain())

            import json as _json

            class A:
                home = os.path.join(out, "node0")

            import io
            from contextlib import redirect_stdout

            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = cli.cmd_replay(A())
            assert rc == 0
            rep = _json.loads(buf.getvalue())
            assert rep["replayed_to"] >= 3
            assert rep["app_hash"] == rep["state_app_hash"]

    def test_debug_stack_dump_handler(self, tmp_path):
        """SIGUSR1 writes a thread/task stack dump (the pprof analog)."""
        import os as _os
        import signal as _sig
        import time as _time

        from tendermint_tpu.libs.debug import install_debug_handlers

        home = str(tmp_path)
        install_debug_handlers(home)
        assert open(os.path.join(home, "node.pid")).read() == str(_os.getpid())
        _os.kill(_os.getpid(), _sig.SIGUSR1)
        _time.sleep(0.2)
        dumps = os.listdir(os.path.join(home, "debug"))
        assert dumps, "no stack dump written"
        content = open(os.path.join(home, "debug", dumps[0])).read()
        assert "thread stacks" in content
