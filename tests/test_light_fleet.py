"""LightFleet — mass light-client serving (light/fleet.py) and the live
light-client-attack evidence lifecycle (light/byzantine.py +
consensus/scenarios.run_light_attack).

Tier-1 carries: hop-proof wire/verification semantics (aggregate fold,
tampering rejected with per-scheme attribution), the verified-hop
cache's amortization + verdict equivalence against cold per-client
verification, busy-shed and coalescing, the lightd metrics fold, the
RPC busy contract, the evidence-layer LCA hardening (reactor parking on
the conflicting height, BeginBlock misbehavior conversion), and THE
acceptance test: a lunatic primary over a live RouterNet — detection →
LightClientAttackEvidence → pools → on-chain commitment → BeginBlock
misbehavior, bit-identical across same-seed runs, audited by audit_net.
The 150-validator soak is slow-marked."""

import asyncio
import dataclasses
import subprocess
import sys
import time

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.consensus import scenarios as sc
from tendermint_tpu.config import LightDConfig
from tendermint_tpu.light import fleet
from tendermint_tpu.light.client import LightClient, TrustOptions
from tendermint_tpu.light.fleet import (
    SCHEME_AGGREGATE,
    SCHEME_PER_SIG,
    HopProof,
    HopProofError,
    LightD,
    LightDBusyError,
    make_hop_proof,
    verify_hop_proof,
)
from tendermint_tpu.light.types import LightBlock, SignedHeader
from tendermint_tpu.testing import (
    make_light_chain,
    make_list_provider,
    make_validator_set,
)

CHAIN = "light-fleet-chain"
LONG_NS = 10 * 365 * 24 * 3600 * 10**9


def ListProvider(blocks):
    """Serve a prebuilt chain; height 0 = tip (shared testing helper)."""
    return make_list_provider(blocks, CHAIN)


def GatedProvider(blocks):
    """Blocks every fetch on an event — the busy-shed fixture."""
    prov = make_list_provider(blocks, CHAIN)
    prov.gate = asyncio.Event()
    inner = prov.light_block

    async def gated(height):
        await prov.gate.wait()
        return await inner(height)

    prov.light_block = gated
    return prov


def ed_chain(n=6, n_vals=4):
    vals, keys = make_validator_set(n_vals)
    return make_light_chain(n, vals, keys, CHAIN), vals


def bls_chain(n=3, n_vals=4):
    vals, keys = make_validator_set(n_vals, key_types=("bls12381",))
    return make_light_chain(n, vals, keys, CHAIN), vals


def trust_for(chain):
    return TrustOptions(period_ns=LONG_NS, height=1, hash=chain[0].header.hash())


def now_for(chain):
    return chain[-1].header.time_ns + 10**9


def tamper_commit(block: LightBlock, **changes) -> LightBlock:
    commit = dataclasses.replace(block.signed_header.commit, **changes)
    return LightBlock(SignedHeader(block.header, commit), block.validators)


# ---------------------------------------------------------------------------
# hop proofs: wire format + verification semantics


class TestHopProof:
    def test_per_sig_roundtrip_and_verify(self):
        chain, _ = ed_chain()
        proof = make_hop_proof(chain[-1])
        assert proof.scheme == SCHEME_PER_SIG
        dec = HopProof.decode(proof.encode())
        assert dec.scheme == SCHEME_PER_SIG
        assert dec.block.header.hash() == chain[-1].header.hash()
        got = verify_hop_proof(CHAIN, chain[0], dec, LONG_NS, now_for(chain))
        assert got.height == chain[-1].height

    def test_bls_commit_folds_to_aggregate(self):
        chain, _ = bls_chain()
        proof = make_hop_proof(chain[-1])
        assert proof.scheme == SCHEME_AGGREGATE
        commit = proof.block.signed_header.commit
        assert commit.is_aggregate() and len(commit.agg_sig) == 96
        # per-validator entries keep flag/address/timestamp only — the
        # flags ARE the signer bitmap
        assert all(not cs.signature for cs in commit.signatures)
        dec = HopProof.decode(proof.encode())
        got = verify_hop_proof(CHAIN, chain[0], dec, LONG_NS, now_for(chain))
        assert got.header.hash() == chain[-1].header.hash()
        # aggregate wire form is dramatically smaller than per-sig
        per_sig = make_hop_proof(chain[-1], aggregate_hops=False)
        assert per_sig.scheme == SCHEME_PER_SIG
        assert proof.wire_bytes() < per_sig.wire_bytes()

    def test_tampered_aggregate_rejected_with_scheme_attribution(self):
        chain, _ = bls_chain()
        proof = make_hop_proof(chain[-1])
        sig = proof.block.signed_header.commit.agg_sig
        bad = tamper_commit(proof.block, agg_sig=bytes([sig[0] ^ 1]) + sig[1:])
        with pytest.raises(HopProofError) as ei:
            verify_hop_proof(
                CHAIN, chain[0], HopProof(bad, SCHEME_AGGREGATE), LONG_NS,
                now_for(chain),
            )
        assert ei.value.scheme == SCHEME_AGGREGATE
        assert "[bls-aggregate]" in str(ei.value)

    def test_tampered_per_sig_rejected_with_scheme_attribution(self):
        chain, _ = ed_chain()
        proof = make_hop_proof(chain[-1])
        commit = proof.block.signed_header.commit
        s0 = commit.signatures[0]
        sigs = (
            dataclasses.replace(
                s0, signature=bytes([s0.signature[0] ^ 1]) + s0.signature[1:]
            ),
        ) + commit.signatures[1:]
        bad = tamper_commit(proof.block, signatures=sigs)
        with pytest.raises(HopProofError) as ei:
            verify_hop_proof(
                CHAIN, chain[0], HopProof(bad, SCHEME_PER_SIG), LONG_NS,
                now_for(chain),
            )
        assert ei.value.scheme == SCHEME_PER_SIG
        assert "[per-sig]" in str(ei.value)

    def test_scheme_lie_rejected_before_any_crypto(self):
        chain, _ = bls_chain()
        agg = make_hop_proof(chain[-1])
        with pytest.raises(HopProofError, match="scheme tag"):
            verify_hop_proof(
                CHAIN, chain[0], HopProof(agg.block, SCHEME_PER_SIG), LONG_NS,
                now_for(chain),
            )
        with pytest.raises(HopProofError, match="scheme tag"):
            chain2, _ = ed_chain()
            verify_hop_proof(
                CHAIN, chain2[0],
                HopProof(chain2[-1], SCHEME_AGGREGATE), LONG_NS,
                now_for(chain2),
            )


# ---------------------------------------------------------------------------
# LightD: the verified-hop cache, coalescing, busy-shed


class TestLightD:
    @pytest.mark.asyncio
    async def test_fleet_verdicts_match_cold_clients_with_amortization(self):
        """THE hop-cache contract: N clients served through one LightD
        get byte-identical verdicts to N cold per-client verifications,
        while LightD verified each hop exactly once."""
        chain, _ = ed_chain(n=6)
        target, now = 6, now_for(chain)
        n_clients = 4
        # cold baseline: every client pays its own verification
        cold_hashes = []
        cold_fetches = 0
        for _ in range(n_clients):
            prov = ListProvider(chain)
            lc = LightClient(CHAIN, trust_for(chain), prov)
            lb = await lc.verify_light_block_at_height(target, now)
            cold_hashes.append(lb.header.hash())
            cold_fetches += prov.fetches
        # fleet: one LightD, N sequential clients
        prov = ListProvider(chain)
        d = LightD(CHAIN, trust_for(chain), prov)
        await d.start()
        try:
            served = [
                (await d.sync(target, now_ns=now)).encode()
                for _ in range(n_clients)
            ]
        finally:
            await d.stop()
        assert all(
            LightBlock.decode(s).header.hash() == cold_hashes[i]
            for i, s in enumerate(served)
        )
        assert len(set(served)) == 1  # byte-identical serving
        # amortization: LightD verified the (anchor, target) hops ONCE;
        # the cold fleet fetched/verified them N times over
        assert d.stats["hops_verified"] == 2
        assert d.stats["hop_cache_hits"] == n_clients - 1
        assert prov.fetches < cold_fetches
        amortization = cold_fetches / prov.fetches
        assert amortization >= n_clients - 1

    @pytest.mark.asyncio
    async def test_concurrent_same_height_syncs_coalesce(self):
        chain, _ = ed_chain(n=4)
        prov = GatedProvider(chain)
        d = LightD(CHAIN, trust_for(chain), prov)
        await d.start()
        try:
            now = now_for(chain)
            tasks = [
                asyncio.ensure_future(d.sync(4, now_ns=now)) for _ in range(5)
            ]
            await asyncio.sleep(0.05)
            prov.gate.set()
            results = await asyncio.gather(*tasks)
        finally:
            await d.stop()
        assert len({lb.header.hash() for lb in results}) == 1
        assert d.stats["coalesced"] == 4
        assert d.stats["hops_verified"] == 2  # anchor + target, once

    @pytest.mark.asyncio
    async def test_busy_shed_is_explicit_and_counted(self):
        """The ingress backpressure contract: beyond max_sessions an
        arrival is REJECTED WITH BUSY — never queued; cache hits keep
        being served while every session slot is occupied."""
        chain, _ = ed_chain(n=6)
        prov = GatedProvider(chain)
        d = LightD(
            CHAIN, trust_for(chain), prov, config=LightDConfig(max_sessions=1)
        )
        await d.start()
        try:
            now = now_for(chain)
            t1 = asyncio.ensure_future(d.sync(4, now_ns=now))
            await asyncio.sleep(0.05)  # t1 occupies the only session
            with pytest.raises(LightDBusyError, match="busy"):
                await d.sync(5, now_ns=now)
            assert d.stats["sheds"] == 1
            prov.gate.set()
            lb = await t1
            assert lb.height == 4
            # warm heights never shed: the cache path takes no session
            prov.gate.clear()
            t2 = asyncio.ensure_future(d.sync(6, now_ns=now))
            await asyncio.sleep(0.05)
            warm = await d.sync(4, now_ns=now)
            assert warm.height == 4
            prov.gate.set()
            await t2
        finally:
            await d.stop()

    @pytest.mark.asyncio
    async def test_hop_proof_endpoint_caches_and_counts(self):
        chain, _ = bls_chain()
        d = LightD(CHAIN, trust_for(chain), ListProvider(chain))
        await d.start()
        try:
            p1 = await d.hop_proof(3)
            p2 = await d.hop_proof(3)
        finally:
            await d.stop()
        assert p1.scheme == SCHEME_AGGREGATE
        assert p1.encode() == p2.encode()
        assert d.stats["proof_cache_hits"] == 1
        assert d.stats["proofs_served"] == 2
        # the hop was VERIFIED as an aggregate too (one pairing, not
        # per-sig then refolded)
        assert d.stats["agg_hops"] > 0

    @pytest.mark.asyncio
    async def test_lightd_stats_fold_into_node_metrics(self):
        from tendermint_tpu.libs.metrics import NodeMetrics

        chain, _ = ed_chain(n=4)
        d = LightD(CHAIN, trust_for(chain), ListProvider(chain))
        await d.start()
        try:
            await d.sync(4, now_ns=now_for(chain))
            await d.sync(4, now_ns=now_for(chain))
            rendered = NodeMetrics().render()
        finally:
            await d.stop()
        assert "tendermint_tpu_lightd_syncs 2" in rendered
        assert "tendermint_tpu_lightd_hop_cache_hits 1" in rendered
        assert "tendermint_tpu_lightd_hops_verified 2" in rendered
        assert 'hops_by_scheme{scheme="per-sig"}' in rendered
        assert "lightd_sync_latency_seconds_count 2" in rendered


# ---------------------------------------------------------------------------
# the RPC surface: fleet routes + the busy contract


class _BusyLightD:
    store = None

    async def sync(self, height):
        raise LightDBusyError("lightd busy: synthetic")

    async def hop_proof(self, height):
        raise LightDBusyError("lightd busy: synthetic")


class TestProxyFleetRoutes:
    @pytest.mark.asyncio
    async def test_hop_proof_route_serves_wire_proof(self):
        from tendermint_tpu.light.proxy import LightProxyEnv

        chain, _ = bls_chain()
        d = LightD(CHAIN, trust_for(chain), ListProvider(chain))
        await d.start()
        try:
            env = LightProxyEnv(d.client, primary_rpc=None, lightd=d)
            res = await env.hop_proof(height=3)
            lb_res = await env.light_block(height=3)
        finally:
            await d.stop()
        assert res["scheme"] == SCHEME_AGGREGATE
        proof = HopProof.decode(bytes.fromhex(res["proof"]))
        assert proof.height == 3
        assert int(res["wire_bytes"]) == proof.wire_bytes()
        assert lb_res["hash"] == proof.block.header.hash().hex()

    @pytest.mark.asyncio
    async def test_busy_shed_maps_to_rpc_busy_contract(self):
        from tendermint_tpu.light.proxy import LIGHT_BUSY_CODE, LightProxyEnv
        from tendermint_tpu.rpc.core import MEMPOOL_BUSY_CODE, RPCError

        assert LIGHT_BUSY_CODE == MEMPOOL_BUSY_CODE  # ONE busy number
        env = LightProxyEnv(None, primary_rpc=None, lightd=_BusyLightD())
        for call in (env.hop_proof, env.light_block, env.header):
            with pytest.raises(RPCError) as ei:
                await call(height=3)
            assert ei.value.code == LIGHT_BUSY_CODE

    @pytest.mark.asyncio
    async def test_hop_proof_without_lightd_is_unsupported(self):
        from tendermint_tpu.light.proxy import LightProxyEnv
        from tendermint_tpu.rpc.core import RPCError

        env = LightProxyEnv(None, primary_rpc=None)
        with pytest.raises(RPCError) as ei:
            await env.hop_proof(height=1)
        assert ei.value.code == -32601

    def test_fleet_routes_are_registered(self):
        from tendermint_tpu.rpc.core import ROUTES

        assert "light_block" in ROUTES and "hop_proof" in ROUTES

    @pytest.mark.asyncio
    async def test_fleet_routes_served_over_the_wire(self):
        """A full node serves light_block + hop_proof over live HTTP
        JSON-RPC (the provider surface a remote LightD consumes), and
        the served hop proof re-verifies against the node's own chain."""
        from tests.test_rpc import rpc_net

        net, clients = await rpc_net()
        c = clients[0]
        try:
            lb_res = await c.call("light_block", height=1)
            lb = LightBlock.decode(bytes.fromhex(lb_res["light_block"]))
            assert lb.height == 1
            assert lb.header.hash().hex() == lb_res["hash"]
            hp_res = await c.call("hop_proof", height=2)
            proof = HopProof.decode(bytes.fromhex(hp_res["proof"]))
            assert proof.scheme == SCHEME_PER_SIG  # ed25519 committee
            assert int(hp_res["wire_bytes"]) == proof.wire_bytes()
            got = verify_hop_proof(
                net.genesis.chain_id, lb, proof, LONG_NS,
                proof.block.header.time_ns + 10**9,
            )
            assert got.height == 2
        finally:
            for cl in clients:
                await cl.close()
            await net.stop()


# ---------------------------------------------------------------------------
# evidence-layer hardening for LCA


class TestLCAEvidenceLayer:
    def _lca(self, conflicting, common_height=1):
        from tendermint_tpu.types.evidence import LightClientAttackEvidence

        return LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=common_height,
            byzantine_validators=(),
            total_voting_power=conflicting.validators.total_voting_power(),
            timestamp_ns=conflicting.header.time_ns,
        )

    def test_lca_hash_and_encode_are_memoized(self):
        chain, _ = ed_chain(n=3)
        ev = self._lca(chain[-1])
        h1, e1 = ev.hash(), ev.encode()
        assert ev.hash() is h1 and ev.encode() is e1  # identity: memo hit
        from tendermint_tpu.types.evidence import decode_evidence

        dec = decode_evidence(e1)
        assert dec.hash() == h1

    def test_reactor_parks_on_conflicting_height_not_common(self):
        """An LCA whose COMMON height is committed but whose conflicting
        height is still ahead of our tip parks (verify needs our own
        block at the conflicting height) instead of costing the honest
        sender a PeerError."""
        from tendermint_tpu.evidence.reactor import EvidenceReactor

        chain, _ = ed_chain(n=6)
        ev = self._lca(chain[5], common_height=1)  # conflicting height 6

        class _S:
            last_block_height = 3  # tip between common and conflicting

        class _Pool:
            state = _S()

        r = EvidenceReactor.__new__(EvidenceReactor)
        r.pool = _Pool()
        assert EvidenceReactor._verify_height(ev) == 6
        assert r._is_future(ev)
        _S.last_block_height = 6
        assert not r._is_future(ev)

    def test_misbehavior_conversion_carries_lca_attribution(self):
        """BeginBlock surface: one light_client_attack entry per
        attributed Validator (address + power from the object — the
        tuple-unpacking regression this pins)."""
        from tendermint_tpu.state.execution import evidence_to_misbehavior

        chain, vals = ed_chain(n=3)
        ev = dataclasses.replace(
            self._lca(chain[-1]),
            byzantine_validators=tuple(vals.validators[:2]),
        )
        mbs = evidence_to_misbehavior((ev,), 123)
        assert len(mbs) == 2
        assert {m.type for m in mbs} == {"light_client_attack"}
        assert [m.validator_address for m in mbs] == [
            v.address for v in vals.validators[:2]
        ]
        assert all(m.power == vals.validators[0].voting_power for m in mbs)
        assert all(m.height == ev.common_height for m in mbs)

    def test_lca_verify_memo_skips_repeat_verification(self, monkeypatch):
        """The pool's verified-LCA memo: the pairing-heavy signature
        re-check runs once per distinct evidence hash; re-asks (gossip
        re-delivery, proposal re-validation on every round) replay the
        verdict — a valid-LCA flood cannot re-melt the pool. A FAILED
        verification is never memoized."""
        from collections import OrderedDict

        from tendermint_tpu.evidence.pool import EvidenceError, EvidencePool

        chain, _ = ed_chain(n=6)
        ev = self._lca(chain[-1], common_height=1)

        class _EvParams:
            max_age_num_blocks = 1 << 20
            max_age_duration_ns = 1 << 62

        class _CP:
            evidence = _EvParams()

        class _State:
            last_block_height = 10
            last_block_time_ns = chain[-1].header.time_ns
            consensus_params = _CP()
            chain_id = CHAIN

        class _Meta:
            header = chain[0].header

        class _Store:
            def load_block_meta(self, h):
                return _Meta()

        pool = EvidencePool.__new__(EvidencePool)
        pool.state = _State()
        pool.block_store = _Store()
        pool._lca_verified = OrderedDict()

        calls = []

        def fake_verify(self, e, t):
            calls.append(e.hash())
            if getattr(fake_verify, "fail", False):
                raise EvidenceError("synthetic rejection")

        monkeypatch.setattr(
            EvidencePool, "_verify_light_client_attack", fake_verify
        )
        pool.verify(ev)
        pool.verify(ev)
        assert len(calls) == 1  # second pass answered from the memo
        # a failing verification is retried every time (a
        # not-yet-committed conflicting height legitimately becomes
        # verifiable as the tip advances)
        other = self._lca(chain[-2], common_height=1)
        fake_verify.fail = True
        for _ in range(2):
            with pytest.raises(EvidenceError):
                pool.verify(other)
        assert len(calls) == 3


# ---------------------------------------------------------------------------
# THE acceptance test: the live lunatic-attack lifecycle over RouterNet


class RecordingApp(KVStoreApp):
    def __init__(self):
        super().__init__()
        self.misbehavior: list[tuple[int, tuple]] = []

    def begin_block(self, req):
        if req.byzantine_validators:
            self.misbehavior.append(
                (req.header.height, tuple(req.byzantine_validators))
            )
        return super().begin_block(req)


class TestLunaticLifecycle:
    @pytest.mark.asyncio
    async def test_full_lifecycle_bit_identical_across_same_seed_runs(self):
        """lunatic primary → LightD witness cross-check detects →
        LightClientAttackEvidence in every honest pool → committed on
        chain within K heights → BeginBlock misbehavior names the
        colluding signers — audited by audit_net, and two same-seed
        runs produce bit-identical block AND evidence bytes."""
        t0 = time.perf_counter()
        apps: dict[int, RecordingApp] = {}

        def app_factory(i):
            if i == 0:
                apps[i] = RecordingApp()
                return apps[i]
            return None

        async def one_run():
            apps.clear()
            r = await sc.run_light_attack(
                n_vals=3, seed=11, k_heights=3, timeout_s=90.0,
                app_factory=app_factory,
            )
            r["misbehavior"] = list(apps.get(0).misbehavior if apps else [])
            return r

        r1 = await one_run()
        r2 = await one_run()

        # -- lifecycle, stage by stage (run 1) --------------------------
        assert r1["outcome"] == "ok", (r1["error"], r1["audit"])
        assert r1["divergence_detected"] and r1["served_forged"] >= 1
        assert r1["lightd_stats"]["divergences"] == 1
        assert len(r1["traitors"]) == 2  # > 1/3 of a 3-val committee
        assert r1["lca_committed_at"] is not None
        assert r1["time_to_lca_commit_heights"] <= 3
        audit = r1["audit"]
        assert audit["ok"], audit
        assert not audit["conflicting_commits"]  # honest safety held
        assert set(audit["lca_commit_heights"]) == set(r1["traitors"])
        assert not audit["missing_lca"]
        # the ABCI surface: BeginBlock carried one entry per colluder
        assert r1["misbehavior"], "app never saw the LCA misbehavior"
        mb_height, mbs = r1["misbehavior"][0]
        assert mb_height == r1["lca_committed_at"]
        assert {m.type for m in mbs} == {"light_client_attack"}
        assert {m.validator_address.hex() for m in mbs} == set(r1["traitors"])

        # -- bit-identity across same-seed runs -------------------------
        assert r2["outcome"] == "ok", (r2["error"], r2["audit"])
        assert r1["blocks_hex"] == r2["blocks_hex"], (
            "block bytes diverged across same-seed lunatic runs"
        )
        assert r1["lca_evidence_hex"] == r2["lca_evidence_hex"]
        assert r1["lca_evidence_hex"], "no evidence bytes captured"
        assert r1["traitors"] == r2["traitors"]
        elapsed = time.perf_counter() - t0
        assert elapsed < 120.0, f"lifecycle test blew its budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_adjacent_forgery_rejected_before_witness_check(self):
        """Negative control: a forged hop ADJACENT to the trust anchor
        dies on next_validators_hash pinning (a VerificationError, not a
        Divergence) — the reason lunatic attacks need skipping hops."""
        with pytest.raises(ValueError, match="non-adjacent"):
            await sc.run_light_attack(n_vals=3, attack_offset=1)


class TestContainment:
    def test_production_import_graph_never_reaches_lunatic_provider(self):
        code = (
            "import sys\n"
            "import tendermint_tpu.node, tendermint_tpu.cli\n"
            "import tendermint_tpu.light.fleet, tendermint_tpu.light.proxy\n"
            "bad = [m for m in sys.modules if 'byzantine' in m]\n"
            "assert not bad, f'production wiring reaches {bad}'\n"
            "print('CONTAINED')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "CONTAINED" in out.stdout


@pytest.mark.slow
class TestLightFleet150:
    @pytest.mark.asyncio
    async def test_lunatic_attack_150_validator_soak(self):
        """The committee-scale soak: the same lifecycle at 150
        validators over real routers (bit-identity is not asserted at
        this scale — commit signer sets float above the f=0 pinning
        construction; safety, detection and accountability still bind)."""
        r = await sc.run_light_attack(
            n_vals=150,
            seed=7,
            k_heights=6,
            timeout_s=900.0,
            commit_window_s=30.0,
        )
        assert r["outcome"] == "ok", (r["error"], r["audit"])
        assert r["divergence_detected"]
        audit = r["audit"]
        assert audit["ok"], audit
        assert not audit["conflicting_commits"]
        assert set(audit["lca_commit_heights"]) == set(r["traitors"])
