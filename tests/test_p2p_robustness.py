"""Flow-rate limiting, persistent address book, and seed mode
(reference internal/libs/flowrate, internal/p2p/pex/addrbook.go,
node/node.go:490 makeSeedNode)."""

import asyncio
import os
import time

import pytest

from tendermint_tpu.libs.flowrate import Meter, RateLimiter
from tendermint_tpu.p2p.addrbook import AddressBook
from tendermint_tpu.p2p.peermanager import PeerManager
from tendermint_tpu.p2p.types import NodeAddress


class TestRateLimiter:
    @pytest.mark.asyncio
    async def test_throttles_to_rate(self):
        limiter = RateLimiter(rate=100_000, burst=10_000)
        t0 = time.monotonic()
        total = 0
        for _ in range(10):
            await limiter.throttle(5_000)
            total += 5_000
        dt = time.monotonic() - t0
        # 50 KB at 100 KB/s with a 10 KB burst: >= ~0.35s
        assert dt >= 0.3, f"finished too fast: {dt:.3f}s for {total} bytes"
        assert dt < 1.5, f"over-throttled: {dt:.3f}s"

    @pytest.mark.asyncio
    async def test_unlimited_passes_through(self):
        limiter = RateLimiter(rate=0)
        t0 = time.monotonic()
        for _ in range(100):
            await limiter.throttle(10**9)
        assert time.monotonic() - t0 < 0.1

    @pytest.mark.asyncio
    async def test_burst_credit(self):
        limiter = RateLimiter(rate=1_000, burst=50_000)
        t0 = time.monotonic()
        await limiter.throttle(40_000)  # within burst: immediate
        assert time.monotonic() - t0 < 0.05

    def test_meter(self):
        m = Meter()
        m.update(1000)
        assert m.total == 1000


class TestTCPFlowRate:
    @pytest.mark.asyncio
    async def test_rate_limited_transfer_is_bounded(self):
        """Two real TCP connections with a 50 KB/s send limit: pushing
        100 KB must take >= ~1s and nothing is dropped."""
        from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
        from tendermint_tpu.p2p.tcp import TCPTransport
        from tendermint_tpu.p2p.types import NodeAddress, NodeInfo

        lt = TCPTransport(send_rate=50_000, recv_rate=0)
        await lt.listen("127.0.0.1:0")
        host, _, port = lt.endpoint().rpartition(":")
        dt_ = TCPTransport(send_rate=50_000, recv_rate=0)

        k1, k2 = Ed25519PrivKey(b"\x01" * 32), Ed25519PrivKey(b"\x02" * 32)
        from tendermint_tpu.p2p.types import node_id_from_pubkey

        i1 = NodeInfo(node_id=node_id_from_pubkey(k1.pub_key()), network="t", moniker="a")
        i2 = NodeInfo(node_id=node_id_from_pubkey(k2.pub_key()), network="t", moniker="b")

        dial_task = asyncio.ensure_future(
            dt_.dial(NodeAddress(node_id=i1.node_id, protocol="tcp", host="127.0.0.1", port=int(port)))
        )
        server_conn = await lt.accept()
        client_conn = await dial_task
        hs_server = asyncio.ensure_future(server_conn.handshake(i1, k1))
        await client_conn.handshake(i2, k2)
        await hs_server

        payload = os.urandom(10_000)
        n_msgs = 10  # ~100 KB total at 50 KB/s -> >= ~1.5s after burst

        async def recv_all():
            got = 0
            while got < n_msgs:
                _ch, data = await server_conn.receive_message()
                assert data == payload
                got += 1
            return got

        recv_task = asyncio.ensure_future(recv_all())
        t0 = time.monotonic()
        for _ in range(n_msgs):
            await client_conn.send_message(0x21, payload)
        got = await asyncio.wait_for(recv_task, timeout=20)
        dt = time.monotonic() - t0
        assert got == n_msgs  # zero drops under throttling
        assert dt >= 0.8, f"rate limit not applied: {dt:.2f}s for 100KB at 50KB/s"
        await client_conn.close()
        await server_conn.close()
        await lt.close()


class TestAddressBook:
    def test_roundtrip(self, tmp_path):
        book = AddressBook(str(tmp_path / "addrbook.json"))
        addr = NodeAddress(node_id="ab" * 20, protocol="tcp", host="10.0.0.1", port=26656)
        book.save(
            [{"address": addr, "persistent": True, "good": True, "attempts": 2}]
        )
        loaded = AddressBook(str(tmp_path / "addrbook.json")).load()
        assert len(loaded) == 1
        assert str(loaded[0]["address"]) == str(addr)
        assert loaded[0]["persistent"] and loaded[0]["good"]

    def test_corrupt_file_tolerated(self, tmp_path):
        path = tmp_path / "addrbook.json"
        path.write_text("{not json")
        assert AddressBook(str(path)).load() == []

    def test_peer_manager_persistence(self, tmp_path):
        path = str(tmp_path / "addrbook.json")
        pm = PeerManager("ff" * 20, addr_book=AddressBook(path))
        addr = NodeAddress(node_id="cd" * 20, protocol="tcp", host="10.0.0.2", port=26656)
        pm.add_address(addr, persistent=True)
        pm.save_addr_book()

        pm2 = PeerManager("ff" * 20, addr_book=AddressBook(path))
        known = pm2.all_known()
        assert [str(a) for a in known] == [str(addr)]


class TestSeedMode:
    @pytest.mark.asyncio
    async def test_seed_serves_addresses_then_disconnects(self):
        """A seed-mode PEX reactor pushes its address book at a fresh peer
        and posts a disconnect error shortly after."""
        from tendermint_tpu.p2p.pex import (
            PEX_CHANNEL,
            PexReactor,
            PexResponse,
            encode_message,
            decode_message,
        )
        from tendermint_tpu.p2p.peermanager import PeerStatus, PeerUpdate
        from tendermint_tpu.p2p.router import Channel

        pm = PeerManager("aa" * 20)
        pm.add_address(
            NodeAddress(node_id="bb" * 20, protocol="tcp", host="10.1.1.1", port=1)
        )
        ch = Channel(PEX_CHANNEL, "pex", 1, encode_message, decode_message)
        updates: asyncio.Queue = asyncio.Queue()
        reactor = PexReactor(
            pm, ch, updates, seed_mode=True, seed_disconnect_after=0.2
        )
        await reactor.start()
        try:
            await updates.put(PeerUpdate("cc" * 20, PeerStatus.UP))
            env = await asyncio.wait_for(ch.out_q.get(), timeout=5)
            while not isinstance(env.message, PexResponse):
                env = await asyncio.wait_for(ch.out_q.get(), timeout=5)
            assert env.to == "cc" * 20
            assert any("10.1.1.1" in a for a in env.message.addresses)
            err = await asyncio.wait_for(ch.err_q.get(), timeout=5)
            assert err.node_id == "cc" * 20
        finally:
            await reactor.stop()
