"""Flow-rate limiting, persistent address book, and seed mode
(reference internal/libs/flowrate, internal/p2p/pex/addrbook.go,
node/node.go:490 makeSeedNode)."""

import asyncio
import os
import time

import pytest

from tendermint_tpu.libs.flowrate import Meter, RateLimiter
from tendermint_tpu.p2p.addrbook import AddressBook
from tendermint_tpu.p2p.peermanager import PeerManager
from tendermint_tpu.p2p.types import NodeAddress


class TestRateLimiter:
    @pytest.mark.asyncio
    async def test_throttles_to_rate(self):
        limiter = RateLimiter(rate=100_000, burst=10_000)
        t0 = time.monotonic()
        total = 0
        for _ in range(10):
            await limiter.throttle(5_000)
            total += 5_000
        dt = time.monotonic() - t0
        # 50 KB at 100 KB/s with a 10 KB burst: >= ~0.35s
        assert dt >= 0.3, f"finished too fast: {dt:.3f}s for {total} bytes"
        assert dt < 1.5, f"over-throttled: {dt:.3f}s"

    @pytest.mark.asyncio
    async def test_unlimited_passes_through(self):
        limiter = RateLimiter(rate=0)
        t0 = time.monotonic()
        for _ in range(100):
            await limiter.throttle(10**9)
        assert time.monotonic() - t0 < 0.1

    @pytest.mark.asyncio
    async def test_burst_credit(self):
        limiter = RateLimiter(rate=1_000, burst=50_000)
        t0 = time.monotonic()
        await limiter.throttle(40_000)  # within burst: immediate
        assert time.monotonic() - t0 < 0.05

    def test_meter(self):
        m = Meter()
        m.update(1000)
        assert m.total == 1000


class TestTCPFlowRate:
    @pytest.mark.asyncio
    async def test_rate_limited_transfer_is_bounded(self):
        """Two real TCP connections with a 50 KB/s send limit: pushing
        100 KB must take >= ~1s and nothing is dropped."""
        from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
        from tendermint_tpu.p2p.tcp import TCPTransport
        from tendermint_tpu.p2p.types import NodeAddress, NodeInfo

        lt = TCPTransport(send_rate=50_000, recv_rate=0)
        await lt.listen("127.0.0.1:0")
        host, _, port = lt.endpoint().rpartition(":")
        dt_ = TCPTransport(send_rate=50_000, recv_rate=0)

        k1, k2 = Ed25519PrivKey(b"\x01" * 32), Ed25519PrivKey(b"\x02" * 32)
        from tendermint_tpu.p2p.types import node_id_from_pubkey

        i1 = NodeInfo(node_id=node_id_from_pubkey(k1.pub_key()), network="t", moniker="a")
        i2 = NodeInfo(node_id=node_id_from_pubkey(k2.pub_key()), network="t", moniker="b")

        dial_task = asyncio.ensure_future(
            dt_.dial(NodeAddress(node_id=i1.node_id, protocol="tcp", host="127.0.0.1", port=int(port)))
        )
        server_conn = await lt.accept()
        client_conn = await dial_task
        hs_server = asyncio.ensure_future(server_conn.handshake(i1, k1))
        await client_conn.handshake(i2, k2)
        await hs_server

        payload = os.urandom(10_000)
        n_msgs = 10  # ~100 KB total at 50 KB/s -> >= ~1.5s after burst

        async def recv_all():
            got = 0
            while got < n_msgs:
                _ch, data = await server_conn.receive_message()
                assert data == payload
                got += 1
            return got

        recv_task = asyncio.ensure_future(recv_all())
        t0 = time.monotonic()
        for _ in range(n_msgs):
            await client_conn.send_message(0x21, payload)
        got = await asyncio.wait_for(recv_task, timeout=20)
        dt = time.monotonic() - t0
        assert got == n_msgs  # zero drops under throttling
        assert dt >= 0.8, f"rate limit not applied: {dt:.2f}s for 100KB at 50KB/s"
        await client_conn.close()
        await server_conn.close()
        await lt.close()


class TestAddressBook:
    def test_roundtrip(self, tmp_path):
        book = AddressBook(str(tmp_path / "addrbook.json"))
        addr = NodeAddress(node_id="ab" * 20, protocol="tcp", host="10.0.0.1", port=26656)
        book.save(
            [{"address": addr, "persistent": True, "good": True, "attempts": 2}]
        )
        loaded = AddressBook(str(tmp_path / "addrbook.json")).load()
        assert len(loaded) == 1
        assert str(loaded[0]["address"]) == str(addr)
        assert loaded[0]["persistent"] and loaded[0]["good"]

    def test_corrupt_file_tolerated(self, tmp_path):
        path = tmp_path / "addrbook.json"
        path.write_text("{not json")
        assert AddressBook(str(path)).load() == []

    def test_peer_manager_persistence(self, tmp_path):
        path = str(tmp_path / "addrbook.json")
        pm = PeerManager("ff" * 20, addr_book=AddressBook(path))
        addr = NodeAddress(node_id="cd" * 20, protocol="tcp", host="10.0.0.2", port=26656)
        pm.add_address(addr, persistent=True)
        pm.save_addr_book()

        pm2 = PeerManager("ff" * 20, addr_book=AddressBook(path))
        known = pm2.all_known()
        assert [str(a) for a in known] == [str(addr)]


class TestSeedMode:
    @pytest.mark.asyncio
    async def test_seed_serves_addresses_then_disconnects(self):
        """A seed-mode PEX reactor pushes its address book at a fresh peer
        and posts a disconnect error shortly after."""
        from tendermint_tpu.p2p.pex import (
            PEX_CHANNEL,
            PexReactor,
            PexResponse,
            encode_message,
            decode_message,
        )
        from tendermint_tpu.p2p.peermanager import PeerStatus, PeerUpdate
        from tendermint_tpu.p2p.router import Channel

        pm = PeerManager("aa" * 20)
        pm.add_address(
            NodeAddress(node_id="bb" * 20, protocol="tcp", host="10.1.1.1", port=1)
        )
        ch = Channel(PEX_CHANNEL, "pex", 1, encode_message, decode_message)
        updates: asyncio.Queue = asyncio.Queue()
        reactor = PexReactor(
            pm, ch, updates, seed_mode=True, seed_disconnect_after=0.2
        )
        await reactor.start()
        try:
            await updates.put(PeerUpdate("cc" * 20, PeerStatus.UP))
            env = await asyncio.wait_for(ch.out_q.get(), timeout=5)
            while not isinstance(env.message, PexResponse):
                env = await asyncio.wait_for(ch.out_q.get(), timeout=5)
            assert env.to == "cc" * 20
            assert any("10.1.1.1" in a for a in env.message.addresses)
            err = await asyncio.wait_for(ch.err_q.get(), timeout=5)
            assert err.node_id == "cc" * 20
        finally:
            await reactor.stop()


# ---------------------------------------------------------------------------
# Chaos-net fault injection (libs/chaos.py): seeded matrix over real routers
# ---------------------------------------------------------------------------

from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork, _corrupt  # noqa: E402
from tests.chaos_net import ChaosSyncNet, run_chaos_sync  # noqa: E402


class TestChaosUnit:
    def test_seeded_plan_is_deterministic(self):
        cfg = ChaosConfig(
            seed=99, drop_rate=0.2, delay_ms=50.0, duplicate_rate=0.1,
            reorder_rate=0.1, corrupt_rate=0.1,
        )
        plans_a = [ChaosNetwork(cfg).plan("a", "b", 0x40) for _ in range(1)]
        net1, net2 = ChaosNetwork(cfg), ChaosNetwork(cfg)
        seq1 = [net1.plan("a", "b", 0x40) for _ in range(200)]
        seq2 = [net2.plan("a", "b", 0x40) for _ in range(200)]
        assert seq1 == seq2
        assert net1.faults == net2.faults
        assert plans_a is not None  # silence lints; determinism shown above

    def test_partition_semantics_and_heal(self):
        net = ChaosNetwork(ChaosConfig(seed=1))
        net.partition({"a", "b"}, {"c"})
        assert net.partitioned("a", "c") and net.partitioned("c", "b")
        assert not net.partitioned("a", "b")
        # ungrouped nodes see everyone
        assert not net.partitioned("a", "zzz")
        assert net.plan("a", "c", 0).drop
        assert net.faults["partition_drop"] == 1
        net.heal()
        assert not net.partitioned("a", "c")

    def test_corrupt_flips_exactly_one_byte(self):
        data = bytes(range(64))
        out = _corrupt(data, 1337)
        assert len(out) == len(data)
        assert sum(1 for x, y in zip(data, out) if x != y) == 1

    def test_per_channel_override(self):
        cfg = ChaosConfig(
            seed=5, drop_rate=0.0,
            per_channel={0x40: ChaosConfig(drop_rate=1.0)},
        )
        net = ChaosNetwork(cfg)
        assert net.plan("a", "b", 0x40).drop  # blocksync channel: all dropped
        assert not net.plan("a", "b", 0x30).drop

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("TMTPU_CHAOS_SEED", "42")
        monkeypatch.setenv("TMTPU_CHAOS_DROP", "0.25")
        monkeypatch.setenv("TMTPU_CHAOS_DELAY_MS", "10")
        cfg = ChaosConfig.from_env()
        assert cfg.seed == 42 and cfg.drop_rate == 0.25 and cfg.delay_ms == 10.0
        assert cfg.enabled()
        assert not ChaosConfig().enabled()


class TestChaosMatrix:
    """A 4-node in-process net (1 source + 3 syncers over real routers)
    must reach the target height under each fault class. The chain is
    deterministic, so the synced hashes are the source's — asserted by
    uniqueness across nodes."""

    @pytest.mark.asyncio
    async def test_drop(self):
        _target, hashes, faults = await run_chaos_sync(
            ChaosConfig(seed=7, drop_rate=0.1), n_sync=3, timeout=75
        )
        assert len(set(hashes)) == 1
        assert faults["drop"] > 0

    @pytest.mark.asyncio
    async def test_delay(self):
        _target, hashes, faults = await run_chaos_sync(
            ChaosConfig(seed=8, delay_ms=100.0), n_sync=3, timeout=75
        )
        assert len(set(hashes)) == 1
        assert faults["delay"] > 0

    @pytest.mark.asyncio
    async def test_duplicate_reorder_corrupt(self):
        _target, hashes, faults = await run_chaos_sync(
            ChaosConfig(
                seed=5, duplicate_rate=0.05, reorder_rate=0.05, corrupt_rate=0.02
            ),
            n_sync=2,
            timeout=75,
        )
        assert len(set(hashes)) == 1
        assert faults["duplicate"] + faults["reorder"] + faults["corrupt"] > 0

    @pytest.mark.asyncio
    async def test_partition_and_heal(self):
        _target, hashes, faults = await run_chaos_sync(
            ChaosConfig(seed=9, delay_ms=40.0),
            n_blocks=32,
            n_sync=3,
            partition_cycle=True,
            partition_at=0.2,
            partition_for=1.5,
            timeout=75,
        )
        assert len(set(hashes)) == 1


class TestChaosSmoke:
    @pytest.mark.asyncio
    async def test_acceptance_scenario_bit_reproducible(self):
        """THE acceptance scenario: fixed seed, 10% drop + 100 ms p50
        delay + one partition-and-heal cycle; the 4-node net reaches the
        target height and TWO invocations produce identical block hashes
        at that height."""
        cfg = dict(
            n_blocks=16,
            n_sync=3,
            partition_cycle=True,
            partition_at=0.5,
            partition_for=1.0,
            timeout=75,
        )
        chaos = ChaosConfig(seed=1234, drop_rate=0.1, delay_ms=100.0)
        target1, hashes1, faults1 = await run_chaos_sync(chaos, **cfg)
        target2, hashes2, faults2 = await run_chaos_sync(chaos, **cfg)
        assert target1 == target2
        assert len(set(hashes1)) == 1, "nodes diverged within run 1"
        assert hashes1 == hashes2, "runs are not bit-reproducible"
        # the fault classes actually fired
        assert faults1["drop"] > 0 and faults1["delay"] > 0
        assert faults1["partition_drop"] > 0


@pytest.mark.slow
class TestChaosSoak:
    @pytest.mark.asyncio
    async def test_soak_repeated_partitions_under_loss(self):
        """~60 s soak: a longer chain synced under sustained drop+delay
        with repeated partition/heal cycles; every node must converge to
        the source chain."""
        from tendermint_tpu.testing import build_kvstore_chain

        bstore, sstore, conns, genesis, _ = await build_kvstore_chain(
            96, 3, chain_id="chaos-chain"
        )
        net = ChaosSyncNet(
            genesis,
            bstore,
            sstore.load(),
            ChaosConfig(seed=4242, drop_rate=0.05, delay_ms=50.0),
            n_sync=3,
            window=8,
        )
        target = 95
        await net.start()
        try:
            ids = [n.node_id for n in net.nodes]
            deadline = asyncio.get_running_loop().time() + 60
            cycle = 0
            # keep cycling partitions for the full soak window (≥12
            # cycles ≈ 50 s) even if the chain syncs early — late cycles
            # exercise the caught-up/resume path under faults too
            while asyncio.get_running_loop().time() < deadline:
                synced = (
                    min(n.block_store.height() for n in net.sync_nodes) >= target
                )
                if synced and cycle >= 12:
                    break
                # alternate split shapes so every node gets isolated
                if cycle % 2 == 0:
                    net.chaos.partition(set(ids[:2]), set(ids[2:]))
                else:
                    net.chaos.partition({ids[0], ids[3]}, {ids[1], ids[2]})
                await asyncio.sleep(1.5)
                net.chaos.heal()
                await asyncio.sleep(2.5)
                cycle += 1
            await net.wait_synced(target, timeout=30)
            assert len(set(net.hashes_at(target))) == 1
            assert net.chaos.faults["partition_drop"] > 0
        finally:
            await net.stop()
            await conns.stop()
