"""Blocksync resilience: adaptive per-peer RTO + health scoring +
timeout bans (pool), and the commit-verification regression from
ADVICE.md — the FIRST block applied after startup/resume must be
full-signature-verified (commit_verified=False), because a range batch
proves the commits for its own heights, never the commit for the height
below its first block."""

import asyncio
import time

import pytest

from tendermint_tpu.blocksync import BLOCKSYNC_CHANNEL
from tendermint_tpu.blocksync import messages as bsm
from tendermint_tpu.blocksync import pool as pool_mod
from tendermint_tpu.blocksync.pool import BlockPool
from tendermint_tpu.blocksync.reactor import BlockSyncReactor
from tendermint_tpu.p2p.peermanager import PeerStatus, PeerUpdate
from tendermint_tpu.p2p.router import Channel
from tendermint_tpu.p2p.types import Envelope


class _FakeBlock:
    def __init__(self, height: int):
        self.header = type("H", (), {"height": height})()


class TestAdaptiveTimeouts:
    def test_rto_learns_from_rtt_samples(self):
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 100)
        p = pool.peers["p1"]
        assert p.request_timeout() == pool_mod.INITIAL_REQUEST_TIMEOUT
        for _ in range(8):
            p.observe_rtt(0.05)
        # Jacobson RTO = srtt + 4*rttvar, floored
        assert (
            pool_mod.MIN_REQUEST_TIMEOUT
            <= p.request_timeout()
            <= 0.05 * 8  # well under the old fixed 15 s
        )

    def test_rto_doubles_per_consecutive_timeout(self):
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 100)
        p = pool.peers["p1"]
        p.observe_rtt(0.1)
        base = p.request_timeout()
        p.timeouts = 2
        assert p.request_timeout() == pytest.approx(min(base * 4, pool_mod.REQUEST_TIMEOUT))
        p.timeouts = 30  # ceiling holds
        assert p.request_timeout() == pool_mod.REQUEST_TIMEOUT

    def test_block_arrival_records_rtt_and_resets_timeouts(self):
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 100)
        reqs = pool.next_requests()
        assert reqs and reqs[0][1] == "p1"
        pool.peers["p1"].timeouts = 3
        h = reqs[0][0]
        pool.add_block("p1", _FakeBlock(h))
        p = pool.peers["p1"]
        assert p.srtt > 0 and p.timeouts == 0 and p.blocks_served == 1

    def test_health_prefers_responsive_peer(self):
        pool = BlockPool(1)
        pool.set_peer_range("fast", 1, 100)
        pool.set_peer_range("flaky", 1, 100)
        pool.peers["fast"].observe_rtt(0.01)
        pool.peers["flaky"].observe_rtt(0.01)
        pool.peers["flaky"].timeouts = 2
        picked = {pool._pick_peer(h).peer_id for h in range(1, 4)}
        assert picked == {"fast"}

    def test_ban_after_consecutive_timeouts_with_cooldown(self):
        pool = BlockPool(1)
        pool.set_peer_range("p1", 1, 100)
        p = pool.peers["p1"]
        p.observe_rtt(0.001)  # tiny RTO so timeouts fire immediately
        for _ in range(pool_mod.BAN_AFTER_TIMEOUTS):
            reqs = pool.next_requests()
            assert reqs, "peer should still be assignable before the ban"
            # age every outstanding request past any RTO
            for req in pool.requests.values():
                req.time -= pool_mod.REQUEST_TIMEOUT + 1
            p.timeouts = p.timeouts  # (clarity: consecutive count grows below)
            pool.next_requests()
            if "p1" not in pool.peers:
                break
        assert pool.take_banned() == ["p1"]
        assert pool.take_banned() == []  # drained
        # quarantined: re-registration is ignored until the cooldown passes
        pool.set_peer_range("p1", 1, 100)
        assert "p1" not in pool.peers
        pool._ban_until["p1"] = time.monotonic() - 1  # cooldown elapsed
        pool.set_peer_range("p1", 1, 100)
        assert "p1" in pool.peers


def _make_sync_stack(genesis, window):
    """Fresh store/executor/reactor wired to a bare channel (the
    test_blocksync_rotation serve pattern)."""
    from tendermint_tpu.abci.kvstore import KVStoreApp
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.proxy import AppConns
    from tendermint_tpu.state.execution import BlockExecutor
    from tendermint_tpu.state.state import state_from_genesis
    from tendermint_tpu.state.store import StateStore
    from tendermint_tpu.store.blockstore import BlockStore
    from tendermint_tpu.store.db import MemDB

    async def build():
        app = KVStoreApp()
        conns = AppConns.local(app)
        await conns.start()
        bstore, sstore = BlockStore(MemDB()), StateStore(MemDB())
        state = await Handshaker(
            sstore, state_from_genesis(genesis), bstore, genesis
        ).handshake(conns)
        sstore.save(state)
        ex = BlockExecutor(sstore, conns.consensus, block_store=bstore)
        ch = Channel(
            BLOCKSYNC_CHANNEL, "bs", 5, bsm.encode_message, bsm.decode_message
        )
        peer_q: asyncio.Queue = asyncio.Queue()
        reactor = BlockSyncReactor(
            state, ex, bstore, ch, peer_q, window=window, active=True
        )
        return conns, bstore, ex, ch, peer_q, reactor

    return build()


class TestFirstBlockFullVerify:
    @pytest.mark.asyncio
    async def test_first_block_after_start_and_resume_full_verified(self):
        """The first block applied after startup AND after resume() must
        take the full apply-time verification path (commit_verified=False);
        blocks whose predecessor commit a range batch proved may skip."""
        from tendermint_tpu.testing import build_kvstore_chain

        n_blocks = 20
        src_store, _sstore, src_conns, genesis, _ = await build_kvstore_chain(
            n_blocks, 3, chain_id="fv-chain"
        )
        conns, bstore, ex, ch, peer_q, reactor = await _make_sync_stack(
            genesis, window=6
        )
        applied: list[tuple[int, bool]] = []
        orig_apply = ex.apply_block

        async def spy_apply(state, block_id, block, commit_verified=False):
            applied.append((block.header.height, commit_verified))
            return await orig_apply(
                state, block_id, block, commit_verified=commit_verified
            )

        ex.apply_block = spy_apply

        # phase 1: serve only the first 12 heights (simulates the peer's
        # visible head); phase 2 extends to the full chain after resume
        served_height = 12

        async def serve():
            while True:
                env = await ch.out_q.get()
                msg = env.message
                if isinstance(msg, bsm.StatusRequest):
                    await ch.in_q.put(
                        Envelope(
                            BLOCKSYNC_CHANNEL,
                            bsm.StatusResponse(served_height, src_store.base()),
                            from_="peer0",
                        )
                    )
                elif isinstance(msg, bsm.BlockRequest):
                    blk = (
                        src_store.load_block(msg.height)
                        if msg.height <= served_height
                        else None
                    )
                    if blk is not None:
                        await ch.in_q.put(
                            Envelope(
                                BLOCKSYNC_CHANNEL,
                                bsm.BlockResponse(blk),
                                from_="peer0",
                            )
                        )
                    else:
                        await ch.in_q.put(
                            Envelope(
                                BLOCKSYNC_CHANNEL,
                                bsm.NoBlockResponse(msg.height),
                                from_="peer0",
                            )
                        )

        server = asyncio.get_running_loop().create_task(serve())
        await peer_q.put(PeerUpdate("peer0", PeerStatus.UP))
        await reactor.start()
        try:
            await asyncio.wait_for(reactor.synced.wait(), timeout=60)
            assert bstore.height() >= served_height - 1
            # startup: first applied block full-verified, the rest of its
            # range batch-proven
            assert applied[0][0] == 1 and applied[0][1] is False
            in_range = [cv for h, cv in applied if 2 <= h <= 6]
            assert any(in_range), "batch proof never exercised"

            # phase 2: the chain grew while we were in consensus; resume
            applied.clear()
            served_height = n_blocks
            # the peer advertises its taller chain before we switch back
            await ch.in_q.put(
                Envelope(
                    BLOCKSYNC_CHANNEL,
                    bsm.StatusResponse(served_height, src_store.base()),
                    from_="peer0",
                )
            )
            await asyncio.sleep(0.1)
            reactor.resume(reactor.state)
            await asyncio.wait_for(reactor.synced.wait(), timeout=60)
            assert bstore.height() >= n_blocks - 1
            first_h, first_cv = applied[0]
            assert first_cv is False, (
                f"first block after resume (h={first_h}) skipped full verify"
            )
            assert any(cv for _h, cv in applied[1:]), (
                "post-resume range batches never proved commits"
            )
        finally:
            server.cancel()
            await reactor.stop()
            await conns.stop()
            await src_conns.stop()
