"""RouterNet-XL (ISSUE 18 tentpole): multi-process committees over real
sockets. Covers the bounded control-frame codec (bomb frames must die
before allocation), the pure cross-process helpers (identity/slice
derivations every process must agree on), the tier-1 acceptance e2e —
2 workers x 2 nodes over TCP with the full SecretConnection handshake,
surviving kill_worker + restart_worker with app-hash chains identical
to an in-process control run — and the slow-marked socket-layer
taxonomy sweep + 500-validator soak."""

from __future__ import annotations

import asyncio
import time

import pytest

from tendermint_tpu.consensus import routernet_xl as xl
from tendermint_tpu.consensus import scenarios as sc
from tendermint_tpu.libs import protoenc as pe


class TestControlCodec:
    def test_all_frames_roundtrip(self):
        msgs = [
            xl.CtlHello(2, ((0, "127.0.0.1:5000"), (3, "/tmp/n3.sock"))),
            xl.CtlTopology(((1, "127.0.0.1:1"), (7, "h:9"))),
            xl.CtlGo(True),
            xl.CtlGo(False),
            xl.CtlEvent("partition", node=0, groups_json='[[0], ["rest"]]'),
            xl.CtlEvent("gray", node=3, delay_us=1500, power=2),
            xl.CtlStatus(1, ((0, 5), (7, 9))),
            xl.CtlStop(True),
            xl.CtlStop(False),
            xl.CtlReport(
                0,
                (xl.NodeReport(4, 2, (b"a", b"b"), (b"c", b"d"), 1),),
                b'{"x": 1}',
                "boom",
            ),
        ]
        for m in msgs:
            assert xl.decode_ctl(xl.encode_ctl(m)) == m

    def test_negative_node_index_roundtrips(self):
        # Event.node = -1 means "last node" (resolved mod n); the wire
        # carries it as an unsigned 32-bit wrap
        c = xl.CtlEvent("crash", node=-1)
        assert xl.decode_ctl(xl.encode_ctl(c)).node == -1

    def test_empty_chain_hashes_keep_alignment(self):
        # height 1's app_hash is b"" (genesis) — default-elision must
        # NOT shift later heights down a slot (that would fabricate
        # cross-node conflicts between nodes at different heights)
        nr = xl.NodeReport(0, 3, (b"", b"x", b"y"), (b"a", b"", b"c"), 0)
        got = xl.decode_ctl(xl.encode_ctl(xl.CtlReport(1, (nr,)))).nodes[0]
        assert got.app_hashes == (b"", b"x", b"y")
        assert got.block_hashes == (b"a", b"", b"c")

    def test_event_conversion_roundtrips(self):
        ev = sc.Event(
            1.5, "oneway", src=(0, 1), dst=("rest",), node=-2,
            delay_ms=2.5, power=3,
        )
        got = xl.ctl_to_event(xl.decode_ctl(xl.encode_ctl(xl.event_to_ctl(ev))))
        assert (got.action, got.src, got.dst, got.node, got.power) == (
            ev.action, ev.src, ev.dst, ev.node, ev.power,
        )
        assert abs(got.delay_ms - ev.delay_ms) < 1e-9
        ev = sc.Event(0.0, "partition", groups=((0,), ("rest",)))
        got = xl.ctl_to_event(xl.decode_ctl(xl.encode_ctl(xl.event_to_ctl(ev))))
        assert got.groups == ((0,), ("rest",))

    @pytest.mark.asyncio
    async def test_oversized_frame_dies_before_allocation(self):
        # a bomb length header must be rejected from the 4 prefix bytes
        # alone — never buffered
        reader = asyncio.StreamReader()
        reader.feed_data((xl.MAX_CTL_FRAME + 1).to_bytes(4, "big"))
        with pytest.raises(ValueError, match="oversized control frame"):
            await xl.read_ctl(reader)

    def test_endpoint_bomb_rejected(self):
        body = pe.varint_field(1, xl.CTL_TOPOLOGY)
        ep = pe.varint_field(1, 1) + pe.string_field(2, "h:1")
        body += pe.message_field(3, ep) * (xl.MAX_XL_NODES + 1)
        with pytest.raises(ValueError, match="xl endpoints"):
            xl.decode_ctl(body)

    def test_chain_bomb_rejected(self):
        entry = pe.message_field(3, pe.bytes_field(1, b"h"))
        nr = pe.varint_field(1, 0) + entry * (xl.MAX_XL_CHAIN + 1)
        body = pe.varint_field(1, xl.CTL_REPORT) + pe.message_field(3, nr)
        with pytest.raises(ValueError, match="xl app hashes"):
            xl.decode_ctl(body)

    def test_diag_bomb_rejected(self):
        body = pe.varint_field(1, xl.CTL_REPORT) + pe.bytes_field(
            4, b"x" * (xl.MAX_XL_DIAG + 1)
        )
        with pytest.raises(ValueError, match="diag blob"):
            xl.decode_ctl(body)

    @pytest.mark.asyncio
    async def test_write_refuses_oversized_frame(self):
        msg = xl.CtlReport(0, (), b"x" * (xl.MAX_CTL_FRAME + 1), "")
        with pytest.raises(ValueError, match="exceeds bound"):
            await xl.write_ctl(None, msg)  # raises before touching writer


class TestCrossProcessDerivations:
    def test_node_id_matches_router_shell(self):
        from tendermint_tpu.p2p.memory import MemoryNetwork
        from tendermint_tpu.p2p.testing import RouterShell

        sh = RouterShell(MemoryNetwork(), 5, "chain", key_seed="routernet")
        assert xl.xl_node_id(5) == sh.node_id

    def test_slice_assignment_is_balanced_and_total(self):
        for n, k in ((4, 2), (5, 2), (500, 4), (7, 3), (3, 3)):
            slices = xl.slice_assignment(n, k)
            assert len(slices) == k
            flat = [i for s in slices for i in s]
            assert flat == list(range(n))
            sizes = [len(s) for s in slices]
            assert max(sizes) - min(sizes) <= 1

    def test_preload_txs_deterministic(self):
        assert xl.preload_txs(7, 3) == xl.preload_txs(7, 3)
        assert xl.preload_txs(7, 3) != xl.preload_txs(8, 3)
        for tx in xl.preload_txs(1, 4):
            assert b"=" in tx  # valid kvstore txs

    def test_xl_topology_bounds_cross_slice_links(self):
        """The locality topology: connected, deterministic, and the
        cross-slice (= encrypted real-socket) edge count bounded by
        bridges per slice pair — the property that makes a
        500-validator soak wall-feasible on images with pure-Python
        AEAD."""
        for n, k, bridges in ((500, 4, 4), (50, 2, 3), (7, 3, 2)):
            slices = xl.slice_assignment(n, k)
            edges = xl.xl_topology_edges(n, 8, 17, slices, bridges)
            assert edges == xl.xl_topology_edges(n, 8, 17, slices, bridges)
            owner = {i: w for w, s in enumerate(slices) for i in s}
            cross = [
                (a, b) for a, b in edges if owner[a] != owner[b]
            ]
            assert 0 < len(cross) <= k * (k - 1) // 2 * bridges
            # every slice pair is bridged
            pairs = {
                tuple(sorted((owner[a], owner[b]))) for a, b in cross
            }
            assert len(pairs) == k * (k - 1) // 2
            # connectivity over the whole graph (BFS)
            adj: dict[int, list[int]] = {i: [] for i in range(n)}
            for a, b in edges:
                adj[a].append(b)
                adj[b].append(a)
            seen, frontier = {0}, [0]
            while frontier:
                nxt = []
                for v in frontier:
                    for u in adj[v]:
                        if u not in seen:
                            seen.add(u)
                            nxt.append(u)
                frontier = nxt
            assert len(seen) == n


class TestXLProcessE2E:
    @pytest.mark.asyncio
    async def test_two_workers_tcp_kill_restart_matches_control(self):
        """The acceptance e2e: 2 worker processes x 2 nodes each over
        TCP with the full SecretConnection handshake commit blocks,
        survive kill_worker (SIGKILL: torn WAL tails on both slice
        nodes) + restart_worker (durable-store respawn + WAL repair +
        re-handshake + catch-up), and produce the SAME app-hash chain
        as an in-process control run fed the identical preload — the
        wall-clock determinism contract."""
        t0 = time.perf_counter()
        seed, preload_n, target = 11, 6, 3
        txs = xl.preload_txs(seed, preload_n)

        # in-process control: same genesis derivation, same preload
        from tendermint_tpu.consensus.routernet import RouterNet

        control = RouterNet(4, use_hub=False, topo_seed=seed)
        try:
            for node in control.nodes:
                await node.prepare()
            control._connect()
            for node in control.nodes:
                for tx in txs:
                    await node.inner.mempool.check_tx(tx)
            await asyncio.gather(*(n.go() for n in control.nodes))
            await asyncio.wait_for(control.wait_for_height(target, 60.0), 60.0)
            control_chain = control.app_hash_chain(target)
        finally:
            await control.stop()

        out = await xl.run_xl(
            "baseline",
            n_vals=4,
            workers=2,
            transport="tcp",
            seed=seed,
            target_height=target,
            preload=preload_n,
            timeout_s=150.0,
            stall_s=60.0,
            process_events=(
                sc.Event(2.0, "kill_worker", node=1),
                sc.Event(4.0, "restart_worker", node=1),
            ),
        )
        assert out["outcome"] == "ok", out
        assert out["process_events_applied"] == [
            "kill_worker:1", "restart_worker:1",
        ], out["process_events_applied"]
        assert set(out["heights"]) == {0, 1, 2, 3}
        assert all(h >= target for h in out["heights"].values()), out["heights"]
        # the aggregated audit: zero conflicting commits across every
        # process, every worker's local audit_net clean
        assert out["audit"]["ok"], out["audit"]
        assert out["audit"]["block_conflicts"] == []
        assert out["audit"]["app_conflicts"] == []
        # identical app-hash chains vs the in-process control run
        xl_chain = [bytes.fromhex(h) for h in out["app_hash_chain"]]
        assert len(xl_chain) >= target
        for h0 in range(target):
            assert xl_chain[h0] == control_chain[h0], (
                f"app-hash divergence at height {h0 + 1}"
            )
        elapsed = time.perf_counter() - t0
        assert elapsed < 150.0, f"XL e2e blew its budget: {elapsed:.1f}s"

    @pytest.mark.asyncio
    async def test_socket_chaos_events_apply_cross_process(self):
        """scenarios.py taxonomy events over real TCP links: the
        asymmetric-partition script applies at the socket frame
        boundary (asym_drop faults counted by the workers' seeded
        chaos) and the committee still converges."""
        out = await xl.run_xl(
            "asym_partition",
            n_vals=4,
            workers=2,
            transport="tcp",
            seed=2,
            target_height=3,
            preload=4,
            timeout_s=150.0,
            stall_s=60.0,
        )
        assert out["outcome"] == "ok", out
        assert out["events_applied"] == ["oneway", "heal"]
        assert out["faults"].get("asym_drop", 0) > 0, out["faults"]
        assert out["audit"]["ok"], out["audit"]


@pytest.mark.slow
class TestXLSlowSoaks:
    @pytest.mark.asyncio
    async def test_uds_churn_and_inworker_crash(self):
        """UDS transport variant + live validator churn + an in-worker
        crash/restart (listener re-bind + re-Hello + topology
        rebroadcast), each a full XL run."""
        out = await xl.run_xl(
            "validator_churn",
            n_vals=4, workers=2, transport="unix", seed=5,
            target_height=3, preload=4, timeout_s=240.0, stall_s=90.0,
        )
        assert out["outcome"] == "ok", out
        assert out["events_applied"] == [
            "churn_join", "churn_rogue_join", "churn_power", "churn_leave",
        ]
        out = await xl.run_xl(
            "baseline",
            n_vals=4, workers=2, transport="tcp", seed=6,
            target_height=3, preload=4, timeout_s=240.0, stall_s=90.0,
            process_events=(
                sc.Event(1.5, "crash", node=2),
                sc.Event(3.0, "restart", node=2),
            ),
        )
        assert out["outcome"] == "ok", out
        assert out["events_applied"] == ["crash", "restart"]

    @pytest.mark.asyncio
    async def test_verifyd_sigkill_degrades_inline(self):
        """Workers share ONE verifyd sidecar via TMTPU_VERIFYD_SOCK;
        SIGKILLing it mid-soak must degrade every worker to inline-local
        verification (client breaker) — never wedge the committee."""
        out = await xl.run_xl(
            "baseline",
            n_vals=4, workers=2, transport="tcp", seed=3,
            target_height=3, preload=4, timeout_s=300.0, stall_s=120.0,
            use_verifyd=True,
            process_events=(sc.Event(1.0, "kill_verifyd", node=0),),
        )
        assert out["outcome"] == "ok", out
        assert out["process_events_applied"] == ["kill_verifyd"]
        # the daemon is dead: the post-run stats probe must see nothing
        assert out["verifyd"] is None

    @pytest.mark.asyncio
    async def test_full_chaos_taxonomy_over_sockets(self):
        """Every named scenario — link faults, clock faults, chaos-fs
        crashes, validator churn, the Byzantine strategies, and the
        everything-at-once scripts — executed over real TCP sockets
        with per-link seeded chaos, 2 worker processes each. The
        socket-layer mirror of the in-process taxonomy sweeps."""
        t0 = time.perf_counter()
        failures = []
        for i, name in enumerate(sorted(sc.SCENARIOS)):
            out = await xl.run_xl(
                name,
                n_vals=4,
                workers=2,
                transport="tcp",
                seed=31 + i,
                target_height=3,
                preload=4,
                timeout_s=420.0,
                stall_s=150.0,
            )
            if out["outcome"] != "ok":
                failures.append({k: out[k] for k in (
                    "scenario", "outcome", "heights", "audit",
                    "worker_errors", "error", "dump_paths",
                )})
        assert not failures, f"socket taxonomy failures: {failures}"
        elapsed = time.perf_counter() - t0
        assert elapsed < 3000.0, f"taxonomy sweep budget blown: {elapsed:.0f}s"

    @pytest.mark.asyncio
    async def test_500_validator_multiprocess_soak(self):
        """The headline scale target: 500 validators split across 4
        worker processes, cross-slice links over TCP with the full
        SecretConnection handshake, one shared verifyd amortizing
        signature verification host-wide. Explicit wall budget for the
        1-core box; MemDB stores (no restart events — durability is the
        e2e's job) keep 500 nodes from writing 1500 SQLite files.
        1-core pacing: gossip_sleep=1.0 (the default 0.3 s is ~17k
        gossip-loop wakes/s host-wide — loop overhead alone saturates
        the core; slower wakes push bigger VoteBatch deltas per frame)
        and degree=4 (host work per height scales with the link count
        n·degree/2 — each link carries the ~1000-vote set once). The
        gate is every one of the 500 validators committing height 1
        (full quorum + full propagation across 4 processes); the
        frontier typically runs heights ahead of the last straggler."""
        t0 = time.perf_counter()
        out = await xl.run_xl(
            "baseline",
            n_vals=500,
            workers=4,
            transport="tcp",
            seed=17,
            target_height=1,
            preload=4,
            durable=False,
            use_verifyd=True,
            gossip_sleep=1.0,
            degree=4,
            timeout_s=3600.0,
            stall_s=1800.0,
        )
        assert out["outcome"] == "ok", {
            k: out[k] for k in (
                "outcome", "honest_min", "worker_errors", "error", "audit",
            )
        }
        assert out["honest_min"] >= 1
        assert len(out["heights"]) == 500
        assert out["audit"]["ok"], out["audit"]
        # cross-tenant amortization: the shared daemon actually served
        stats = out["verifyd"]
        assert stats, "verifyd stats missing after the soak"
        elapsed = time.perf_counter() - t0
        assert elapsed < 4200.0, f"500-val soak blew its budget: {elapsed:.0f}s"
