"""Tests for libs: protoenc determinism/roundtrip, BitArray semantics."""

from tendermint_tpu.libs import protoenc as pe
from tendermint_tpu.libs.bits import BitArray


def test_uvarint_roundtrip():
    for v in [0, 1, 127, 128, 300, 2**32, 2**63 - 1, 2**64 - 1]:
        r = pe.Reader(pe.uvarint(v))
        assert r.read_uvarint() == v
        assert r.eof()


def test_varint_field_default_elision():
    assert pe.varint_field(1, 0) == b""
    assert pe.bytes_field(2, b"") == b""
    assert pe.sfixed64_field(3, 0) == b""


def test_negative_varint_matches_proto_two_complement():
    # proto3 int64 -1 encodes as 10 bytes of 0xff...0x01
    data = pe.varint_field(1, -1)
    r = pe.Reader(data)
    field, wt = r.read_tag()
    assert field == 1 and wt == pe.WIRE_VARINT
    v = r.read_uvarint()
    assert v == 2**64 - 1


def test_sfixed64_roundtrip():
    data = pe.sfixed64_field(5, -42)
    r = pe.Reader(data)
    field, wt = r.read_tag()
    assert field == 5 and wt == pe.WIRE_FIXED64
    assert r.read_sfixed64() == -42


def test_message_field_emits_empty():
    assert pe.message_field(1, b"") != b""


def test_bitarray_basic():
    ba = BitArray(10)
    assert ba.is_empty() and not ba.is_full()
    ba.set(3, True)
    ba.set(9, True)
    assert ba.get(3) and ba.get(9) and not ba.get(4)
    assert ba.true_indices() == [3, 9]
    assert ba.num_true() == 2
    assert not ba.set(10, True)  # out of range
    assert not ba.get(100)


def test_bitarray_full_and_not():
    ba = BitArray(9)
    for i in range(9):
        ba.set(i, True)
    assert ba.is_full()
    inv = ba.not_()
    assert inv.is_empty()


def test_bitarray_sub_or():
    a = BitArray.from_indices(8, [0, 1, 2])
    b = BitArray.from_indices(8, [1, 3])
    assert a.sub(b).true_indices() == [0, 2]
    assert a.or_(b).true_indices() == [0, 1, 2, 3]
    assert a.and_(b).true_indices() == [1]


def test_bitarray_bytes_roundtrip():
    a = BitArray.from_indices(20, [0, 13, 19])
    b = BitArray.from_bytes(20, a.to_bytes())
    assert a == b


class TestLoopWatchdog:
    def test_wedged_loop_dumps_stacks_once(self, tmp_path):
        import asyncio
        import time

        from tendermint_tpu.libs.watchdog import LoopWatchdog

        async def main():
            wd = LoopWatchdog(str(tmp_path), threshold_s=0.3, interval_s=0.1)
            wd.start()
            await asyncio.sleep(0.2)  # loop healthy: no report
            assert wd.reports == []
            time.sleep(1.0)  # wedge the loop (blocking sleep inline)
            await asyncio.sleep(0.5)  # recover; watchdog re-arms
            wd.stop()
            return wd.reports

        reports = asyncio.run(main())
        assert len(reports) == 1, reports
        text = open(reports[0]).read()
        assert "event loop unresponsive" in text
        assert "thread" in text

    def test_healthy_loop_never_reports(self, tmp_path):
        import asyncio

        from tendermint_tpu.libs.watchdog import LoopWatchdog

        async def main():
            wd = LoopWatchdog(str(tmp_path), threshold_s=0.5, interval_s=0.05)
            wd.start()
            await asyncio.sleep(0.8)
            wd.stop()
            return wd.reports

        assert asyncio.run(main()) == []
