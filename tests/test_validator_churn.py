"""Validator-set churn as a live scenario axis (ISSUE 18 satellite 3):
the typed `val:` tx format, the PoP-on-update defense at the mempool/app
boundary (PR 9's rogue-key closure exercised post-genesis for the first
time), and join/leave/power-shift landing in the consensus validator
set while the committee keeps committing."""

from __future__ import annotations

import asyncio
import hashlib

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.consensus import scenarios as sc
from tendermint_tpu.crypto import bls, ed25519


class TestValidatorTxFormat:
    def _parse(self, tx: bytes) -> abci.ValidatorUpdate:
        return KVStoreApp._parse_validator_tx(tx)

    def test_legacy_ed25519(self):
        priv = ed25519.Ed25519PrivKey.generate()
        pub = priv.pub_key().bytes()
        vu = self._parse(b"val:" + pub.hex().encode() + b"!7")
        assert vu == abci.ValidatorUpdate("ed25519", pub, 7)

    def test_typed_ed25519(self):
        priv = ed25519.Ed25519PrivKey.generate()
        pub = priv.pub_key().bytes()
        vu = self._parse(b"val:ed25519:" + pub.hex().encode() + b"!3")
        assert vu == abci.ValidatorUpdate("ed25519", pub, 3)

    def test_bls_join_with_valid_pop(self):
        priv = bls.BLSPrivKey(hashlib.sha256(b"churn-ok").digest())
        pub, pop = priv.pub_key().bytes(), priv.pop_prove()
        tx = (
            b"val:bls12381:" + pub.hex().encode() + b"!5!" + pop.hex().encode()
        )
        vu = self._parse(tx)
        assert vu == abci.ValidatorUpdate("bls12381", pub, 5, pop)

    def test_bls_join_without_pop_rejected(self):
        priv = bls.BLSPrivKey(hashlib.sha256(b"churn-rogue").digest())
        tx = b"val:bls12381:" + priv.pub_key().bytes().hex().encode() + b"!5"
        with pytest.raises(ValueError, match="proof of possession"):
            self._parse(tx)

    def test_bls_join_with_forged_pop_rejected(self):
        priv = bls.BLSPrivKey(hashlib.sha256(b"churn-forge").digest())
        other = bls.BLSPrivKey(hashlib.sha256(b"other-key").digest())
        tx = (
            b"val:bls12381:"
            + priv.pub_key().bytes().hex().encode()
            + b"!5!"
            + other.pop_prove().hex().encode()
        )
        with pytest.raises(ValueError, match="proof of possession"):
            self._parse(tx)

    def test_bls_leave_needs_no_pop(self):
        priv = bls.BLSPrivKey(hashlib.sha256(b"churn-leave").digest())
        tx = b"val:bls12381:" + priv.pub_key().bytes().hex().encode() + b"!0"
        assert self._parse(tx).power == 0

    def test_bad_inputs_rejected(self):
        priv = ed25519.Ed25519PrivKey.generate()
        pub_hex = priv.pub_key().bytes().hex().encode()
        for tx, pat in (
            (b"val:" + pub_hex, "val:<hex pubkey>"),
            (b"val:" + pub_hex + b"!-2", "negative power"),
            (b"val:zz!1", "bad validator tx encoding"),
            (b"val:" + b"ab" * 8 + b"!1", "bad validator pubkey"),
            (b"val:nosuchtype:" + pub_hex + b"!1", "bad validator pubkey"),
        ):
            with pytest.raises(ValueError, match=pat):
                self._parse(tx)

    def test_checktx_and_delivertx_reject_rogue(self):
        app = KVStoreApp()
        priv = bls.BLSPrivKey(hashlib.sha256(b"churn-e2e").digest())
        tx = b"val:bls12381:" + priv.pub_key().bytes().hex().encode() + b"!5"
        assert app.check_tx(abci.RequestCheckTx(tx)).code == 2
        app.begin_block(abci.RequestBeginBlock(b"", None, abci.LastCommitInfo(0)))
        assert app.deliver_tx(abci.RequestDeliverTx(tx)).code == 2
        assert app.end_block(abci.RequestEndBlock(1)).validator_updates == ()


class TestChurnScenarioRegistry:
    def test_registered_with_all_axes(self):
        s = sc.SCENARIOS["validator_churn"]
        actions = [e.action for e in s.events]
        assert actions == [
            "churn_join", "churn_rogue_join", "churn_power", "churn_leave",
        ]
        assert s.chaos.drop_rate > 0  # churn composes with link chaos

    def test_churn_join_key_is_deterministic(self):
        a = sc.churn_join_key(7, 100).pub_key().bytes()
        b = sc.churn_join_key(7, 100).pub_key().bytes()
        c = sc.churn_join_key(8, 100).pub_key().bytes()
        assert a == b != c


class TestLiveChurn:
    @pytest.mark.asyncio
    async def test_churn_lands_in_consensus_validator_set(self):
        """Join + power-shift + leave flow through the mempool into
        EndBlock validator updates and land in the CONSENSUS validator
        set (not just the app's mirror) while the committee keeps
        committing; the rogue bls join bounces off every mempool."""
        from tendermint_tpu.consensus.harness import GENESIS_TIME_NS, MS
        from tendermint_tpu.consensus.routernet import RouterNet
        from tendermint_tpu.consensus.scenarios import (
            Event,
            _churn_tx,
            _inject_tx,
            churn_join_key,
        )
        from tendermint_tpu.libs.clock import ManualClock

        net = RouterNet(
            4, base_clock=ManualClock(GENESIS_TIME_NS - 500 * MS), topo_seed=7
        )
        seed = 7
        try:
            await asyncio.wait_for(net.start(), 60.0)

            async def wait_set(pred, what, timeout=30.0):
                async def _poll():
                    while True:
                        vals = net.nodes[0].cs.rs.validators
                        by_addr = {
                            v.address: v.voting_power for v in vals.validators
                        }
                        if pred(by_addr):
                            return by_addr
                        await asyncio.sleep(0.05)

                try:
                    return await asyncio.wait_for(_poll(), timeout)
                except asyncio.TimeoutError:
                    raise AssertionError(f"churn never applied: {what}")

            join_addr = churn_join_key(seed, 100).pub_key().address()
            v1_addr = net.keys[1].pub_key().address()
            v3_addr = net.keys[3].pub_key().address()

            tx, rej = _churn_tx(Event(0, "churn_join", node=100), net, seed)
            assert not rej
            await _inject_tx(net, tx, expect_reject=False)
            await wait_set(lambda m: m.get(join_addr) == 1, "join")

            # the rogue bls12381 join must bounce off EVERY mempool —
            # _inject_tx raises if any node accepts it
            tx, rej = _churn_tx(Event(0, "churn_rogue_join", node=5), net, seed)
            assert rej
            await _inject_tx(net, tx, expect_reject=True)

            tx, _ = _churn_tx(Event(0, "churn_power", node=1, power=3), net, seed)
            await _inject_tx(net, tx, expect_reject=False)
            await wait_set(lambda m: m.get(v1_addr) == 3, "power shift")

            tx, _ = _churn_tx(Event(0, "churn_leave", node=3), net, seed)
            await _inject_tx(net, tx, expect_reject=False)
            left = await wait_set(lambda m: v3_addr not in m, "leave")
            assert left.get(join_addr) == 1 and left.get(v1_addr) == 3

            # the committee (including the now non-validator node 3)
            # keeps committing after the full churn sequence
            h = min(net.heights())
            await asyncio.wait_for(net.wait_for_height(h + 1, 30.0), 30.0)
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_validator_churn_scenario_smoke(self):
        """The registered scenario runs end to end under link chaos with
        a clean audit — the tier-1 smoke the slow sweeps scale up."""
        res = await sc.run_scenario(
            "validator_churn", n_vals=4, target_height=3, seed=3,
            timeout_s=90.0, stall_s=30.0,
        )
        assert res.ok, res.as_dict()
        assert res.events_applied == [
            "churn_join", "churn_rogue_join", "churn_power", "churn_leave",
        ]
        assert not res.error, res.error
        assert res.audit and res.audit["ok"], res.audit
