"""WireGen: the compiled hot codec is pinned to the interpreted one.

Four contracts, each structural rather than aspirational:

  * **determinism** — the same lockfile always renders the identical
    module (`scripts/wiregen --update` twice is a no-op), and the
    checked-in module IS that render (`--check` is the CI wiring; the
    wiregen-drift tmtlint rule enforces the same thing in the tier-1
    lint gate);
  * **bit identity** — seeded structured frames for every generated
    family encode to the same bytes and decode to equal objects under
    both codecs, and malformed frames (truncations, bit flips, garbage
    tails) raise the identical error class AND message;
  * **bounds** — the generated decoders read the owning module's MAX_*
    bounds at call time, so a monkeypatched-down bound rejects with the
    interpreted codec's exact message;
  * **dispatch** — `use_wiregen` / `TMTPU_WIREGEN` really swap the hot
    entry points, and the speedup the generator exists for is measured
    (slow-marked microbench).
"""

from __future__ import annotations

import copy
import os
import random
import subprocess
import sys
import time
import zlib

import pytest

from tendermint_tpu.consensus import messages as cm
from tendermint_tpu.consensus import wire_gen as wg
from tendermint_tpu.tools.wiregen import generator as wgen
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.block import NIL_BLOCK_ID, BlockID, PartSetHeader
from tendermint_tpu.types.keys import SignedMsgType
from tendermint_tpu.types.part_set import Part
from tendermint_tpu.types.vote import Proposal, Vote
from tendermint_tpu.crypto.merkle import Proof

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GEN_PATH = os.path.join(REPO, wgen.GENERATED_REL)


# ---------------------------------------------------------------------------
# seeded structured-frame generators (the fuzz A/B harness)


class FrameGen:
    """Seeded random generator for every compiled frame family."""

    def __init__(self, seed: int):
        self.rng = random.Random(seed)

    def rbytes(self, n: int) -> bytes:
        r = self.rng
        return bytes(r.getrandbits(8) for _ in range(r.randint(0, n)))

    def rbits(self) -> BitArray:
        r = self.rng
        n = r.randint(0, 200)
        b = BitArray(n)
        for i in range(n):
            if r.random() < 0.5:
                b.set(i, True)
        return b

    def rpsh(self) -> PartSetHeader:
        return PartSetHeader(self.rng.randint(0, 1000), self.rbytes(32))

    def rbid(self) -> BlockID:
        if self.rng.random() < 0.2:
            return NIL_BLOCK_ID
        return BlockID(self.rbytes(32), self.rpsh())

    def rts(self) -> int:
        r = self.rng
        return r.randint(0, 2**40) * r.choice([1, 1_000_000_000]) + r.randint(
            0, 999
        )

    def rproof(self) -> Proof:
        r = self.rng
        return Proof(
            r.randint(0, 100),
            r.randint(0, 99),
            self.rbytes(32),
            tuple(self.rbytes(32) for _ in range(r.randint(0, 5))),
        )

    def rpart(self) -> Part:
        return Part(self.rng.randint(0, 50), self.rbytes(100), self.rproof())

    def rvote(self) -> Vote:
        r = self.rng
        return Vote(
            type=r.choice(list(SignedMsgType)),
            height=r.randint(0, 2**40),
            round=r.randint(0, 100),
            block_id=self.rbid(),
            timestamp_ns=self.rts(),
            validator_address=self.rbytes(20),
            validator_index=r.randint(-1, 100),
            signature=self.rbytes(64),
        )

    def rprop(self) -> Proposal:
        r = self.rng
        return Proposal(
            height=r.randint(0, 2**40),
            round=r.randint(0, 100),
            pol_round=r.randint(-1, 50),
            block_id=self.rbid(),
            timestamp_ns=self.rts(),
            signature=self.rbytes(64),
        )

    def rhv(self) -> cm.HasVoteMessage:
        r = self.rng
        return cm.HasVoteMessage(
            r.randint(0, 2**40),
            r.randint(-1, 100),
            r.choice(list(SignedMsgType)),
            r.randint(-1, 1000),
        )

    # one constructor per envelope family, keyed for parametrization
    def message(self, family: str) -> cm.Message:
        r = self.rng
        if family == "NewRoundStep":
            return cm.NewRoundStepMessage(
                r.randint(0, 2**40),
                r.randint(-1, 100),
                r.randint(0, 8),
                r.randint(0, 10**6),
                r.randint(-1, 100),
            )
        if family == "NewValidBlock":
            return cm.NewValidBlockMessage(
                r.randint(0, 2**40),
                r.randint(0, 100),
                (r.randint(0, 1000), self.rbytes(32)),
                self.rbits(),
                r.random() < 0.5,
            )
        if family == "Proposal":
            return cm.ProposalMessage(self.rprop())
        if family == "ProposalPOL":
            return cm.ProposalPOLMessage(
                r.randint(0, 2**40), r.randint(0, 100), self.rbits()
            )
        if family == "BlockPart":
            return cm.BlockPartMessage(
                r.randint(0, 2**40), r.randint(0, 100), self.rpart()
            )
        if family == "Vote":
            return cm.VoteMessage(self.rvote())
        if family == "VoteBatch":
            return cm.VoteBatchMessage(
                tuple(self.rvote() for _ in range(r.randint(0, 8)))
            )
        if family == "HasVote":
            return self.rhv()
        if family == "HasVoteBatch":
            return cm.HasVoteBatchMessage(
                tuple(self.rhv() for _ in range(r.randint(0, 8)))
            )
        if family == "VoteSetMaj23":
            return cm.VoteSetMaj23Message(
                r.randint(0, 2**40),
                r.randint(0, 100),
                r.choice(list(SignedMsgType)),
                self.rbid(),
            )
        assert family == "VoteSetBits"
        return cm.VoteSetBitsMessage(
            r.randint(0, 2**40),
            r.randint(0, 100),
            r.choice(list(SignedMsgType)),
            self.rbid(),
            self.rbits(),
        )


FAMILIES = (
    "NewRoundStep",
    "NewValidBlock",
    "Proposal",
    "ProposalPOL",
    "BlockPart",
    "Vote",
    "VoteBatch",
    "HasVote",
    "HasVoteBatch",
    "VoteSetMaj23",
    "VoteSetBits",
)


def _outcome(fn, data):
    try:
        return ("ok", fn(data))
    except Exception as e:  # noqa: BLE001 — the exception IS the datum
        return (type(e).__name__, str(e))


# ---------------------------------------------------------------------------
# generation determinism + CLI + CI wiring


def test_generate_is_deterministic_and_matches_checked_in():
    lock = wgen.load_lock()
    a = wgen.generate(lock)
    b = wgen.generate(lock)
    assert a == b  # byte-determinism of the render itself
    with open(GEN_PATH, encoding="utf-8") as f:
        assert f.read() == a  # checked-in module IS the render
    assert wgen.schema_hash(lock) in a  # lockfile hash pinned in header


def test_scripts_wiregen_check_is_green():
    """THE CI wiring: the tier-1 suite shells the same `--check` a
    pipeline would, so a stale generated module fails CI even without
    the lint gate."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wiregen"), "--check"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "is fresh" in out.stdout


def test_check_and_update_on_a_stale_tree(tmp_path):
    lockdir = tmp_path / "tendermint_tpu" / "tools" / "lint"
    lockdir.mkdir(parents=True)
    gendir = tmp_path / "tendermint_tpu" / "consensus"
    gendir.mkdir(parents=True)
    with open(os.path.join(REPO, wgen.LOCKFILE_REL), encoding="utf-8") as f:
        (lockdir / "wire_schema.lock.json").write_text(f.read())
    (gendir / "wire_gen.py").write_text("# stale\n")
    repo = str(tmp_path)
    assert any("stale" in p for p in wgen.check(repo))
    assert wgen.update(repo) is True  # rewrote
    assert wgen.check(repo) == []  # now fresh
    assert wgen.update(repo) is False  # idempotent — byte-identical


def test_spec_mismatch_refuses_generation():
    lock = copy.deepcopy(wgen.load_lock())
    entry = lock["files"]["tendermint_tpu/crypto/merkle.py"]
    # a renumbered field in a compiled family must refuse, not miscompile
    entry["encoders"]["Proof.encode"] = ["6:varint", "2:varint", "3:bytes", "4:message"]
    with pytest.raises(wgen.SpecMismatch, match="Proof.encode"):
        wgen.generate(lock)
    # a dropped decode bound must refuse too — the generated codec
    # carries the clamp
    lock = copy.deepcopy(wgen.load_lock())
    entry = lock["files"]["tendermint_tpu/crypto/merkle.py"]
    entry["bounds"] = []
    with pytest.raises(wgen.SpecMismatch, match="MAX_PROOF_AUNTS"):
        wgen.generate(lock)


# ---------------------------------------------------------------------------
# the wiregen-drift lint rule (fixture-driven)


def _drift_findings(tree, lock, full_tree=False):
    from tendermint_tpu.tools.lint.framework import Allowlist, lint_tree
    from tendermint_tpu.tools.lint.rules.wiregen_rules import WiregenDrift

    fs = lint_tree(tree, [WiregenDrift(lock=lock)], Allowlist(), full_tree=full_tree)
    return [f for f in fs if f.rule == "wiregen-drift"]


def test_drift_rule_clean_on_fresh_module():
    lock = wgen.load_lock()
    with open(GEN_PATH, encoding="utf-8") as f:
        fresh = f.read()
    assert _drift_findings({wgen.GENERATED_REL: fresh}, lock) == []


def test_drift_rule_fires_on_hand_edit():
    lock = wgen.load_lock()
    with open(GEN_PATH, encoding="utf-8") as f:
        edited = f.read() + "\n# sneaky\n"
    fs = _drift_findings({wgen.GENERATED_REL: edited}, lock)
    assert len(fs) == 1 and "byte-identical" in fs[0].message
    assert "scripts/wiregen --update" in fs[0].message


def test_drift_rule_fires_on_lockfile_change_without_regen():
    """A re-blessed wire schema (here: a retuned bound set) changes the
    schema hash, so the checked-in module is stale until regenerated."""
    lock = copy.deepcopy(wgen.load_lock())
    lock["files"]["tendermint_tpu/crypto/merkle.py"]["bounds"] = [
        "MAX_PROOF_AUNTS=64",
    ]
    with open(GEN_PATH, encoding="utf-8") as f:
        checked_in = f.read()
    fs = _drift_findings({wgen.GENERATED_REL: checked_in}, lock)
    assert len(fs) == 1 and "byte-identical" in fs[0].message


def test_drift_rule_fires_on_spec_mismatch():
    lock = copy.deepcopy(wgen.load_lock())
    lock["files"]["tendermint_tpu/crypto/merkle.py"]["bounds"] = []
    with open(GEN_PATH, encoding="utf-8") as f:
        checked_in = f.read()
    fs = _drift_findings({wgen.GENERATED_REL: checked_in}, lock)
    assert len(fs) == 1 and "spec mismatch" in fs[0].message


def test_drift_rule_fires_on_missing_module_full_tree():
    fs = _drift_findings({}, wgen.load_lock(), full_tree=True)
    assert len(fs) == 1 and "missing" in fs[0].message


def test_drift_rule_flags_raw_interpreted_calls():
    src = (
        "from tendermint_tpu.consensus import messages\n"
        "def relay(m):\n"
        "    return messages.encode_message_py(m)\n"
    )
    fs = _drift_findings(
        {"tendermint_tpu/p2p/some_reactor.py": src}, wgen.load_lock()
    )
    assert len(fs) == 1 and "encode_message_py" in fs[0].message
    assert fs[0].line == 3
    # the owning module and tests/tools are allowed to name them
    assert (
        _drift_findings(
            {"tendermint_tpu/consensus/messages.py": src}, wgen.load_lock()
        )
        == []
    )


# ---------------------------------------------------------------------------
# machine-written pragma header


def test_generated_pragma_header_is_accepted():
    """The generated module carries `tmtlint: allow-file[*]` with a
    machine-written reason; the full rule set (bad-pragma included)
    must accept the file as-is — generated code never needs allowlist
    growth."""
    from tendermint_tpu.tools.lint import ALL_RULES, RULES_BY_ID
    from tendermint_tpu.tools.lint.framework import lint_source

    with open(GEN_PATH, encoding="utf-8") as f:
        src = f.read()
    header = src.split('"""', 1)[0]
    assert "@generated" in header
    assert "tmtlint: allow-file[*]" in header
    fs = lint_source(
        src,
        wgen.GENERATED_REL,
        ALL_RULES,
        known_rules=set(RULES_BY_ID),
        report_pragma_errors=True,
    )
    assert fs == []


# ---------------------------------------------------------------------------
# dispatch + kill switch


def test_use_wiregen_swaps_the_hot_entry_points():
    was = cm.wiregen_active()
    try:
        assert cm.use_wiregen(False) is False
        assert cm.encode_message is cm.encode_message_py
        assert cm.decode_message is cm.decode_message_py
        assert not cm.wiregen_active()
        assert cm.use_wiregen(True) is True
        assert cm.encode_message is wg.encode_message
        assert cm.decode_message is wg.decode_message
        assert cm.wiregen_active()
    finally:
        cm.use_wiregen(was)


def test_env_kill_switch():
    code = (
        "from tendermint_tpu.consensus import messages as cm; "
        "print(cm.wiregen_active())"
    )
    for env_val, expect in (("0", "False"), ("1", "True")):
        out = subprocess.run(
            [sys.executable, "-c", code],
            cwd=REPO,
            env={**os.environ, "TMTPU_WIREGEN": env_val, "JAX_PLATFORMS": "cpu"},
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == expect


# ---------------------------------------------------------------------------
# fuzz A/B: bit identity on structured frames


@pytest.mark.parametrize("family", FAMILIES)
def test_structured_frames_bit_identical(family):
    g = FrameGen(seed=zlib.crc32(family.encode()))
    for _ in range(60):
        msg = g.message(family)
        bi = cm.encode_message_py(msg)
        bg = wg.encode_message(msg)
        assert bi == bg, f"{family}: encode bytes differ"
        di = cm.decode_message_py(bi)
        dg = wg.decode_message(bi)
        assert di == dg == msg or di == dg, f"{family}: decode results differ"


@pytest.mark.parametrize("family", FAMILIES)
def test_malformed_frames_identical_outcomes(family):
    """Truncations, bit flips and garbage tails must produce the same
    outcome under both codecs: the same value, or the same exception
    class AND message."""
    g = FrameGen(seed=zlib.crc32(family.encode()) + 1)
    mut = random.Random(777)
    for _ in range(12):
        frame = cm.encode_message_py(g.message(family))
        variants = [frame[:cut] for cut in range(0, min(len(frame), 10))]
        if len(frame) > 4:
            variants += [frame[: len(frame) // 2], frame[:-1]]
        for _ in range(6):
            if not frame:
                break
            b = bytearray(frame)
            b[mut.randrange(len(b))] ^= 1 << mut.randrange(8)
            variants.append(bytes(b))
        variants.append(frame + bytes(mut.getrandbits(8) for _ in range(5)))
        for v in variants:
            oi = _outcome(cm.decode_message_py, v)
            og = _outcome(wg.decode_message, v)
            assert oi == og, f"{family}: {v.hex()}: {oi} != {og}"


def test_bound_rejections_identical(monkeypatch):
    """Every decode bound the generated codec carries is read from the
    owning interpreted module at call time: patched-down bounds must
    reject with the interpreted codec's exact message under both."""
    import tendermint_tpu.crypto.merkle as mkl
    import tendermint_tpu.types.block as blk

    g = FrameGen(seed=99)
    monkeypatch.setattr(cm, "MAX_BATCH_VOTES", 3)
    monkeypatch.setattr(cm, "MAX_WIRE_BITS", 8)
    monkeypatch.setattr(cm, "MAX_WIRE_INDEX", 5)
    monkeypatch.setattr(blk, "MAX_WIRE_COMMIT_SIGS", 2)
    monkeypatch.setattr(mkl, "MAX_PROOF_AUNTS", 2)

    bombs = [
        cm.VoteBatchMessage(tuple(g.rvote() for _ in range(4))),
        cm.HasVoteBatchMessage(tuple(g.rhv() for _ in range(4))),
        cm.ProposalPOLMessage(5, 1, BitArray(64)),
        cm.HasVoteMessage(5, 1, SignedMsgType.PREVOTE, 99),
        cm.BlockPartMessage(
            5,
            1,
            Part(
                0,
                b"x",
                Proof(8, 0, b"\x11" * 32, tuple(b"\x22" * 32 for _ in range(3))),
            ),
        ),
    ]
    for msg in bombs:
        frame = cm.encode_message_py(msg)
        oi = _outcome(cm.decode_message_py, frame)
        og = _outcome(wg.decode_message, frame)
        assert oi[0] == "ValueError", f"bomb not rejected: {msg!r}"
        assert oi == og


# ---------------------------------------------------------------------------
# the point of the exercise: decode/s


def _paired_best(fa, fb, arg, iters, reps):
    ba = bb = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            fa(arg)
        t1 = time.perf_counter()
        for _ in range(iters):
            fb(arg)
        t2 = time.perf_counter()
        ba = min(ba, (t1 - t0) / iters)
        bb = min(bb, (t2 - t1) / iters)
    return ba, bb


def _soak_block_part() -> cm.BlockPartMessage:
    """The shape the motivating workload (chaos_soak) actually gossips:
    a single-part block — a 50-signature commit plus a few txs fits one
    part, whose merkle proof over a one-leaf tree has no aunts."""
    import tendermint_tpu.types.block as blk

    bid = BlockID(b"\xab" * 32, PartSetHeader(1, b"\xcd" * 32))
    sigs = tuple(
        blk.CommitSig(
            flag=blk.BLOCK_ID_FLAG_COMMIT,
            validator_address=bytes([i % 256]) * 20,
            timestamp_ns=1_700_000_000_000_000_000 + i,
            signature=bytes([i % 256]) * 64,
        )
        for i in range(50)
    )
    hdr = blk.Header(
        chain_id="soak",
        height=3,
        time_ns=1_700_000_000_000_000_000,
        last_block_id=bid,
        proposer_address=b"\x01" * 20,
        validators_hash=b"\x02" * 32,
        next_validators_hash=b"\x02" * 32,
        app_hash=b"\x03" * 32,
    )
    block = blk.Block(
        header=hdr,
        txs=(b"tx-aaaa", b"tx-bbbb"),
        last_commit=blk.Commit(height=2, round=0, block_id=bid, signatures=sigs),
    )
    return cm.BlockPartMessage(3, 0, block.make_part_set().parts[0])


@pytest.mark.slow
def test_microbench_decode_speedup():
    """≥5× decode/s on VoteBatch and block-part (soak shape). Timings
    are paired per rep (interpreted then generated inside the same
    window) and the best rep wins, so shared-host noise hits both
    sides — the quiet-machine ratio is what's asserted."""
    bid = BlockID(b"\xab" * 32, PartSetHeader(4, b"\xcd" * 32))
    votes = tuple(
        Vote(
            type=SignedMsgType.PREVOTE,
            height=1000 + i,
            round=2,
            block_id=bid,
            timestamp_ns=1_700_000_000_000_000_000 + i,
            validator_address=bytes([i % 256]) * 20,
            validator_index=i,
            signature=bytes([i % 256]) * 64,
        )
        for i in range(64)
    )
    cases = {
        "VoteBatch[64]": (cm.VoteBatchMessage(votes), 200, 5.0),
        "BlockPart[soak]": (_soak_block_part(), 1000, 5.0),
    }
    # warm the clock/caches before the first paired window
    t0 = time.perf_counter()
    frame0 = cm.encode_message_py(cases["BlockPart[soak]"][0])
    while time.perf_counter() - t0 < 0.5:
        wg.decode_message(frame0)
        cm.decode_message_py(frame0)
    ratios = {}
    for name, (msg, iters, want) in cases.items():
        frame = cm.encode_message_py(msg)
        assert frame == wg.encode_message(msg)
        best = 0.0
        for _ in range(3):  # best-of-rounds: ride out host steal spikes
            di, dg = _paired_best(
                cm.decode_message_py, wg.decode_message, frame, iters, reps=12
            )
            best = max(best, di / dg)
            if best >= want:
                break
        ratios[name] = best
        assert best >= want, f"{name}: {best:.2f}x < {want}x ({ratios})"
