"""Manifest-driven multi-process e2e runner (reference
test/e2e/pkg/manifest.go:12-68 + runner/perturb.go — containers replaced
by OS processes; same black-box method: drive and observe over RPC only).

A Manifest describes the network: per-node mode (validator/full/seed),
key type, late-start height, statesync bootstrapping, and a perturbation
sequence (kill / pause / disconnect / restart). The runner generates the
homes, spawns the processes, applies the perturbations, and asserts
whole-network app-hash convergence at a common height."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field

from tendermint_tpu.cli import main as cli_main
from tendermint_tpu.config import Config, config_from_toml, config_to_toml

MS = 1_000_000


@dataclass
class NodeSpec:
    """One node (reference manifest.go ManifestNode)."""

    name: str
    mode: str = "validator"  # validator | full | seed
    key_type: str = "ed25519"
    start_at: int = 0  # join once the network reaches this height
    state_sync: bool = False
    perturb: tuple[str, ...] = ()  # kill | pause | disconnect | restart


@dataclass
class Manifest:
    nodes: list[NodeSpec]
    target_height: int = 4  # height before perturbations begin


class Runner:
    def __init__(self, manifest: Manifest, base_dir: str, base_port: int):
        self.m = manifest
        self.base = base_dir
        self.base_port = base_port
        self.procs: dict[str, subprocess.Popen] = {}
        self.rpc_ports: dict[str, int] = {}
        self.p2p_ports: dict[str, int] = {}

    # -- setup -----------------------------------------------------------

    def setup(self) -> None:
        validators = [n for n in self.m.nodes if n.mode == "validator"]
        others = [n for n in self.m.nodes if n.mode != "validator"]
        rc = cli_main(
            [
                "testnet",
                "--validators", str(len(validators)),
                "--output", self.base,
                "--base-port", str(self.base_port),
                "--key-types", ",".join(v.key_type for v in validators),
            ]
        )
        assert rc == 0
        genesis_src = os.path.join(self.base, "node0", "config", "genesis.json")
        genesis = open(genesis_src).read()

        for i, spec in enumerate(validators):
            self._adopt(spec, os.path.join(self.base, f"node{i}"),
                        self.base_port + 2 * i)
        port = self.base_port + 2 * len(validators)
        for spec in others:
            home = os.path.join(self.base, spec.name)
            rc = cli_main(["--home", home, "init", "full"])
            assert rc == 0
            with open(os.path.join(home, "config", "genesis.json"), "w") as f:
                f.write(genesis)
            self._adopt(spec, home, port)
            port += 2

        # every node lists every validator as a persistent peer, except
        # seed-discovery nodes which learn addresses from the seed only
        seed_specs = [s for s in self.m.nodes if s.mode == "seed"]
        val_peers = ",".join(
            self._peer_addr(os.path.join(self.base, f"node{i}"),
                            self.p2p_ports[s.name])
            for i, s in enumerate(validators)
        )
        for spec in self.m.nodes:
            home = self._home(spec)
            cfg_path = os.path.join(home, "config", "config.toml")
            cfg = config_from_toml(open(cfg_path).read())
            if spec.mode == "seed":
                cfg.mode = "seed"
                cfg.p2p.persistent_peers = val_peers
            elif spec.state_sync:
                # statesync nodes learn peers normally but bootstrap state
                # from a snapshot; trust anchor filled in at start time
                cfg.p2p.persistent_peers = val_peers
            elif seed_specs and spec.mode == "full":
                # full nodes exercise seed discovery: no persistent peers
                cfg.p2p.persistent_peers = ""
                cfg.p2p.seeds = ",".join(
                    self._peer_addr(self._home(s), self.p2p_ports[s.name])
                    for s in seed_specs
                )
            else:
                cfg.p2p.persistent_peers = val_peers
            open(cfg_path, "w").write(config_to_toml(cfg))

    def _adopt(self, spec: NodeSpec, home: str, p2p_port: int) -> None:
        if os.path.basename(home) != spec.name:
            os.rename(home, self._home(spec))
        home = self._home(spec)
        self.p2p_ports[spec.name] = p2p_port
        self.rpc_ports[spec.name] = p2p_port + 1
        cfg_path = os.path.join(home, "config", "config.toml")
        cfg = config_from_toml(open(cfg_path).read())
        cfg.p2p.laddr = f"127.0.0.1:{p2p_port}"
        cfg.rpc.laddr = f"127.0.0.1:{p2p_port + 1}"
        # generous timeouts on purpose: the CI host has ONE core shared
        # by every node process plus pytest — tight propose windows make
        # starved proposers miss their slot and the network churn rounds
        # instead of progressing (observed as full-suite-only flakes)
        cfg.consensus.timeout_propose_ns = 3000 * MS
        cfg.consensus.timeout_prevote_ns = 1000 * MS
        cfg.consensus.timeout_precommit_ns = 1000 * MS
        cfg.consensus.timeout_commit_ns = 300 * MS
        open(cfg_path, "w").write(config_to_toml(cfg))

    def _home(self, spec: NodeSpec) -> str:
        return os.path.join(self.base, spec.name)

    def _peer_addr(self, home: str, port: int) -> str:
        nk = json.load(open(os.path.join(home, "config", "node_key.json")))
        from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
        from tendermint_tpu.p2p.types import node_id_from_pubkey

        key = Ed25519PrivKey(bytes.fromhex(nk["priv_key"])[:32])
        return f"tcp://{node_id_from_pubkey(key.pub_key())}@127.0.0.1:{port}"

    # -- process control --------------------------------------------------

    def spawn(self, spec: NodeSpec) -> None:
        env = dict(
            os.environ,
            TMTPU_DISABLE_TPU="1",
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        home = self._home(spec)
        log_f = (
            open(os.path.join(home, "node.log"), "ab")
            if os.environ.get("E2E_KEEP_LOGS")
            else None
        )
        try:
            self.procs[spec.name] = subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    "from tendermint_tpu.cli import main; import sys; "
                    f"sys.exit(main(['--home', {home!r}, 'start']))",
                ],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=log_f if log_f is not None else subprocess.DEVNULL,
                start_new_session=True,
            )
        finally:
            if log_f is not None:
                log_f.close()  # the child holds its own duplicated fd

    def rpc(self, name: str, path: str) -> dict:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{self.rpc_ports[name]}/{path}", timeout=5
        ) as resp:
            return json.loads(resp.read())["result"]

    def height(self, name: str) -> int:
        return int(self.rpc(name, "status")["sync_info"]["latest_block_height"])

    def wait_height(self, name: str, height: int, timeout: float) -> None:
        deadline = time.time() + timeout
        last = None
        while time.time() < deadline:
            try:
                last = self.height(name)
                if last >= height:
                    return
            except Exception:
                pass
            time.sleep(0.5)
        raise TimeoutError(f"{name} stuck at {last} (wanted {height})")

    # -- perturbations (reference runner/perturb.go) ----------------------

    def perturb(self, spec: NodeSpec, kind: str, observer: str) -> None:
        proc = self.procs[spec.name]
        if kind == "kill":
            # SIGKILL + restart on the same stores (WAL/handshake recovery)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            self.wait_network_progress(observer, 2, 240)
            self.spawn(spec)
        elif kind == "restart":
            # graceful stop + restart
            os.killpg(proc.pid, signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
            self.spawn(spec)
        elif kind == "pause":
            # SIGSTOP briefly (reference perturb pause): peers keep it
            os.killpg(proc.pid, signal.SIGSTOP)
            time.sleep(3)
            os.killpg(proc.pid, signal.SIGCONT)
        elif kind == "disconnect":
            # long freeze: peers time the node out and drop it; on resume
            # it must re-dial and catch up (the no-container analog of
            # docker network disconnect)
            os.killpg(proc.pid, signal.SIGSTOP)
            self.wait_network_progress(observer, 2, 240)
            time.sleep(8)
            os.killpg(proc.pid, signal.SIGCONT)
        else:
            raise ValueError(f"unknown perturbation {kind!r}")

    def wait_network_progress(self, observer: str, blocks: int, timeout: float):
        h = self.height(observer)
        self.wait_height(observer, h + blocks, timeout)

    # -- the run ----------------------------------------------------------

    def run(self) -> None:
        starters = [n for n in self.m.nodes if n.start_at == 0]
        late = [n for n in self.m.nodes if n.start_at > 0]
        for spec in starters:
            self.spawn(spec)
        observer = next(n.name for n in self.m.nodes if n.mode == "validator")
        for spec in starters:
            if spec.mode != "seed":
                self.wait_height(spec.name, self.m.target_height, 300)

        for spec in late:
            self.wait_height(observer, spec.start_at, 300)
            if spec.state_sync:
                trust_h = max(1, self.height(observer) - 8)
                trust_hash = self.rpc(
                    observer, f"block?height={trust_h}"
                )["block_id"]["hash"]
                home = self._home(spec)
                cfg_path = os.path.join(home, "config", "config.toml")
                cfg = config_from_toml(open(cfg_path).read())
                cfg.statesync.enable = True
                cfg.statesync.trust_height = trust_h
                cfg.statesync.trust_hash = trust_hash
                open(cfg_path, "w").write(config_to_toml(cfg))
            self.spawn(spec)
            self.wait_height(spec.name, self.height(observer), 300)

        for spec in self.m.nodes:
            for kind in spec.perturb:
                self.perturb(spec, kind, observer)
                # every perturbation must heal: the node returns to the
                # network tip (reference perturb.go waits for progress)
                self.wait_network_progress(observer, 2, 240)
                self.wait_height(spec.name, self.height(observer), 300)

        self.assert_convergence()

    def assert_convergence(self) -> None:
        non_seed = [n for n in self.m.nodes if n.mode != "seed"]
        common = min(self.height(n.name) for n in non_seed)
        # statesync nodes have no blocks below their snapshot; pick a
        # height everyone serves
        floor = max(
            int(self.rpc(n.name, "status")["sync_info"].get(
                "earliest_block_height", 1
            ))
            for n in non_seed
        )
        check = max(common, floor)
        hashes = {
            self.rpc(n.name, f"block?height={check}")["block"]["header"][
                "app_hash"
            ]
            for n in non_seed
        }
        assert len(hashes) == 1, f"app hash divergence at {check}: {hashes}"

    def teardown(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGCONT)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
