"""Decode-bound regression tests — the tree-wide sweep the tmtlint
`wire-bounds` pass forced (PR 15 tentpole, satellite "fix every real
finding").

Every decoder that grows a collection from untrusted (or bit-rot-prone
durable) bytes now clamps it with a named MAX_* bound; these tests pin
each fixed site with a crafted bomb frame: the decode must raise
ValueError, never allocate. Bounds that are large by design (2^16/2^20)
are monkeypatched down so the bombs stay test-sized — the guard reads
the module global at call time, so a low patched bound exercises the
identical code path.
"""

from __future__ import annotations

import hashlib

import pytest

from tendermint_tpu.libs import protoenc as pe


@pytest.fixture(params=["interpreted", "generated"], autouse=True)
def codec(request, monkeypatch):
    """Run every bomb in this module against BOTH codecs.

    The wiregen-generated codec carries the same decode bounds as the
    interpreted one (read from the owning module at call time, so the
    monkeypatched-down bounds below govern both). For the frame
    families wiregen compiles — merkle proofs, commits, and the
    consensus message envelope — the generated decoders are swapped in;
    families wiregen does not compile run their (interpreted) decode
    unchanged under both params.
    """
    import tendermint_tpu.consensus.messages as cm

    was_generated = cm.wiregen_active()
    if request.param == "generated":
        if not cm.use_wiregen(True):
            pytest.skip("generated codec unavailable")
        from tendermint_tpu.consensus import wire_gen as wg
        from tendermint_tpu.crypto import merkle
        from tendermint_tpu.types import block as b

        monkeypatch.setattr(
            merkle.Proof, "decode", staticmethod(wg.decode_proof)
        )
        monkeypatch.setattr(b.Commit, "decode", staticmethod(wg.decode_commit))
    else:
        cm.use_wiregen(False)
    yield request.param
    cm.use_wiregen(was_generated)


# ---------------------------------------------------------------------------
# mempool gossip frames


def test_mempool_tx_frame_bomb_raises():
    from tendermint_tpu.mempool import reactor as mr

    good = mr.encode_txs([b"tx-%d" % i for i in range(16)])
    assert len(mr.decode_txs(good)) == 16
    bomb = b"".join(pe.bytes_field(1, b"x") for _ in range(mr.MAX_WIRE_TXS + 1))
    with pytest.raises(ValueError, match="exceeds"):
        mr.decode_txs(bomb)


# ---------------------------------------------------------------------------
# pex address frames


def test_pex_response_bomb_raises():
    from tendermint_tpu.p2p import pex

    ok = pex.encode_message(pex.PexResponse(("a@1.2.3.4:1",) * 10))
    assert len(pex.decode_message(ok).addresses) == 10
    body = b"".join(
        pe.string_field(1, "a@1.2.3.4:1") for _ in range(pex.MAX_ADDRESSES + 1)
    )
    bomb = pe.message_field(2, body)
    with pytest.raises(ValueError, match="exceeds"):
        pex.decode_message(bomb)


# ---------------------------------------------------------------------------
# merkle proofs


def test_merkle_proof_aunt_bomb_raises():
    from tendermint_tpu.crypto import merkle

    items = [b"leaf-%d" % i for i in range(8)]
    root, proofs = merkle.proofs_from_byte_slices(items)
    proof = proofs[3]
    rt = merkle.Proof.decode(proof.encode())
    assert rt.verify(root, items[3])
    bomb = proof.encode() + b"".join(
        pe.message_field(4, b"\x00" * 32) for _ in range(merkle.MAX_PROOF_AUNTS)
    )
    with pytest.raises(ValueError, match="aunts exceed"):
        merkle.Proof.decode(bomb)


# ---------------------------------------------------------------------------
# ABCI events (socket + durable state store bytes)


def test_abci_event_attr_bomb_raises(monkeypatch):
    from tendermint_tpu.abci import types as abci

    monkeypatch.setattr(abci, "MAX_WIRE_EVENT_ATTRS", 4)
    attr = abci.EventAttribute("k", "v").encode()
    ok = abci.Event("t", tuple([abci.EventAttribute("k", "v")] * 4)).encode()
    assert len(abci.Event.decode(ok).attributes) == 4
    bomb = pe.string_field(1, "t") + b"".join(
        pe.message_field(2, attr) for _ in range(5)
    )
    with pytest.raises(ValueError, match="attributes exceed"):
        abci.Event.decode(bomb)


def test_abci_deliver_tx_event_bomb_raises(monkeypatch):
    from tendermint_tpu.abci import types as abci

    monkeypatch.setattr(abci, "MAX_WIRE_EVENTS", 4)
    ev = abci.Event("t").encode()
    bomb = b"".join(pe.message_field(6, ev) for _ in range(5))
    with pytest.raises(ValueError, match="events exceed"):
        abci.ResponseDeliverTx.decode(bomb)


def test_state_store_abci_responses_bomb_raises(monkeypatch):
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.state import store as st

    monkeypatch.setattr(st, "MAX_STORE_ITEMS", 4)
    tx = abci.ResponseDeliverTx().encode()
    ok = b"".join(pe.message_field(1, tx) for _ in range(4))
    assert len(st.ABCIResponses.decode(ok).deliver_txs) == 4
    bomb = b"".join(pe.message_field(1, tx) for _ in range(5))
    with pytest.raises(ValueError, match="deliver-txs"):
        st.ABCIResponses.decode(bomb)


# ---------------------------------------------------------------------------
# verifyd sidecar protocol


def test_verifyd_repeated_field_bomb_raises(monkeypatch):
    from tendermint_tpu.crypto import verifyd as vd

    monkeypatch.setattr(vd, "MAX_REPEATED", 8)
    ok = vd.encode_hello_ok(1, ("ed25519",), [64, 128], b"e")
    t, fields = vd.decode_message(ok)
    assert t == vd.MSG_HELLO_OK and fields["ladder"] == [64, 128]
    bomb = vd.encode_hello_ok(1, ("ed25519",), list(range(64, 64 + 9)), b"e")
    with pytest.raises(ValueError, match="repeats ladder"):
        vd.decode_message(bomb)
    items = [("ed25519", b"p", b"m", b"s", "live")] * 9
    with pytest.raises(ValueError, match="repeats items"):
        vd.decode_message(vd.encode_verify_batch(1, items))


# ---------------------------------------------------------------------------
# block / commit / validator-set / evidence / params


def _validator():
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.types.validator_set import Validator

    pk = ed25519.Ed25519PrivKey(hashlib.sha256(b"wb-val").digest()).pub_key()
    return Validator(pk, 10)


def test_commit_sig_bomb_raises(monkeypatch):
    from tendermint_tpu.types import block as b

    monkeypatch.setattr(b, "MAX_WIRE_COMMIT_SIGS", 4)
    sig = b.CommitSig.absent().encode()
    bomb = (
        pe.sfixed64_field(1, 3)
        + pe.sfixed64_field(2, 0)
        + b"".join(pe.message_field(4, sig) for _ in range(5))
    )
    with pytest.raises(ValueError, match="signatures exceed"):
        b.Commit.decode(bomb)


def test_block_tx_bomb_raises(monkeypatch):
    from tendermint_tpu.types import block as b

    monkeypatch.setattr(b, "MAX_WIRE_BLOCK_TXS", 4)
    bomb = b"".join(pe.bytes_field(2, b"tx") for _ in range(5))
    with pytest.raises(ValueError, match="txs exceed"):
        b.Block.decode(bomb)


def test_validator_set_bomb_raises(monkeypatch):
    from tendermint_tpu.types import validator_set as vs

    monkeypatch.setattr(vs, "MAX_WIRE_VALIDATORS", 4)
    venc = _validator().encode()
    ok = b"".join(pe.message_field(1, venc) for _ in range(4))
    assert len(vs.ValidatorSet.decode(ok).validators) == 4
    bomb = b"".join(pe.message_field(1, venc) for _ in range(5))
    with pytest.raises(ValueError, match="exceeds"):
        vs.ValidatorSet.decode(bomb)


def test_lca_byzantine_validator_bomb_raises(monkeypatch):
    from tendermint_tpu.types import evidence as ev

    monkeypatch.setattr(ev, "MAX_WIRE_VALIDATORS", 4)
    venc = _validator().encode()
    bomb = pe.Reader(
        b"".join(pe.message_field(4, venc) for _ in range(5))
    )
    with pytest.raises(ValueError, match="byzantine validators exceed"):
        ev.LightClientAttackEvidence.decode_fields(bomb)


def test_params_key_type_bomb_raises():
    from tendermint_tpu.types import params as pp

    body = b"".join(
        pe.bytes_field(1, b"ed25519") for _ in range(pp.MAX_PUB_KEY_TYPES + 1)
    )
    bomb = pe.message_field(3, body)
    with pytest.raises(ValueError, match="pub_key_types exceed"):
        pp.ConsensusParams.decode(bomb)


# ---------------------------------------------------------------------------
# statesync frames (BootFleet: every donor-supplied frame is clamped
# before the joiner's fetch/verify loops can act on it)


def test_snapshot_chunk_count_bomb_raises():
    from tendermint_tpu.statesync import messages as ssm

    ok = ssm.encode_message(
        ssm.SnapshotsResponse(10, 1, 4, b"\x00" * 32)
    )
    assert ssm.decode_message(ok).chunks == 4
    # a lying donor's 10-byte frame must not schedule 2^32 chunk fetches
    body = (
        pe.varint_field(1, 10)
        + pe.varint_field(2, 1)
        + pe.varint_field(3, ssm.MAX_WIRE_SNAPSHOT_CHUNKS + 1)
    )
    with pytest.raises(ValueError, match="exceeds"):
        ssm.decode_message(pe.message_field(ssm.T_SNAPSHOTS_RESPONSE, body))


def test_snapshot_hash_bomb_raises():
    from tendermint_tpu.statesync import messages as ssm

    body = pe.varint_field(1, 10) + pe.bytes_field(
        4, b"\x00" * (ssm.MAX_WIRE_SNAPSHOT_HASH + 1)
    )
    with pytest.raises(ValueError, match="exceeds"):
        ssm.decode_message(pe.message_field(ssm.T_SNAPSHOTS_RESPONSE, body))


def test_snapshot_metadata_bomb_raises(monkeypatch):
    from tendermint_tpu.statesync import messages as ssm

    monkeypatch.setattr(ssm, "MAX_WIRE_SNAPSHOT_METADATA", 16)
    body = pe.varint_field(1, 10) + pe.bytes_field(5, b"\x00" * 17)
    with pytest.raises(ValueError, match="exceeds"):
        ssm.decode_message(pe.message_field(ssm.T_SNAPSHOTS_RESPONSE, body))


def test_chunk_payload_bomb_raises(monkeypatch):
    from tendermint_tpu.statesync import messages as ssm

    ok = ssm.encode_message(ssm.ChunkResponse(10, 1, 0, b"x" * 64))
    assert ssm.decode_message(ok).chunk == b"x" * 64
    monkeypatch.setattr(ssm, "MAX_WIRE_CHUNK", 64)
    body = (
        pe.varint_field(1, 10)
        + pe.varint_field(2, 1)
        + pe.varint_field(3, 0)
        + pe.bytes_field(4, b"x" * 65)
    )
    with pytest.raises(ValueError, match="exceeds"):
        ssm.decode_message(pe.message_field(ssm.T_CHUNK_RESPONSE, body))


def test_chunk_busy_flag_roundtrips():
    """`busy` (the BootD shed signal) must survive the wire and stay
    distinct from `missing` — conflating them would steer the fetcher
    away from a healthy-but-loaded donor."""
    from tendermint_tpu.statesync import messages as ssm

    res = ssm.decode_message(
        ssm.encode_message(ssm.ChunkResponse(10, 1, 2, busy=True))
    )
    assert res.busy and not res.missing
    res = ssm.decode_message(
        ssm.encode_message(ssm.ChunkResponse(10, 1, 2, missing=True))
    )
    assert res.missing and not res.busy


def test_backfill_batch_request_bomb_raises():
    from tendermint_tpu.statesync import messages as ssm

    ok = ssm.encode_message(ssm.LightBlockBatchRequest(100, 64))
    assert ssm.decode_message(ok).count == 64
    body = pe.varint_field(1, 100) + pe.varint_field(
        2, ssm.MAX_WIRE_BACKFILL_BATCH + 1
    )
    with pytest.raises(ValueError, match="exceeds"):
        ssm.decode_message(
            pe.message_field(ssm.T_LIGHT_BLOCK_BATCH_REQUEST, body)
        )


def test_backfill_batch_response_bomb_raises(monkeypatch):
    from tendermint_tpu.statesync import messages as ssm

    ok = ssm.encode_message(ssm.LightBlockBatchResponse(()))
    assert ssm.decode_message(ok).light_blocks == ()
    # the list-length guard fires BEFORE the excess element is decoded,
    # so at a patched bound of 0 the first field must raise even though
    # its payload is not a valid LightBlock
    monkeypatch.setattr(ssm, "MAX_WIRE_BACKFILL_BATCH", 0)
    bomb = pe.message_field(
        ssm.T_LIGHT_BLOCK_BATCH_RESPONSE, pe.message_field(1, b"junk")
    )
    with pytest.raises(ValueError, match="exceeds"):
        ssm.decode_message(bomb)


# ---------------------------------------------------------------------------
# the transitive-blocking sweep: the split probe API


def test_tpu_wait_available_is_the_only_blocking_probe(monkeypatch):
    """PR 15 split the blocking wait out of `tpu_verifier_available` so
    the verifyd daemon coroutine (and anything else async) can kick the
    probe without a sleep anywhere on its call chain — the tmtlint
    transitive-blocking pass holds this structurally; this pins the
    split's semantics."""
    from tendermint_tpu.crypto import batch

    # verdict already known: both return it, neither sleeps
    monkeypatch.setattr(batch, "_tpu_available", True)
    assert batch.tpu_verifier_available() is True
    assert batch.tpu_wait_available() is True
    monkeypatch.setattr(batch, "_tpu_available", False)
    assert batch.tpu_verifier_available() is False
    assert batch.tpu_wait_available() is False
    # probe disabled: non-blocking verdict False, wait returns without
    # spinning (the disable check precedes the sleep loop)
    monkeypatch.setattr(batch, "_tpu_available", None)
    monkeypatch.setenv("TMTPU_DISABLE_TPU", "1")
    assert batch.tpu_verifier_available() is False
    assert batch.tpu_wait_available() is False
