"""Hardening pins for the TCP/SecretConnection stack (ISSUE 18
satellites 1+2): the socket layer now carries real consensus load
across processes, so the handshake path is bounded and deadlined, a
full accept queue sheds instead of blocking, and silent links die on a
pong deadline instead of trusting the kernel's ACK machinery."""

from __future__ import annotations

import asyncio
import os
import signal
import struct
import subprocess
import sys
import textwrap

import pytest

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.p2p import secret as secretmod
from tendermint_tpu.p2p.secret import SecretStream
from tendermint_tpu.p2p.tcp import (
    MAX_HANDSHAKE_MSG_SIZE,
    TCPTransport,
    UDSTransport,
    _T_DATA,
)
from tendermint_tpu.p2p.transport import ConnectionClosedError
from tendermint_tpu.p2p.types import NodeAddress, NodeInfo, node_id_from_pubkey


def _identity(tag: str):
    priv = ed25519.Ed25519PrivKey(bytes([len(tag)]) * 31 + tag.encode()[:1])
    nid = node_id_from_pubkey(priv.pub_key())
    return priv, nid, NodeInfo(node_id=nid, network="hardening")


async def _listening(transport_cls=TCPTransport, **kwargs):
    t = transport_cls(**kwargs)
    await t.listen("127.0.0.1:0")
    return t


class TestHandshakeHardening:
    @pytest.mark.asyncio
    async def test_torn_handshake_times_out_and_cleans_up(self):
        """A dialer that connects, sends two bytes, and stalls must cost
        the acceptor one bounded handshake deadline — not a forever-
        parked reader task pinning the accept slot."""
        priv, _nid, info = _identity("srv")
        t = await _listening(handshake_timeout=0.4)
        host, port = t.endpoint().rsplit(":", 1)

        # raw socket: open, write a torn ephemeral-key header, stall
        reader, writer = await asyncio.open_connection(host, int(port))

        async def server():
            conn = await t.accept()
            with pytest.raises(ConnectionError, match="handshake timed out"):
                await conn.handshake(info, priv)

        stask = asyncio.create_task(server())
        writer.write(b"\x00")  # half of the 2-byte length prefix
        await writer.drain()
        await asyncio.wait_for(stask, 5.0)
        # the acceptor closed its side: after its own ephemeral-key
        # bytes, our raw socket drains to EOF
        assert await asyncio.wait_for(reader.read(), 5.0) is not None
        assert reader.at_eof()
        writer.close()
        await t.close()

    @pytest.mark.asyncio
    async def test_bad_ephemeral_key_length_rejected(self):
        """The cleartext ephemeral key is exactly 32 bytes; a hostile
        length claim is refused before any allocation."""
        priv, _nid, info = _identity("srv")
        t = await _listening()
        host, port = t.endpoint().rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))

        async def server():
            conn = await t.accept()
            with pytest.raises(ConnectionError):
                await conn.handshake(info, priv)

        stask = asyncio.create_task(server())
        writer.write(struct.pack(">H", 60000) + b"\x00" * 64)
        await writer.drain()
        await asyncio.wait_for(stask, 5.0)
        writer.close()
        await t.close()

    @pytest.mark.asyncio
    async def test_oversized_handshake_frame_rejected(self):
        """A peer that completes the secret handshake but then claims a
        multi-megabyte NodeInfo gets the 64 KiB handshake bound, not the
        32 MiB data bound."""
        priv_s, _nid, info = _identity("srv")
        priv_c, _cid, _cinfo = _identity("cli")
        t = await _listening(handshake_timeout=5.0)
        host, port = t.endpoint().rsplit(":", 1)

        async def server():
            conn = await t.accept()
            with pytest.raises(ConnectionError, match="oversized message"):
                await conn.handshake(info, priv_s)

        stask = asyncio.create_task(server())
        reader, writer = await asyncio.open_connection(host, int(port))
        stream = SecretStream(reader, writer)
        await stream.handshake(priv_c)
        # valid frame header claiming a bomb-sized NodeInfo
        hdr = struct.pack(">BBI", _T_DATA, 0xFF, MAX_HANDSHAKE_MSG_SIZE + 1)
        await stream.write_all(hdr)
        await asyncio.wait_for(stask, 5.0)
        stream.close()
        await t.close()

    @pytest.mark.asyncio
    async def test_oversized_auth_frame_rejected(self, monkeypatch):
        """The encrypted auth frame (pubkey + challenge signature) is
        ~100 bytes; the sender refuses to emit one past MAX_AUTH_FRAME."""
        monkeypatch.setattr(secretmod, "MAX_AUTH_FRAME", 8)
        priv_s, _nid, _info = _identity("srv")
        priv_c, _cid, _cinfo = _identity("cli")

        async def _peer(r, w):
            s = SecretStream(r, w)
            try:
                await s.handshake(priv_s)
            except (secretmod.AuthError, OSError, EOFError):
                pass  # the dialer aborts first
            s.close()

        server = await asyncio.start_server(_peer, "127.0.0.1", 0)
        host, port = server.sockets[0].getsockname()[:2]
        reader, writer = await asyncio.open_connection(host, port)
        stream = SecretStream(reader, writer)
        with pytest.raises(secretmod.AuthError, match="handshake bound"):
            await stream.handshake(priv_c)
        stream.close()
        server.close()

    @pytest.mark.asyncio
    async def test_accept_queue_sheds_on_flood(self):
        """A dial flood past the accept backlog sheds the excess sockets
        (they see EOF and own their redial) instead of blocking the
        asyncio server callback."""
        t = await _listening(accept_backlog=2)
        host, port = t.endpoint().rsplit(":", 1)
        socks = []
        for _ in range(6):
            socks.append(await asyncio.open_connection(host, int(port)))
        # give the server callbacks a chance to run
        for _ in range(50):
            if t.sheds >= 4:
                break
            await asyncio.sleep(0.02)
        assert t.sheds >= 4
        # shed sockets see EOF; queued ones stay open
        eofs = 0
        for r, w in socks:
            try:
                data = await asyncio.wait_for(r.read(1), 0.5)
                if data == b"":
                    eofs += 1
            except asyncio.TimeoutError:
                pass
            w.close()
        assert eofs >= 4
        await t.close()

    @pytest.mark.asyncio
    async def test_transport_close_drains_queued_conns(self):
        """Sockets accepted but never claimed by the router are closed
        with the transport — no leaked reader tasks."""
        t = await _listening(accept_backlog=4)
        host, port = t.endpoint().rsplit(":", 1)
        r1, w1 = await asyncio.open_connection(host, int(port))
        for _ in range(50):
            if t._accept_q.qsize() >= 1:
                break
            await asyncio.sleep(0.02)
        await t.close()
        assert await asyncio.wait_for(r1.read(16), 5.0) == b""
        w1.close()
        with pytest.raises(ConnectionClosedError):
            await t.accept()


class TestUDSTransport:
    @pytest.mark.asyncio
    async def test_uds_dial_handshake_exchange(self, tmp_path):
        """Full SecretConnection handshake + framed exchange over a
        Unix-domain socket — the XL same-host inter-process link."""
        priv_a, id_a, info_a = _identity("ua")
        priv_b, id_b, info_b = _identity("ub")
        sock = str(tmp_path / "xl.sock")
        tb = UDSTransport()
        await tb.listen(sock)

        async def server():
            conn = await tb.accept()
            peer = await conn.handshake(info_b, priv_b)
            assert peer.node_id == id_a
            ch, data = await conn.receive_message()
            await conn.send_message(ch, data.upper())
            return conn

        stask = asyncio.create_task(server())
        ta = UDSTransport()
        conn = await ta.dial(NodeAddress(node_id=id_b, host=sock, port=0))
        peer = await conn.handshake(info_a, priv_a)
        assert peer.node_id == id_b
        await conn.send_message(0x30, b"uds")
        ch, data = await conn.receive_message()
        assert (ch, data) == (0x30, b"UDS")
        sconn = await asyncio.wait_for(stask, 5.0)
        await conn.close()
        await sconn.close()
        await ta.close()
        await tb.close()

    def test_uds_address_roundtrip(self, tmp_path):
        a = NodeAddress(
            node_id="ab" * 20, protocol="unix",
            host=str(tmp_path / "n3.sock"), port=0,
        )
        assert NodeAddress.parse(str(a)) == a


_STOPPED_PEER = textwrap.dedent(
    """
    import asyncio, os, sys
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.p2p.tcp import TCPTransport
    from tendermint_tpu.p2p.types import NodeAddress, NodeInfo, node_id_from_pubkey

    async def main():
        host, port = sys.argv[1], int(sys.argv[2])
        priv = ed25519.Ed25519PrivKey(bytes([7]) * 31 + b"c")
        nid = node_id_from_pubkey(priv.pub_key())
        info = NodeInfo(node_id=nid, network="hardening")
        t = TCPTransport(ping_interval=0.1, pong_timeout=1e9)
        conn = await t.dial(NodeAddress(node_id="", host=host, port=port))
        await conn.handshake(info, priv)
        print("READY", flush=True)
        # freeze this whole process: the kernel keeps ACKing the
        # parent's bytes but no pong ever comes back
        os.kill(os.getpid(), 19)  # SIGSTOP
        await conn.receive_message()

    asyncio.run(main())
    """
)


class TestDeadPeerDetection:
    @pytest.mark.asyncio
    async def test_sigstopped_peer_disconnects_on_pong_deadline(self):
        """A SIGSTOPped peer process never answers pings even though its
        kernel ACKs every byte — only the pong deadline notices, and it
        closes the connection explicitly (router reconnect owns retry)."""
        priv, _nid, info = _identity("srv")
        t = await _listening(ping_interval=0.2, pong_timeout=0.6)
        host, port = t.endpoint().rsplit(":", 1)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.setdefault("TMTPU_DISABLE_TPU", "1")
        proc = await asyncio.to_thread(
            subprocess.Popen,
            [sys.executable, "-c", _STOPPED_PEER, host, port],
            stdout=subprocess.PIPE,
            env=env,
            start_new_session=True,
        )
        try:
            conn = await asyncio.wait_for(t.accept(), 30.0)
            await asyncio.wait_for(conn.handshake(info, priv), 30.0)
            # wait for the child to announce it froze itself
            line = await asyncio.wait_for(
                asyncio.to_thread(proc.stdout.readline), 30.0
            )
            assert b"READY" in line
            with pytest.raises(ConnectionClosedError, match="pong timeout"):
                # next frames never come; the ping loop must kill the
                # link within ~pong_timeout + one ping interval
                await asyncio.wait_for(conn.receive_message(), 10.0)
            assert conn.close_reason == "pong timeout"
        finally:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            await asyncio.to_thread(proc.wait)
            await t.close()

    @pytest.mark.asyncio
    async def test_live_peer_survives_pong_deadline(self):
        """A responsive peer's pongs refresh the deadline: aggressive
        ping settings must not kill a healthy idle link."""
        priv_a, _ida, info_a = _identity("la")
        priv_b, id_b, info_b = _identity("lb")
        ta = TCPTransport(ping_interval=0.1, pong_timeout=0.35)
        tb = await _listening(ping_interval=0.1, pong_timeout=0.35)
        host, port = tb.endpoint().rsplit(":", 1)

        async def server():
            conn = await tb.accept()
            await conn.handshake(info_b, priv_b)
            # serve pongs until the peer sends real data
            ch, data = await conn.receive_message()
            return conn, (ch, data)

        stask = asyncio.create_task(server())
        conn = await ta.dial(NodeAddress(node_id=id_b, host=host, port=int(port)))
        await conn.handshake(info_a, priv_a)
        recv = asyncio.create_task(conn.receive_message())
        # idle for several pong deadlines; pings+pongs keep both alive
        await asyncio.sleep(1.2)
        assert not recv.done(), "healthy idle link was torn down"
        await conn.send_message(0x01, b"still-here")
        sconn, got = await asyncio.wait_for(stask, 5.0)
        assert got == (0x01, b"still-here")
        recv.cancel()
        await asyncio.gather(recv, return_exceptions=True)
        await conn.close()
        await sconn.close()
        await ta.close()
        await tb.close()
