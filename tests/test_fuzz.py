"""Deterministic fuzz sweeps over the attacker-facing decoders (the
surfaces the reference fuzzes continuously in test/fuzz/: the consensus
WAL decoder, the secret-connection handshake, p2p addresses, and the wire
Reader). go-fuzz's coverage feedback is replaced by seeded random mutation
at volume — every input here is attacker-controlled bytes, and the
invariant under test is always the same: reject cleanly, never crash,
never hang."""

import os
import random

import pytest

from tendermint_tpu.libs import protoenc as pe


def _rng(seed: int) -> random.Random:
    return random.Random(seed)


class TestProtoencReaderFuzz:
    def test_random_garbage_never_crashes(self):
        rng = _rng(1)
        for trial in range(500):
            data = rng.randbytes(rng.randrange(0, 200))
            r = pe.Reader(data)
            try:
                while not r.eof():
                    f, wt = r.read_tag()
                    r.skip(wt)
            except ValueError:
                pass  # clean rejection is the contract

    def test_mutated_valid_messages(self):
        """Flip bytes of a valid encoding; decode must reject or produce
        SOME value — never raise anything but ValueError."""
        from tendermint_tpu.types.block import BlockID, Commit, CommitSig, PartSetHeader
        from tendermint_tpu.crypto.hashes import sha256

        bid = BlockID(sha256(b"x"), PartSetHeader(1, sha256(b"y")))
        commit = Commit(
            5, 0, bid, (CommitSig.for_block(b"\x01" * 20, 123, b"\x02" * 64),)
        )
        base = commit.encode()
        rng = _rng(2)
        for trial in range(400):
            buf = bytearray(base)
            for _ in range(rng.randrange(1, 4)):
                buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
            try:
                Commit.decode(bytes(buf))
            except (ValueError, OverflowError):
                pass

    def test_evidence_decoder_fuzz(self):
        from tendermint_tpu.types.evidence import decode_evidence

        rng = _rng(3)
        for trial in range(300):
            try:
                decode_evidence(rng.randbytes(rng.randrange(1, 150)))
            except (ValueError, OverflowError):
                pass


class TestWALFuzz:
    def test_torn_and_corrupted_tails(self, tmp_path):
        """Any byte-level corruption of the WAL tail must yield a clean
        truncation (non-strict) — records before the corruption survive."""
        from tendermint_tpu.consensus.wal import WAL, WALCorruptionError

        rng = _rng(4)
        for trial in range(25):
            wal_dir = str(tmp_path / f"wal{trial}")
            wal = WAL(wal_dir)
            payloads = [bytes([i]) * (i + 1) for i in range(10)]
            for p in payloads:
                wal.write_sync(p)
            wal.close()
            # corrupt the file tail
            files = sorted(
                os.path.join(wal_dir, f) for f in os.listdir(wal_dir)
            )
            with open(files[-1], "r+b") as f:
                size = f.seek(0, 2)
                cut = rng.randrange(size // 2, size)
                if rng.random() < 0.5:
                    f.truncate(cut)  # torn write
                else:
                    f.seek(cut - 1)
                    f.write(bytes([rng.randrange(256)]))  # flipped byte
            wal2 = WAL(wal_dir)
            got = [rec.data for rec in wal2.iter_records()]
            wal2.close()
            # a prefix must survive, in order, unmodified
            assert got == payloads[: len(got)]
            assert len(got) >= 1

    def test_random_wal_files_never_crash(self, tmp_path):
        from tendermint_tpu.consensus.wal import WAL

        rng = _rng(5)
        for trial in range(20):
            wal_dir = str(tmp_path / f"rw{trial}")
            os.makedirs(wal_dir)
            with open(os.path.join(wal_dir, "wal.0"), "wb") as f:
                f.write(rng.randbytes(rng.randrange(1, 4096)))
            wal = WAL(wal_dir)
            list(wal.iter_records())  # must not raise in tolerant mode
            wal.close()


class TestSecretConnectionFuzz:
    @pytest.mark.asyncio
    async def test_garbage_handshake_rejected(self):
        """An attacker spewing bytes at the STS handshake must produce a
        clean error, not a hang or crash."""
        import asyncio

        from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
        from tendermint_tpu.p2p.secret import SecretStream

        rng = _rng(6)
        for trial in range(8):
            garbage = rng.randbytes(rng.randrange(1, 256))

            async def attacker(reader, writer, garbage=garbage):
                writer.write(garbage)
                try:
                    await writer.drain()
                    writer.close()
                except ConnectionError:
                    pass

            server = await asyncio.start_server(attacker, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            stream = SecretStream(reader, writer)
            with pytest.raises(Exception) as exc_info:
                await asyncio.wait_for(
                    stream.handshake(Ed25519PrivKey(b"\x07" * 32)), timeout=5
                )
            assert not isinstance(exc_info.value, asyncio.TimeoutError)
            stream.close()
            server.close()
            await server.wait_closed()


class TestAddressFuzz:
    def test_node_address_parse_fuzz(self):
        from tendermint_tpu.p2p.types import NodeAddress

        rng = _rng(7)
        corpus = [
            "tcp://" + "a" * 40 + "@127.0.0.1:26656",
            "memory:" + "b" * 40,
        ]
        for trial in range(500):
            s = rng.choice(corpus)
            buf = list(s)
            for _ in range(rng.randrange(1, 5)):
                i = rng.randrange(len(buf))
                buf[i] = chr(rng.randrange(32, 127))
            try:
                NodeAddress.parse("".join(buf))
            except ValueError:
                pass


class TestBatchVerifyFuzz:
    def test_grouped_chunked_verify_vs_oracle(self, monkeypatch):
        """Randomized differential: batches with duplicated keys, bad
        signatures, tampered messages, malformed keys/sigs, and forced
        small chunking must produce exactly the per-signature oracle's
        bitmap (grouping + chunk pipelining are pure optimizations)."""
        from tendermint_tpu.crypto import ed25519
        from tendermint_tpu.crypto.ed25519_math import verify_zip215
        from tendermint_tpu.crypto.tpu import verify as V

        monkeypatch.setattr(V, "_MAX_BUCKET", 64)
        rng = _rng(42)
        keys = [ed25519.Ed25519PrivKey.generate() for _ in range(5)]
        for trial in range(4):
            items = []
            n = rng.randrange(3, 140)
            for i in range(n):
                k = keys[rng.randrange(len(keys))]
                msg = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 60)))
                sig = k.sign(msg)
                roll = rng.random()
                if roll < 0.12:  # corrupt signature byte
                    b = rng.randrange(64)
                    sig = sig[:b] + bytes([sig[b] ^ (1 + rng.randrange(255))]) + sig[b + 1:]
                elif roll < 0.2:  # tamper message
                    msg = msg + b"!"
                elif roll < 0.25:  # malformed pubkey length
                    items.append((k.pub_key().bytes()[:-1], msg, sig))
                    continue
                elif roll < 0.3:  # malformed sig length
                    items.append((k.pub_key().bytes(), msg, sig[:-2]))
                    continue
                items.append((k.pub_key().bytes(), msg, sig))
            got = V.verify_batch_eq(items)
            want = [
                len(p) == 32 and len(s) == 64 and verify_zip215(p, m, s)
                for p, m, s in items
            ]
            assert list(got) == want, f"trial {trial}: mismatch"
