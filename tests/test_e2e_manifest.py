"""Manifest-driven e2e matrix (reference test/e2e/pkg/manifest.go +
runner/perturb.go): perturbations, a statesync-joining node, a seed node
with seed-discovered full node, and a mixed-key validator set — each run
ends in whole-network app-hash convergence."""

import pytest

from tests.e2e_manifest import Manifest, NodeSpec, Runner


def _run(manifest: Manifest, tmp_path, base_port: int) -> None:
    r = Runner(manifest, str(tmp_path / "net"), base_port)
    try:
        r.setup()
        r.run()
    finally:
        r.teardown()


@pytest.mark.slow
def test_perturbation_matrix(tmp_path):
    """4 validators (one secp256k1 — mixed-key set): pause one, kill +
    restart another, freeze-disconnect a third; every wound heals to
    app-hash convergence."""
    _run(
        Manifest(
            nodes=[
                NodeSpec("node0", perturb=("pause",)),
                NodeSpec("node1", key_type="secp256k1", perturb=("kill",)),
                NodeSpec("node2", perturb=("disconnect",)),
                NodeSpec("node3", perturb=("restart",)),
            ],
            target_height=3,
        ),
        tmp_path,
        28700,
    )


@pytest.mark.slow
def test_statesync_joiner_and_seed_discovery(tmp_path):
    """A seed node plus a full node that discovers the network ONLY
    through the seed, and a statesync node that joins late from a
    snapshot (kvstore snapshots every 10 blocks)."""
    _run(
        Manifest(
            nodes=[
                NodeSpec("node0"),
                NodeSpec("node1"),
                NodeSpec("node2"),
                NodeSpec("seed0", mode="seed"),
                NodeSpec("full0", mode="full"),
                NodeSpec("sync0", mode="full", state_sync=True, start_at=12),
            ],
            target_height=3,
        ),
        tmp_path,
        28760,
    )
