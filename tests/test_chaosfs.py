"""Chaos-fs storage fault injection + WAL crash repair (libs/chaosfs.py,
consensus/wal.py) and the new chaos-net fault classes (asymmetric
partitions, bandwidth shaping, gray failures, clock skew)."""

import os
import subprocess
import sys

import pytest

from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork
from tendermint_tpu.libs.chaosfs import ChaosDB, ChaosFS, ChaosFSConfig
from tendermint_tpu.libs.clock import ManualClock, SkewedClock
from tendermint_tpu.libs.metrics import STORAGE
from tendermint_tpu.store.db import MemDB

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fill(wal: WAL, n: int = 5, sync: bool = True) -> list[bytes]:
    payloads = [bytes([i]) * (10 + i) for i in range(n)]
    for p in payloads:
        (wal.write_sync if sync else wal.write)(p)
    return payloads


class TestChaosFSCrashModel:
    def test_fsynced_records_always_survive(self, tmp_path):
        fs = ChaosFS(ChaosFSConfig(seed=1))
        wal = WAL(str(tmp_path / "w"), fs=fs)
        payloads = _fill(wal, 5, sync=True)
        wal.write(b"buffered-not-synced")
        fs.halt()
        wal.close()
        fs.simulate_crash()
        wal2 = WAL(str(tmp_path / "w"), fs=fs)
        # crash at a record boundary: the buffered tail vanishes cleanly
        assert [r.data for r in wal2.iter_records()] == payloads
        assert wal2.last_repair == []
        wal2.close()

    def test_torn_write_repaired_to_last_whole_record(self, tmp_path):
        fs = ChaosFS(ChaosFSConfig(seed=7, torn_write_rate=1.0))
        wal = WAL(str(tmp_path / "w"), fs=fs)
        payloads = _fill(wal, 5, sync=True)
        wal.write(b"torn-away-1")
        wal.write(b"torn-away-2")
        fs.halt()
        wal.close()
        fs.simulate_crash()
        assert fs.faults["torn_write"] == 1
        wal2 = WAL(str(tmp_path / "w"), fs=fs)
        got = [r.data for r in wal2.iter_records()]
        # a partial mid-record tail was rotated aside, whole prefix kept
        assert got == payloads[: len(got)] and len(got) >= 5
        if wal2.last_repair:
            rep = wal2.last_repair[0]
            assert os.path.exists(rep.tail_path)
            assert os.path.getsize(rep.path) == rep.valid_end
            # and the head is appendable again after repair
            wal2.write_sync(b"after-restart")
            assert [r.data for r in wal2.iter_records()][-1] == b"after-restart"
        wal2.close()

    def test_lost_fsync_is_acked_but_not_durable(self, tmp_path):
        fs = ChaosFS(ChaosFSConfig(seed=3, lost_fsync_rate=1.0))
        wal = WAL(str(tmp_path / "w"), fs=fs)
        _fill(wal, 4, sync=True)  # every fsync acked, none durable
        fs.halt()
        wal.close()
        fs.simulate_crash()
        assert fs.faults["lost_fsync"] >= 4
        wal2 = WAL(str(tmp_path / "w"), fs=fs)
        assert list(wal2.iter_records()) == []
        wal2.close()

    def test_enospc_mid_record_rolls_back_partial_frame(self, tmp_path):
        fs = ChaosFS(ChaosFSConfig(seed=1, enospc_at_byte=40))
        wal = WAL(str(tmp_path / "w"), fs=fs)
        with pytest.raises(OSError):
            _fill(wal, 5, sync=True)
        assert fs.faults["enospc"] == 1
        # the partial frame was truncated away inline: no garbage gap,
        # and the trigger is one-shot so the "restarted" WAL can write
        wal.write_sync(b"after-enospc")
        fs.halt()
        wal.close()
        fs.simulate_crash()
        wal2 = WAL(str(tmp_path / "w"), fs=fs)
        recs = [r.data for r in wal2.iter_records()]
        assert recs and recs[-1] == b"after-enospc"
        wal2.close()

    def test_repair_survives_enospc_during_salvage(self, tmp_path):
        """Disk still full at restart: the forensic tail-salvage write
        fails with ENOSPC, but repair degrades (truncate without salvage)
        instead of turning the restart into a startup failure."""
        fs = ChaosFS(ChaosFSConfig(seed=7, torn_write_rate=1.0))
        wal = WAL(str(tmp_path / "w"), fs=fs)
        payloads = _fill(wal, 5, sync=True)
        wal.write(b"torn-away-1")
        wal.write(b"torn-away-2")
        fs.halt()
        wal.close()
        fs.simulate_crash()  # seed 7 tears mid-record (repair will fire)

        fs2 = ChaosFS(ChaosFSConfig(seed=1, enospc_at_byte=0))  # disk full NOW
        wal2 = WAL(str(tmp_path / "w"), fs=fs2)  # must not raise
        assert wal2.last_repair and wal2.last_repair[0].tail_path == ""
        assert not os.path.exists(str(tmp_path / "w" / "wal.corrupt.0"))
        got = [r.data for r in wal2.iter_records()]
        assert got == payloads[: len(got)] and len(got) >= 5
        wal2.write_sync(b"after")  # one-shot ENOSPC already spent
        wal2.close()

    def test_bitrot_detected_and_truncated_with_metric(self, tmp_path):
        fs = ChaosFS(ChaosFSConfig(seed=9))
        wal = WAL(str(tmp_path / "w"), fs=fs)
        payloads = _fill(wal, 6, sync=True)
        wal.close()
        before = STORAGE["wal_corrupt_records"]
        rot = ChaosFS(ChaosFSConfig(seed=2, bitrot_rate=0.3))
        wal2 = WAL.__new__(WAL)  # read through the rotten fs WITHOUT repair
        wal2.dir = str(tmp_path / "w")
        wal2.fs = rot
        wal2._head_path = os.path.join(wal2.dir, "wal")
        wal2._f = None
        import logging

        wal2.logger = logging.getLogger("wal-test")
        got = [r.data for r in wal2.iter_records()]
        # bit-rot either missed (full read) or truncated at the flip —
        # never garbage records, and never silent: the metric moved
        assert got == payloads[: len(got)]
        if len(got) < len(payloads):
            assert rot.faults["bitrot"] >= 1
            assert STORAGE["wal_corrupt_records"] > before

    def test_same_seed_same_crash(self, tmp_path):
        """Bit-reproducibility: two identical op sequences under the same
        seed crash to byte-identical survivors."""
        sizes = []
        for run in range(2):
            fs = ChaosFS(ChaosFSConfig(seed=42, torn_write_rate=0.5, lost_fsync_rate=0.3))
            wal = WAL(str(tmp_path / f"w{run}"), fs=fs)
            _fill(wal, 8, sync=True)
            fs.halt()
            wal.close()
            fs.simulate_crash()
            path = str(tmp_path / f"w{run}" / "wal")
            with open(path, "rb") as f:
                sizes.append(f.read())
        assert sizes[0] == sizes[1]


class TestChaosDB:
    def test_enospc_and_bitrot(self):
        fs = ChaosFS(ChaosFSConfig(seed=5, enospc_rate=1.0))
        db = ChaosDB(fs, MemDB())
        with pytest.raises(OSError):
            db.set(b"k", b"v")
        with pytest.raises(OSError):
            db.write_batch([(b"k", b"v")])
        assert fs.faults["db_enospc"] == 2
        assert db.get(b"k") is None  # batch applied nothing

        fs2 = ChaosFS(ChaosFSConfig(seed=5, bitrot_rate=1.0))
        db2 = ChaosDB(fs2, MemDB())
        db2.set(b"k", b"value")
        assert db2.get(b"k") != b"value"  # exactly one flipped byte
        assert fs2.faults["db_bitrot"] == 1


class TestChaosNetNewFaults:
    def test_asymmetric_partition(self):
        net = ChaosNetwork(ChaosConfig(seed=1))
        net.partition_oneway("a", "b")
        assert net.plan("a", "b", 0).drop  # a→b dies
        assert not net.plan("b", "a", 0).drop  # b→a flows
        assert net.faults["asym_drop"] == 1
        net.heal()
        assert not net.plan("a", "b", 0).drop

    def test_bandwidth_shaping_queue_buildup(self):
        net = ChaosNetwork(ChaosConfig(seed=1, bandwidth_rate=1000.0))
        d1 = net.plan("a", "b", 0, nbytes=500, now=10.0).delay_s
        d2 = net.plan("a", "b", 0, nbytes=500, now=10.0).delay_s
        d3 = net.plan("a", "b", 0, nbytes=500, now=10.0).delay_s
        # each 500B message takes 0.5s on a 1000B/s link; the queue builds
        assert abs(d1 - 0.5) < 1e-9 and abs(d2 - 1.0) < 1e-9 and abs(d3 - 1.5) < 1e-9
        assert net.faults["shaped"] == 2  # msgs 2 and 3 queued behind msg 1
        # another link has its own bucket
        assert abs(net.plan("a", "c", 0, nbytes=500, now=10.0).delay_s - 0.5) < 1e-9

    def test_gray_failure_fixed_delay(self):
        net = ChaosNetwork(ChaosConfig(seed=1))
        net.set_gray("b", delay_ms=150.0)
        p = net.plan("a", "b", 0)
        assert not p.drop and abs(p.delay_s - 0.15) < 1e-9
        assert net.faults["gray_delay"] == 1
        assert net.plan("a", "c", 0).delay_s == 0.0  # only the gray peer crawls

    def test_clock_skew_deterministic_per_node(self):
        net1 = ChaosNetwork(ChaosConfig(seed=11, clock_skew_ms=100.0))
        net2 = ChaosNetwork(ChaosConfig(seed=11, clock_skew_ms=100.0))
        base = ManualClock(1_000_000_000)
        c1 = net1.clock_for("nodeA", base=base)
        # order-independent: hand out B first on the second controller
        net2.clock_for("nodeB", base=base)
        c2 = net2.clock_for("nodeA", base=base)
        assert isinstance(c1, SkewedClock)
        assert c1.offset_ns == c2.offset_ns
        assert abs(c1.offset_ns) <= 100_000_000
        assert c1.now_ns() == 1_000_000_000 + c1.offset_ns
        # different seed → different offset
        c3 = ChaosNetwork(ChaosConfig(seed=12, clock_skew_ms=100.0)).clock_for(
            "nodeA", base=base
        )
        assert c3.offset_ns != c1.offset_ns
        # fault class off → base clock untouched
        off = ChaosNetwork(ChaosConfig(seed=11)).clock_for("nodeA", base=base)
        assert off is base

    def test_clock_drift_scales_timeouts(self):
        net = ChaosNetwork(ChaosConfig(seed=4, clock_drift=0.1))
        c = net.clock_for("nodeA")
        assert c.rate != 1.0 and abs(c.rate - 1.0) <= 0.1
        # a fast clock waits LESS real time for the same nominal duration
        assert abs(c.timeout_s(1_000_000_000) - 1.0 / c.rate) < 1e-9
        # drawn from (seed, node_id): reproducible, order-independent
        assert ChaosNetwork(ChaosConfig(seed=4, clock_drift=0.1)).clock_for(
            "nodeA"
        ).rate == c.rate


def test_fs_callsite_lint_clean():
    """scripts/check_fs_callsites.py is the tier-1 guard against storage
    writes sneaking around the injectable chaos-fs layer."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_fs_callsites.py")],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
