"""Light client tests (modeled on reference light/verifier_test.go and
light/client_test.go: sequential, skipping with validator rotation,
backwards, expired trust, divergence detection)."""

import asyncio
from fractions import Fraction

import pytest

from tendermint_tpu.consensus.harness import LocalNetwork
from tendermint_tpu.light.client import (
    Divergence,
    LightClient,
    TrustOptions,
    TrustedStore,
)
from tendermint_tpu.light.provider import BlockStoreProvider, LightBlockNotFoundError
from tendermint_tpu.light.types import LightBlock, SignedHeader
from tendermint_tpu.light import verifier
from tendermint_tpu.light.verifier import VerificationError
from tendermint_tpu.testing import make_commit
from tendermint_tpu.types.block import BlockID


HOUR_NS = 3600 * 10**9
LONG_NS = 10 * 365 * 24 * HOUR_NS  # block 1 carries the (old) genesis time


async def run_chain(n_vals=3, heights=4):
    """Produce a real chain and return (net, provider for node 0)."""
    net = LocalNetwork(n_vals)
    await net.start()
    await net.wait_for_height(heights, timeout=60)
    await net.stop()
    node = net.nodes[0]
    return net, BlockStoreProvider(net.genesis.chain_id, node.block_store, node.state_store)


class TestVerifier:
    @pytest.mark.asyncio
    async def test_adjacent_and_nonadjacent(self):
        net, provider = await run_chain(heights=5)
        chain_id = net.genesis.chain_id
        lb1 = await provider.light_block(1)
        lb2 = await provider.light_block(2)
        lb4 = await provider.light_block(4)
        now = lb4.header.time_ns + 1_000_000_000
        verifier.verify_adjacent(chain_id, lb1, lb2, LONG_NS, now)
        # skipping 1 -> 4 (same validator set: 100% overlap)
        verifier.verify_non_adjacent(chain_id, lb1, lb4, LONG_NS, now)
        # reversed heights rejected
        with pytest.raises(VerificationError):
            verifier.verify_adjacent(chain_id, lb2, lb1, LONG_NS, now)

    @pytest.mark.asyncio
    async def test_adjacent_chain_bulk(self):
        """verify_adjacent_chain == the sequential verify_adjacent loop:
        same acceptance, same rejection (with height attribution), one
        range-batched signature proof instead of per-header calls."""
        import dataclasses

        net, provider = await run_chain(heights=6)
        chain_id = net.genesis.chain_id
        blocks = [await provider.light_block(h) for h in range(1, 6)]
        now = blocks[-1].header.time_ns + 1_000_000_000

        head = verifier.verify_adjacent_chain(
            chain_id, blocks[0], blocks[1:], LONG_NS, now
        )
        assert head.height == blocks[-1].height

        # non-adjacent gap rejected
        with pytest.raises(VerificationError):
            verifier.verify_adjacent_chain(
                chain_id, blocks[0], blocks[2:], LONG_NS, now
            )

        # tampered commit rejected, naming the right height
        lb3 = blocks[2]
        sigs = list(lb3.signed_header.commit.signatures)
        s0 = sigs[0]
        sigs[0] = dataclasses.replace(
            s0, signature=s0.signature[:63] + bytes([s0.signature[63] ^ 1])
        )
        bad = LightBlock(
            SignedHeader(
                lb3.header,
                dataclasses.replace(
                    lb3.signed_header.commit, signatures=tuple(sigs)
                ),
            ),
            lb3.validators,
        )
        with pytest.raises(VerificationError, match=str(lb3.height)):
            verifier.verify_adjacent_chain(
                chain_id,
                blocks[0],
                [blocks[1], bad, blocks[3], blocks[4]],
                LONG_NS,
                now,
            )

    @pytest.mark.asyncio
    async def test_expired_trust_rejected(self):
        net, provider = await run_chain(heights=3)
        chain_id = net.genesis.chain_id
        lb1 = await provider.light_block(1)
        lb2 = await provider.light_block(2)
        long_after = lb1.header.time_ns + 10 * HOUR_NS
        with pytest.raises(VerificationError):
            verifier.verify_adjacent(chain_id, lb1, lb2, HOUR_NS, long_after)

    @pytest.mark.asyncio
    async def test_tampered_commit_rejected(self):
        net, provider = await run_chain(heights=3)
        chain_id = net.genesis.chain_id
        lb1 = await provider.light_block(1)
        lb2 = await provider.light_block(2)
        # graft a commit whose signatures are for a different block id
        from tendermint_tpu.testing import make_block_id

        fake_bid = make_block_id(b"attack")
        bad_commit = make_commit(
            chain_id, 2, lb2.signed_header.commit.round, fake_bid,
            lb2.validators,
            {k.pub_key().address(): k for k in net.keys},
        )
        bad_lb = LightBlock(SignedHeader(lb2.header, bad_commit), lb2.validators)
        now = lb2.header.time_ns + 10**9
        with pytest.raises((VerificationError, ValueError)):
            verifier.verify_adjacent(chain_id, lb1, bad_lb, LONG_NS, now)


class TestLightClient:
    @pytest.mark.asyncio
    async def test_initialize_and_verify_forward(self):
        net, provider = await run_chain(heights=5)
        chain_id = net.genesis.chain_id
        lb1 = await provider.light_block(1)
        client = LightClient(
            chain_id,
            TrustOptions(LONG_NS, 1, lb1.header.hash()),
            provider,
        )
        tip = await provider.light_block(0)
        got = await client.verify_light_block_at_height(tip.height)
        assert got.header.hash() == tip.header.hash()
        # intermediate headers cached in the trusted store on bisection path
        assert client.store.latest().height == tip.height

    @pytest.mark.asyncio
    async def test_initialize_rejects_wrong_hash(self):
        net, provider = await run_chain(heights=3)
        client = LightClient(
            net.genesis.chain_id,
            TrustOptions(LONG_NS, 1, b"\x00" * 32),
            provider,
        )
        with pytest.raises(VerificationError):
            await client.initialize()

    @pytest.mark.asyncio
    async def test_backwards_verification(self):
        net, provider = await run_chain(heights=5)
        chain_id = net.genesis.chain_id
        lb4 = await provider.light_block(4)
        client = LightClient(
            chain_id,
            TrustOptions(LONG_NS, 4, lb4.header.hash()),
            provider,
        )
        await client.initialize()
        lb2 = await client.verify_light_block_at_height(2)
        assert lb2.height == 2
        assert lb2.header.hash() == (await provider.light_block(2)).header.hash()

    @pytest.mark.asyncio
    async def test_witness_divergence_detected(self):
        net, provider = await run_chain(heights=4)
        chain_id = net.genesis.chain_id
        lb1 = await provider.light_block(1)

        class ForkedProvider(BlockStoreProvider):
            """Witness serving a validly-signed CONFLICTING header."""

            async def light_block(self, height):
                lb = await super().light_block(height)
                if lb.height < 3:
                    return lb
                keys = {k.pub_key().address(): k for k in net.keys}
                # forge a different header (evil app hash) and sign it
                from dataclasses import replace

                evil = replace(lb.header, app_hash=b"\xde\xad" * 16)
                bid = BlockID(evil.hash(), lb.signed_header.commit.block_id.part_set_header)
                commit = make_commit(
                    chain_id, lb.height, 0, bid, lb.validators, keys
                )
                return LightBlock(SignedHeader(evil, commit), lb.validators)

        witness = ForkedProvider(
            chain_id, net.nodes[0].block_store, net.nodes[0].state_store
        )
        client = LightClient(
            chain_id,
            TrustOptions(LONG_NS, 1, lb1.header.hash()),
            provider,
            witnesses=[witness],
        )
        with pytest.raises(Divergence):
            await client.verify_light_block_at_height(3)

    @pytest.mark.asyncio
    async def test_bad_witness_dropped_not_fatal(self):
        net, provider = await run_chain(heights=3)
        chain_id = net.genesis.chain_id
        lb1 = await provider.light_block(1)

        class GarbageProvider(BlockStoreProvider):
            async def light_block(self, height):
                lb = await super().light_block(height)
                from dataclasses import replace

                evil = replace(lb.header, app_hash=b"\xbb" * 32)
                # unsigned garbage: commit doesn't match the forged header
                return LightBlock(
                    SignedHeader(evil, lb.signed_header.commit), lb.validators
                )

        witness = GarbageProvider(
            chain_id, net.nodes[0].block_store, net.nodes[0].state_store
        )
        client = LightClient(
            chain_id,
            TrustOptions(LONG_NS, 1, lb1.header.hash()),
            provider,
            witnesses=[witness],
        )
        got = await client.verify_light_block_at_height(2)
        assert got.height == 2
        assert client.witnesses == []  # garbage witness removed
