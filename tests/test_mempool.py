"""Mempool tests (modeled on reference internal/mempool/v1/mempool_test.go
and cache_test.go)."""

import asyncio

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.application import BaseApplication
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.mempool.pool import (
    MempoolFullError,
    PriorityMempool,
    TxCache,
    TxInCacheError,
    TxRejectedError,
)


class PriorityApp(BaseApplication):
    """CheckTx assigns priority from the tx's leading digits; rejects txs
    containing 'bad'; on recheck rejects txs containing 'stale'."""

    def check_tx(self, req):
        if b"bad" in req.tx:
            return abci.ResponseCheckTx(code=1, log="bad tx")
        if req.type == abci.CheckTxType.RECHECK and b"stale" in req.tx:
            return abci.ResponseCheckTx(code=2, log="stale")
        try:
            prio = int(req.tx.split(b":")[0])
        except ValueError:
            prio = 0
        return abci.ResponseCheckTx(priority=prio, gas_wanted=1)


def make_pool(**cfg) -> PriorityMempool:
    config = MempoolConfig(**cfg)
    return PriorityMempool(config, LocalClient(PriorityApp()))


class TestTxCache:
    def test_lru_eviction(self):
        c = TxCache(2)
        assert c.push(b"a") and c.push(b"b")
        assert not c.push(b"a")  # refreshes a
        assert c.push(b"c")  # evicts b (least recent)
        assert c.has(b"a") and c.has(b"c") and not c.has(b"b")
        c.remove(b"a")
        assert not c.has(b"a")


class TestPriorityMempool:
    @pytest.mark.asyncio
    async def test_checktx_and_priority_order(self):
        mp = make_pool()
        for tx in [b"1:a", b"9:b", b"5:c"]:
            await mp.check_tx(tx)
        assert mp.size() == 3
        assert mp.reap_max_txs(-1) == [b"9:b", b"5:c", b"1:a"]
        # byte budget cuts the reap
        assert mp.reap_max_bytes_max_gas(8, -1) == [b"9:b", b"5:c"]
        # gas budget: each tx wants 1 gas
        assert mp.reap_max_bytes_max_gas(-1, 2) == [b"9:b", b"5:c"]

    @pytest.mark.asyncio
    async def test_rejected_and_cached(self):
        mp = make_pool()
        with pytest.raises(TxRejectedError):
            await mp.check_tx(b"bad:1")
        # rejected tx NOT kept in cache by default → can be resubmitted
        with pytest.raises(TxRejectedError):
            await mp.check_tx(b"bad:1")
        await mp.check_tx(b"3:x")
        with pytest.raises(TxInCacheError):
            await mp.check_tx(b"3:x")

    @pytest.mark.asyncio
    async def test_eviction_by_priority(self):
        mp = make_pool(size=2)
        await mp.check_tx(b"1:a")
        await mp.check_tx(b"2:b")
        # higher priority newcomer evicts the lowest resident
        await mp.check_tx(b"5:c")
        assert mp.size() == 2
        assert mp.reap_max_txs(-1) == [b"5:c", b"2:b"]
        # lower priority newcomer is refused
        with pytest.raises(MempoolFullError):
            await mp.check_tx(b"0:d")

    @pytest.mark.asyncio
    async def test_update_removes_committed_and_rechecks(self):
        mp = make_pool()
        await mp.check_tx(b"5:keep")
        await mp.check_tx(b"4:stale-later")
        await mp.check_tx(b"3:gone")
        ok = abci.ResponseDeliverTx()
        async with mp.lock():
            await mp.update(2, [b"3:gone"], [ok])
        assert mp.size() == 1  # stale-later failed recheck, gone committed
        assert mp.reap_max_txs(-1) == [b"5:keep"]
        # committed tx stays in cache → resubmission rejected
        with pytest.raises(TxInCacheError):
            await mp.check_tx(b"3:gone")

    @pytest.mark.asyncio
    async def test_tx_too_large(self):
        mp = make_pool(max_tx_bytes=10)
        with pytest.raises(TxRejectedError):
            await mp.check_tx(b"1:" + b"x" * 20)

    @pytest.mark.asyncio
    async def test_wait_for_txs(self):
        mp = make_pool()
        waiter = asyncio.create_task(mp.wait_for_txs())
        await asyncio.sleep(0.01)
        assert not waiter.done()
        await mp.check_tx(b"1:a")
        await asyncio.wait_for(waiter, 1.0)


class TestMempoolThroughConsensus:
    @pytest.mark.asyncio
    async def test_txs_get_committed(self):
        """Txs admitted to any node's mempool appear in committed blocks
        and are removed from the mempool afterwards."""
        from tendermint_tpu.consensus.harness import LocalNetwork

        net = LocalNetwork(2)
        await net.start()
        try:
            for node in net.nodes:
                await node.mempool.check_tx(b"k1=v1")
                # same tx on both nodes: in-cache on neither is an error here
            h0 = net.nodes[0].cs.rs.height
            await net.wait_for_height(h0 + 2, timeout=30)
            committed = []
            for h in range(1, net.nodes[0].block_store.height() + 1):
                blk = net.nodes[0].block_store.load_block(h)
                if blk:
                    committed.extend(blk.txs)
            assert b"k1=v1" in committed
            assert all(n.mempool.size() == 0 for n in net.nodes)
            # the app executed it: query returns the value
            from tendermint_tpu.abci import types as abci

            res = net.nodes[0].app.query(abci.RequestQuery(data=b"k1"))
            assert res.value == b"v1"
        finally:
            await net.stop()
