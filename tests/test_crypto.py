"""Host crypto tests: ed25519 (incl. ZIP-215 oracle vs RFC 8032 backend),
secp256k1 low-S, merkle tree/proofs, batch verifier dispatch."""

import hashlib
import secrets

import pytest

from tendermint_tpu.crypto import ed25519, ed25519_math, secp256k1
from tendermint_tpu.crypto import merkle
from tendermint_tpu.crypto.batch import (
    CPUBatchVerifier,
    create_batch_verifier,
    supports_batch_verifier,
)
from tendermint_tpu.crypto import pubkey_from_type_and_bytes
from tendermint_tpu.crypto.hashes import address, sha256


def test_ed25519_sign_verify():
    sk = ed25519.Ed25519PrivKey.generate()
    pk = sk.pub_key()
    msg = b"consensus is hard"
    sig = sk.sign(msg)
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg + b"!", sig)
    assert not pk.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    assert not pk.verify_signature(msg, b"short")


def test_ed25519_oracle_agrees_with_openssl():
    for i in range(20):
        seed = secrets.token_bytes(32)
        sk = ed25519.Ed25519PrivKey(seed)
        msg = secrets.token_bytes(i * 7 + 1)
        sig = sk.sign(msg)
        # pure-Python signer must produce the identical signature (RFC 8032 determinism)
        assert ed25519_math.sign(seed, msg) == sig
        assert ed25519_math.public_from_seed(seed) == sk.pub_key().bytes()
        assert ed25519_math.verify_zip215(sk.pub_key().bytes(), msg, sig)
        bad = bytearray(sig)
        bad[0] ^= 1
        assert not ed25519_math.verify_zip215(sk.pub_key().bytes(), msg, bytes(bad))


def test_ed25519_rejects_noncanonical_s():
    sk = ed25519.Ed25519PrivKey.generate()
    msg = b"m"
    sig = bytearray(sk.sign(msg))
    s = int.from_bytes(sig[32:], "little")
    sig[32:] = (s + ed25519_math.L).to_bytes(32, "little")
    assert not ed25519_math.verify_zip215(sk.pub_key().bytes(), msg, bytes(sig))


def test_ed25519_zip215_accepts_noncanonical_point_encoding():
    # ZIP-215: y-encodings >= p fold mod p. Encoding of p+1 represents y=1,
    # i.e. the identity point (0, 1).
    nc = (ed25519_math.P + 1).to_bytes(32, "little")
    pt = ed25519_math.Point.decompress(nc)
    assert pt is not None and pt.is_identity()
    # canonical encoding of the same point decompresses identically
    assert ed25519_math.Point.decompress((1).to_bytes(32, "little")).is_identity()
    # but 2^255-19+2 with no curve point at y=2... check a y with no x is rejected
    # (y=2: x^2=(4-1)/(4d+1); verify rejection matches _recover_x)
    y2 = ed25519_math.Point.decompress((2).to_bytes(32, "little"))
    x = ed25519_math._recover_x(2, 0)
    assert (y2 is None) == (x is None)


def test_ed25519_math_base_point():
    # base point order: L*B == identity
    assert ed25519_math.BASE.scalar_mul(ed25519_math.L).is_identity()
    # compress/decompress roundtrip
    P = ed25519_math.BASE.scalar_mul(12345)
    assert ed25519_math.Point.decompress(P.compress()).equals(P)


def test_secp256k1_sign_verify_low_s():
    sk = secp256k1.Secp256k1PrivKey.generate()
    pk = sk.pub_key()
    msg = b"ecdsa"
    sig = sk.sign(msg)
    assert len(sig) == 64
    s = int.from_bytes(sig[32:], "big")
    assert s <= secp256k1.HALF_N
    assert pk.verify_signature(msg, sig)
    # high-S version must be rejected even though mathematically valid
    high = sig[:32] + (secp256k1.N - s).to_bytes(32, "big")
    assert not pk.verify_signature(msg, high)
    assert not pk.verify_signature(b"other", sig)


def test_address_is_truncated_sha256():
    sk = ed25519.Ed25519PrivKey.generate()
    pk = sk.pub_key()
    assert pk.address() == hashlib.sha256(pk.bytes()).digest()[:20]
    assert len(address(pk.bytes())) == 20


def test_pubkey_registry_roundtrip():
    for sk in [ed25519.Ed25519PrivKey.generate(), secp256k1.Secp256k1PrivKey.generate()]:
        pk = sk.pub_key()
        pk2 = pubkey_from_type_and_bytes(pk.TYPE, pk.bytes())
        assert pk2 == pk


def test_merkle_empty_and_single():
    assert merkle.hash_from_byte_slices([]) == sha256(b"")
    one = merkle.hash_from_byte_slices([b"x"])
    assert one == sha256(b"\x00x")


def test_merkle_structure():
    items = [b"a", b"b", b"c"]
    # split point for 3 is 2: inner(inner(leaf a, leaf b), leaf c)
    la, lb, lc = (sha256(b"\x00" + i) for i in items)
    expect = sha256(b"\x01" + sha256(b"\x01" + la + lb) + lc)
    assert merkle.hash_from_byte_slices(items) == expect


def test_merkle_proofs():
    items = [f"item{i}".encode() for i in range(7)]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, p in enumerate(proofs):
        assert p.verify(root, items[i]), i
        assert not p.verify(root, b"wrong")
        assert not p.verify(sha256(b"bad root"), items[i])
        # encode/decode roundtrip
        p2 = merkle.Proof.decode(p.encode())
        assert p2.verify(root, items[i])


def test_batch_verifier_cpu():
    bv = CPUBatchVerifier()
    keys = [ed25519.Ed25519PrivKey.generate() for _ in range(8)]
    msgs = [f"msg{i}".encode() for i in range(8)]
    for k, m in zip(keys, msgs):
        bv.add(k.pub_key(), m, k.sign(m))
    ok, bits = bv.verify()
    assert ok and all(bits) and len(bits) == 8

    bv2 = CPUBatchVerifier()
    for i, (k, m) in enumerate(zip(keys, msgs)):
        sig = k.sign(m)
        if i == 3:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        bv2.add(k.pub_key(), m, sig)
    ok, bits = bv2.verify()
    assert not ok
    assert bits == [i != 3 for i in range(8)]


def test_batch_dispatch():
    ed = ed25519.Ed25519PrivKey.generate().pub_key()
    sec = secp256k1.Secp256k1PrivKey.generate().pub_key()
    assert supports_batch_verifier(ed)
    assert not supports_batch_verifier(sec)
    assert create_batch_verifier(ed) is not None
    with pytest.raises(ValueError):
        create_batch_verifier(sec)


class TestXChaCha20Poly1305:
    """Reference crypto/xchacha20poly1305/vector_test.go vectors."""

    HCHACHA_VECTORS = [
        # (key, nonce16, keystream) — reference vector_test.go:36-63 (the
        # 24-byte nonces there feed only their first 16 bytes to HChaCha20)
        ("00" * 32, "00" * 16,
         "1140704c328d1d5d0e30086cdf209dbd6a43b8f41518a11cc387b669b2ee6586"),
        ("80" + "00" * 31, "00" * 16,
         "7d266a7fd808cae4c02a0a70dcbfbcc250dae65ce3eae7fc210f54cc8f77df86"),
        # vector 3's 24-byte nonce has its only nonzero byte at index 23,
        # outside HChaCha20's 16-byte input — the Go harness truncates, so
        # the expectation holds for an all-zero nonce16
        ("00" * 31 + "01", "00" * 16,
         "e0c77ff931bb9163a5460c02ac281c2b53d792b1c43fea817e9ad275ae546963"),
        ("000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
         "000102030405060708090a0b0c0d0e0f",
         "51e3ff45a895675c4b33b46c64f4a9ace110d34df6a2ceab486372bacbd3eff6"),
        ("24f11cce8a1b3d61e441561a696c1c1b7e173d084fd4812425435a8896a013dc",
         "d9660c5900ae19ddad28d6e06e45fe5e",
         "5966b3eec3bff1189f831f06afe4d4e3be97fa9235ec8c20d08acfbbb4e851e3"),
    ]

    def test_hchacha20_vectors(self):
        from tendermint_tpu.crypto.xchacha20poly1305 import hchacha20

        for key_h, nonce_h, want_h in self.HCHACHA_VECTORS:
            got = hchacha20(bytes.fromhex(key_h), bytes.fromhex(nonce_h))
            assert got.hex() == want_h

    def test_seal_open_roundtrip_and_forgery(self):
        import os

        import pytest

        from tendermint_tpu.crypto.xchacha20poly1305 import (
            InvalidTag,
            XChaCha20Poly1305,
        )

        key = os.urandom(32)
        aead = XChaCha20Poly1305(key)
        nonce = os.urandom(24)
        ct = aead.seal(nonce, b"attack at dawn", b"header")
        assert aead.open(nonce, ct, b"header") == b"attack at dawn"
        # forgery / wrong aad / wrong nonce all fail
        with pytest.raises(InvalidTag):
            aead.open(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), b"header")
        with pytest.raises(InvalidTag):
            aead.open(nonce, ct, b"other")
        with pytest.raises(InvalidTag):
            aead.open(os.urandom(24), ct, b"header")

    def test_distinct_nonce_prefix_changes_subkey(self):
        from tendermint_tpu.crypto.xchacha20poly1305 import hchacha20

        k = bytes(range(32))
        assert hchacha20(k, bytes(16)) != hchacha20(k, b"\x01" + bytes(15))


class TestTPUDegradation:
    """crypto/batch.py: a TPU-backend failure mid-batch must degrade to
    the CPU path with IDENTICAL results, trip the circuit breaker, and a
    later half-open probe must restore TPU routing."""

    def _batch(self, n=6, bad=3):
        keys = [ed25519.Ed25519PrivKey.generate() for _ in range(n)]
        items = []
        for i, k in enumerate(keys):
            msg = b"degrade-%d" % i
            sig = k.sign(msg)
            if i == bad:
                sig = sig[:-1] + bytes([sig[-1] ^ 1])
            items.append((k.pub_key(), msg, sig))
        return items

    def test_fallback_identical_results_breaker_opens_then_probes(self, monkeypatch):
        from tendermint_tpu.crypto import batch as batch_mod
        from tendermint_tpu.libs.metrics import RESILIENCE
        from tendermint_tpu.libs.retry import CircuitBreaker

        class FakeClock:
            now = 1000.0

            def __call__(self):
                return self.now

        clock = FakeClock()
        breaker = CircuitBreaker(
            failure_threshold=1, reset_timeout=30.0, clock=clock, name="t"
        )
        monkeypatch.setattr(batch_mod, "_tpu_breaker", breaker)
        monkeypatch.setattr(batch_mod, "tpu_verifier_available", lambda: True)
        monkeypatch.setattr(batch_mod, "MIN_TPU_BATCH", 1)

        crashes = {"n": 0}
        tpu_calls = {"n": 0}

        class CrashingTPU(CPUBatchVerifier):
            def verify(self):
                crashes["n"] += 1
                raise RuntimeError("simulated TPU backend crash mid-batch")

        class HealthyTPU(CPUBatchVerifier):
            def verify(self):
                tpu_calls["n"] += 1
                return super().verify()

        items = self._batch()
        expect_cpu = CPUBatchVerifier()
        for pk, msg, sig in items:
            expect_cpu.add(pk, msg, sig)
        want = expect_cpu.verify()

        fallback_before = RESILIENCE["tpu_fallback_batches"]

        # 1) crash mid-batch -> transparent CPU fallback, identical tuple
        monkeypatch.setattr(
            batch_mod.AdaptiveBatchVerifier,
            "_make_tpu_verifier",
            lambda self: CrashingTPU(),
        )
        bv = create_batch_verifier(items[0][0])
        for pk, msg, sig in items:
            bv.add(pk, msg, sig)
        got = bv.verify()
        assert got == want  # same (ok, per-signature) result as pure CPU
        assert crashes["n"] == 1
        assert breaker.state == "open"
        assert RESILIENCE["tpu_fallback_batches"] == fallback_before + 1

        # 2) while open: TPU never touched, CPU results still correct
        bv = create_batch_verifier(items[0][0])
        for pk, msg, sig in items:
            bv.add(pk, msg, sig)
        assert bv.verify() == want
        assert crashes["n"] == 1  # no new device attempts

        # 3) reset timeout elapses -> half-open probe restores TPU routing
        monkeypatch.setattr(
            batch_mod.AdaptiveBatchVerifier,
            "_make_tpu_verifier",
            lambda self: HealthyTPU(),
        )
        clock.now += 30.0
        assert breaker.state == "half-open"
        bv = create_batch_verifier(items[0][0])
        for pk, msg, sig in items:
            bv.add(pk, msg, sig)
        assert bv.verify() == want
        assert tpu_calls["n"] == 1  # the probe went to the "device"
        assert breaker.state == "closed"
        # 4) and stays on the device afterwards
        bv = create_batch_verifier(items[0][0])
        for pk, msg, sig in items:
            bv.add(pk, msg, sig)
        assert bv.verify() == want
        assert tpu_calls["n"] == 2
