"""Process-boundary tests: out-of-process ABCI over sockets, and the
remote signer (modeled on reference abci/client/socket_client_test.go
and privval/signer_client_test.go)."""

import asyncio
import os
import tempfile

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.abci.socket import ABCIServer, SocketClient
from tendermint_tpu.privval import FilePV, MockPV, DoubleSignError
from tendermint_tpu.privval_remote import SignerClient, SignerServer
from tendermint_tpu.testing import make_block_id
from tendermint_tpu.types.keys import SignedMsgType
from tendermint_tpu.types.vote import Vote


class TestABCISocket:
    @pytest.mark.asyncio
    async def test_full_roundtrip(self):
        app = KVStoreApp()
        server = ABCIServer(app)
        await server.start()
        client = SocketClient("127.0.0.1", server.port)
        await client.start()
        try:
            assert await client.echo("hi") == "hi"
            info = await client.info(abci.RequestInfo())
            assert info.last_block_height == 0
            res = await client.check_tx(abci.RequestCheckTx(b"a=b"))
            assert res.is_ok()
            await client.init_chain(
                abci.RequestInitChain(0, "c", None, (), b"{}", 1)
            )
            # a block cycle over the socket
            from tendermint_tpu.types.block import Header

            await client.begin_block(
                abci.RequestBeginBlock(
                    hash=b"\x01" * 32,
                    header=Header(chain_id="c", height=1),
                    last_commit_info=abci.LastCommitInfo(0),
                )
            )
            dres = await client.deliver_tx(abci.RequestDeliverTx(b"a=b"))
            assert dres.is_ok()
            await client.end_block(abci.RequestEndBlock(1))
            cres = await client.commit()
            assert cres.data  # app hash
            q = await client.query(abci.RequestQuery(data=b"a"))
            assert q.value == b"b"
        finally:
            await client.stop()
            await server.stop()

    @pytest.mark.asyncio
    async def test_pipelining(self):
        """Many concurrent requests on one connection resolve correctly
        and in order."""
        app = KVStoreApp()
        server = ABCIServer(app)
        await server.start()
        client = SocketClient("127.0.0.1", server.port)
        await client.start()
        try:
            results = await asyncio.gather(
                *(client.check_tx(abci.RequestCheckTx(b"k%d=v" % i)) for i in range(50))
            )
            assert all(r.is_ok() for r in results)
        finally:
            await client.stop()
            await server.stop()

    @pytest.mark.asyncio
    async def test_node_runs_against_socket_app(self):
        """A consensus node driven entirely through the ABCI socket."""
        from tendermint_tpu.consensus.harness import Node as HNode, make_genesis
        from tendermint_tpu.proxy import AppConns

        app = KVStoreApp()
        server = ABCIServer(app)
        await server.start()
        genesis, keys = make_genesis(1)
        node = HNode(genesis, keys[0])

        def factory(name: str):
            return SocketClient("127.0.0.1", server.port)

        node.app_conns = AppConns.from_factory(factory)
        await node.app_conns.start()
        await node.start()
        try:
            await node.cs.wait_for_height(2, timeout=30)
            assert app.height >= 2
        finally:
            await node.stop()
            await server.stop()


class TestRemoteSigner:
    @pytest.mark.asyncio
    async def test_sign_via_socket(self):
        with tempfile.TemporaryDirectory() as tmp:
            pv = FilePV.generate(
                os.path.join(tmp, "k.json"), os.path.join(tmp, "s.json")
            )
            server = SignerServer(pv)
            await server.start()
            client = SignerClient("127.0.0.1", server.port)

            def sync_part():
                pub = client.get_pub_key()
                assert pub.bytes() == pv.get_pub_key().bytes()
                vote = Vote(
                    type=SignedMsgType.PREVOTE,
                    height=3,
                    round=0,
                    block_id=make_block_id(b"x"),
                    timestamp_ns=1_700_000_000_000_000_000,
                    validator_address=pub.address(),
                    validator_index=0,
                )
                signed = client.sign_vote("chain", vote)
                assert pub.verify_signature(vote.sign_bytes("chain"), signed.signature)
                # double-sign guard propagates over the wire
                conflicting = Vote(
                    **{**vote.__dict__, "block_id": make_block_id(b"y")}
                )
                try:
                    client.sign_vote("chain", conflicting)
                    assert False, "expected DoubleSignError"
                except DoubleSignError:
                    pass

            await asyncio.to_thread(sync_part)
            await server.stop()

    @pytest.mark.asyncio
    async def test_consensus_with_remote_signer(self):
        """A validator whose key lives behind the signer socket. The
        server runs on its own thread loop — the consensus-side client
        blocks while signing, exactly like a separate signer process."""
        from tendermint_tpu.consensus.harness import Node as HNode, make_genesis
        from tendermint_tpu.privval_remote import ThreadedSignerServer

        genesis, keys = make_genesis(1)
        server = ThreadedSignerServer(MockPV(keys[0]))
        port = server.start()
        node = HNode(genesis, None)
        node.priv_val = SignerClient("127.0.0.1", port)
        await node.start()
        try:
            await node.cs.wait_for_height(2, timeout=30)
        finally:
            await node.stop()
            server.stop()


class TestABCIGrpc:
    """gRPC attachment mode (reference abci/client/grpc_client.go,
    abci/server/grpc_server.go) — same method table and codec as the
    socket transport."""

    @pytest.mark.asyncio
    async def test_full_roundtrip(self):
        from tendermint_tpu.abci.grpcnet import GrpcABCIServer, GrpcClient

        app = KVStoreApp()
        server = GrpcABCIServer(app)
        await server.start()
        client = GrpcClient("127.0.0.1", server.port)
        await client.start()
        try:
            assert await client.echo("hi") == "hi"
            info = await client.info(abci.RequestInfo())
            assert info.last_block_height == 0
            await client.init_chain(
                abci.RequestInitChain(0, "c", None, (), b"{}", 1)
            )
            from tendermint_tpu.types.block import Header

            await client.begin_block(
                abci.RequestBeginBlock(
                    hash=b"\x01" * 32,
                    header=Header(chain_id="c", height=1),
                    last_commit_info=abci.LastCommitInfo(0),
                )
            )
            dres = await client.deliver_tx(abci.RequestDeliverTx(b"g=rpc"))
            assert dres.is_ok()
            await client.end_block(abci.RequestEndBlock(1))
            cres = await client.commit()
            assert cres.data
            q = await client.query(abci.RequestQuery(data=b"g"))
            assert q.value == b"rpc"
        finally:
            await client.stop()
            await server.stop()

    @pytest.mark.asyncio
    async def test_node_runs_against_grpc_app(self):
        """Full consensus through the gRPC app connection."""
        from tendermint_tpu.abci.grpcnet import GrpcABCIServer, GrpcClient
        from tendermint_tpu.consensus.harness import Node as HNode, make_genesis
        from tendermint_tpu.proxy import AppConns

        app = KVStoreApp()
        server = GrpcABCIServer(app)
        await server.start()
        genesis, keys = make_genesis(1)
        node = HNode(genesis, keys[0])

        def factory(name: str):
            return GrpcClient("127.0.0.1", server.port)

        node.app_conns = AppConns.from_factory(factory)
        await node.app_conns.start()
        await node.start()
        try:
            await node.cs.wait_for_height(2, timeout=30)
            assert app.height >= 2
        finally:
            await node.stop()
            await server.stop()


class TestGrpcSigner:
    @pytest.mark.asyncio
    async def test_sign_via_grpc(self):
        """privval gRPC mode (reference privval/grpc/{server,client}.go):
        pubkey fetch, vote signing, double-sign guard over the channel."""
        from tendermint_tpu.privval_remote import GrpcSignerClient, GrpcSignerServer

        with tempfile.TemporaryDirectory() as tmp:
            pv = FilePV.generate(
                os.path.join(tmp, "k.json"), os.path.join(tmp, "s.json")
            )
            server = GrpcSignerServer(pv)
            port = server.start()
            client = GrpcSignerClient("127.0.0.1", port)

            def sync_part():
                pub = client.get_pub_key()
                assert pub.bytes() == pv.get_pub_key().bytes()
                vote = Vote(
                    type=SignedMsgType.PREVOTE,
                    height=3,
                    round=0,
                    block_id=make_block_id(b"x"),
                    timestamp_ns=1_700_000_000_000_000_000,
                    validator_address=pub.address(),
                    validator_index=0,
                )
                signed = client.sign_vote("chain", vote)
                assert pub.verify_signature(
                    vote.sign_bytes("chain"), signed.signature
                )
                conflicting = Vote(
                    **{**vote.__dict__, "block_id": make_block_id(b"y")}
                )
                try:
                    client.sign_vote("chain", conflicting)
                    assert False, "expected DoubleSignError"
                except DoubleSignError:
                    pass
                client.close()

            await asyncio.to_thread(sync_part)
            server.stop()
