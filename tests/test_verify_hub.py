"""VerifyHub tests: micro-batch window semantics, per-item result
routing, dedup-cache + in-flight coalescing, TPU-breaker CPU-fallback
identity, clean shutdown with in-flight requests, adoption (votes,
proposals, commits route through the hub), the callsite lint, and the
4-node live-consensus cache-hit acceptance check."""

import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu.crypto import verify_hub as vh
from tendermint_tpu.crypto.batch import CPUBatchVerifier
from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
from tendermint_tpu.crypto.verify_hub import VerifyHub

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _items(n, tag=b"vh", priv=None):
    priv = priv or Ed25519PrivKey(b"\x11" * 32)
    pub = priv.pub_key()
    out = []
    for i in range(n):
        msg = tag + b"-%d" % i
        out.append((pub, msg, priv.sign(msg)))
    return out


@pytest.fixture
def hub():
    """Standalone hub (not the process default) for scheduler tests."""
    h = VerifyHub(max_batch=8, window_ms=100.0, cache_size=256, adaptive=False)
    h.start()
    yield h
    h.stop()


@pytest.fixture
def process_hub():
    """The process-wide hub — what verify_one / Vote.verify / the
    validation shim discover via running_hub()."""
    h = vh.acquire_hub(max_batch=8, window_ms=100.0, cache_size=256, adaptive=False)
    yield h
    vh.release_hub()


class TestScheduling:
    def test_sync_facade_verdicts(self, hub):
        (pub, msg, sig), = _items(1)
        assert hub.verify_sync(pub, msg, sig) is True
        assert hub.verify_sync(pub, msg, b"\x00" * 64) is False

    def test_window_coalesces_concurrent_submissions(self, hub):
        """Non-urgent requests submitted inside the window land in ONE
        dispatch (batch occupancy = number of requests)."""
        futs = [hub.submit_nowait(pk, m, s) for pk, m, s in _items(4)]
        assert all(f.result(10.0) is True for f in futs)
        s = hub.stats()
        assert s["dispatches"] == 1, s
        assert s["dispatched_sigs"] == 4
        assert s["mean_occupancy"] == 4.0

    def test_full_batch_dispatches_before_window(self):
        """max_batch queued requests dispatch immediately — the window
        is a deadline, not a delay."""
        h = VerifyHub(max_batch=8, window_ms=3000.0, cache_size=64, adaptive=False)
        h.start()
        try:
            t0 = time.monotonic()
            futs = [h.submit_nowait(pk, m, s) for pk, m, s in _items(8, b"full")]
            assert all(f.result(10.0) is True for f in futs)
            # well under the 3s window: the full batch fired on size
            assert time.monotonic() - t0 < 2.0
            assert h.stats()["dispatches"] == 1
        finally:
            h.stop()

    def test_per_item_result_routing(self, hub):
        """One bad signature fails only its own future."""
        items = _items(6, b"route")
        pub, msg, _ = items[2]
        items[2] = (pub, msg, items[3][2])  # sig for a different msg
        res = hub.verify_many(items)
        assert res == [True, True, False, True, True, True]

    def test_dedup_cache_hit(self, hub):
        (pub, msg, sig), = _items(1, b"dup")
        assert hub.verify_sync(pub, msg, sig) is True
        assert hub.verify_sync(pub, msg, sig) is True
        s = hub.stats()
        assert s["cache_hits"] == 1
        assert s["dispatched_sigs"] == 1  # the duplicate never dispatched
        # negative verdicts are cached too (deterministic)
        assert hub.verify_sync(pub, msg, b"\x01" * 64) is False
        assert hub.verify_sync(pub, msg, b"\x01" * 64) is False
        assert hub.stats()["cache_hits"] == 2

    def test_inflight_duplicate_coalesces(self, hub):
        """An identical triple submitted while the first is still queued
        attaches to the SAME pending verify — the device sees it once."""
        (pub, msg, sig), = _items(1, b"join")
        f1 = hub.submit_nowait(pub, msg, sig)
        f2 = hub.submit_nowait(pub, msg, sig)
        assert f1.result(10.0) is True and f2.result(10.0) is True
        s = hub.stats()
        assert s["coalesced"] == 1
        assert s["dispatched_sigs"] == 1

    def test_async_api(self, hub):
        import asyncio

        items = _items(5, b"async")

        async def go():
            return await asyncio.gather(
                *(hub.verify(pk, m, s) for pk, m, s in items)
            )

        assert asyncio.run(go()) == [True] * 5

    def test_clean_shutdown_resolves_inflight(self):
        """stop() drains: every future submitted before shutdown still
        resolves with a correct verdict."""
        h = VerifyHub(max_batch=16, window_ms=500.0, cache_size=64, adaptive=False)
        h.start()
        items = _items(40, b"drain")
        futs = [h.submit_nowait(pk, m, s) for pk, m, s in items]
        h.stop()  # long window: most of the queue is still undispatched
        assert all(f.result(10.0) is True for f in futs)
        # post-shutdown submissions verify inline, never hang
        (pub, msg, sig), = _items(1, b"late")
        assert h.submit_nowait(pub, msg, sig).result(1.0) is True

    def test_verifier_exception_fails_batch_futures(self, hub, monkeypatch):
        def boom(_pk):
            raise RuntimeError("verifier construction exploded")

        monkeypatch.setattr(vh, "create_batch_verifier", boom)
        futs = [hub.submit_nowait(pk, m, s) for pk, m, s in _items(3, b"err")]
        hub.flush()
        for f in futs:
            with pytest.raises(RuntimeError):
                f.result(10.0)
        assert hub.stats()["verify_errors"] == 1


class TestFallbackIdentity:
    def test_tpu_crash_degrades_to_identical_cpu_results(self, hub, monkeypatch):
        """A TPU failure mid-hub-batch trips the breaker and the batch
        transparently re-verifies on the CPU — hub verdicts identical to
        the pure-CPU path (same contract as AdaptiveBatchVerifier)."""
        from tendermint_tpu.crypto import batch as batch_mod
        from tendermint_tpu.libs.metrics import RESILIENCE
        from tendermint_tpu.libs.retry import CircuitBreaker

        breaker = CircuitBreaker(failure_threshold=1, reset_timeout=30.0, name="t")
        monkeypatch.setattr(batch_mod, "_tpu_breaker", breaker)
        monkeypatch.setattr(batch_mod, "tpu_verifier_available", lambda: True)
        monkeypatch.setattr(batch_mod, "MIN_TPU_BATCH", 1)

        class CrashingTPU(CPUBatchVerifier):
            def verify(self):
                raise RuntimeError("simulated TPU backend crash mid-batch")

        monkeypatch.setattr(
            batch_mod.AdaptiveBatchVerifier,
            "_make_tpu_verifier",
            lambda self: CrashingTPU(),
        )

        items = _items(6, b"fb")
        pub, msg, _ = items[4]
        items[4] = (pub, msg, b"\x02" * 64)  # one bad sig survives fallback too

        expect = CPUBatchVerifier()
        for pk, m, s in items:
            expect.add(pk, m, s)
        _, want = expect.verify()

        fallback_before = RESILIENCE["tpu_fallback_batches"]
        got = hub.verify_many(items)
        assert got == want
        assert breaker.state == "open"
        assert RESILIENCE["tpu_fallback_batches"] == fallback_before + 1


class TestAdoption:
    def test_vote_verify_routes_through_hub(self, process_hub):
        hub = process_hub
        from tendermint_tpu import testing as tt
        from tendermint_tpu.types.keys import SignedMsgType

        vals, keys = tt.make_validator_set(4)
        val = vals.validators[0]
        vote = tt.make_vote(
            "hub-chain", keys[val.address], 0, 1, 0,
            SignedMsgType.PREVOTE, tt.make_block_id(),
        )
        before = hub.stats()["dispatched_sigs"]
        assert vote.verify("hub-chain", val.pub_key) is True
        assert hub.stats()["dispatched_sigs"] == before + 1
        # gossip duplicate: second verification is a cache hit
        hits = hub.stats()["cache_hits"]
        assert vote.verify("hub-chain", val.pub_key) is True
        assert hub.stats()["cache_hits"] == hits + 1

    def test_commit_verification_routes_through_hub(self, process_hub):
        hub = process_hub
        from tendermint_tpu import testing as tt
        from tendermint_tpu.types import validation

        vals, keys = tt.make_validator_set(4)
        bid = tt.make_block_id(b"commit-hub")
        commit = tt.make_commit("hub-chain", 1, 0, bid, vals, keys)
        before = hub.stats()["dispatched_sigs"]
        validation.verify_commit("hub-chain", vals, bid, 1, commit)
        assert hub.stats()["dispatched_sigs"] > before

    def test_fallbacks_without_hub(self):
        """No hub running -> verify_one and the validation shim hit the
        host directly (library/unit-test mode, bypass by design)."""
        assert vh.running_hub() is None
        (pub, msg, sig), = _items(1, b"nohub")
        assert vh.verify_one(pub, msg, sig) is True
        assert vh.verify_one(pub, msg, b"\x03" * 64) is False

    def test_metrics_render_folds_hub_series(self):
        from tendermint_tpu.libs.metrics import NodeMetrics

        hub = vh.acquire_hub(max_batch=8, window_ms=1.0)
        try:
            (pub, msg, sig), = _items(1, b"metrics")
            hub.verify_sync(pub, msg, sig)
            hub.verify_sync(pub, msg, sig)
            out = NodeMetrics().render()
            assert "tendermint_tpu_verifyhub_dispatches 1" in out
            assert "tendermint_tpu_verifyhub_cache_hits 1" in out
            assert "tendermint_tpu_verifyhub_batch_occupancy" in out
            assert "tendermint_tpu_verifyhub_queue_latency_seconds_count 1" in out
        finally:
            vh.release_hub()


def test_callsite_lint_clean():
    """scripts/check_verify_callsites.py is the tier-1 guard against new
    direct verify_signature call sites bypassing the hub."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_verify_callsites.py")],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


class TestLiveConsensusCacheHits:
    @pytest.mark.asyncio
    async def test_four_node_gossip_duplicates_served_from_cache(self):
        """Acceptance: in a 4-validator live-consensus net every vote is
        signed once but verified by all four nodes — the shared hub
        answers the three duplicate verifications from its cache, so the
        cache-hit metric must be > 0 (and far fewer sigs reach the
        device than verifications requested)."""
        from tests.test_node import NodeNet

        net = NodeNet(4)
        await net.start()
        try:
            await net.wait_for_height(2, timeout=60)
            hub = vh.running_hub()
            assert hub is not None, "nodes did not acquire the verify hub"
            s = hub.stats()
            assert s["cache_hits"] > 0, s
            assert s["dispatched_sigs"] > 0, s
            # duplicates (cache + in-flight joins) never reached a verifier
            requests = s["submitted"] + s["cache_hits"] + s["coalesced"]
            assert requests > s["dispatched_sigs"]
        finally:
            await net.stop()
        assert vh.running_hub() is None  # last node released the hub
