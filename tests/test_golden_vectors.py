"""Golden-vector freeze of the consensus-critical byte formats.

The bespoke deterministic codec (libs/protoenc.py + types/canonical.py)
defines sign-bytes and hashes — consensus-critical bytes with no protobuf
schema pinning them. These vectors freeze the CURRENT wire format: any
refactor that silently reorders a dataclass field or changes a tag now
fails here instead of hard-forking a running network (the reference
freezes the same surface with generated protobuf + types/canonical.go:56;
its own golden tests live in types/*_test.go).

If a vector changes INTENTIONALLY (a deliberate wire format revision),
update it here in the same commit and call the break out loudly.
"""

from tendermint_tpu.crypto.hashes import sha256
from tendermint_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
)
from tendermint_tpu.types.canonical import proposal_sign_bytes, vote_sign_bytes
from tendermint_tpu.types.keys import SignedMsgType

BID = BlockID(bytes(range(32)), PartSetHeader(3, bytes(range(32, 64))))


class TestSignBytesVectors:
    def test_precommit_sign_bytes(self):
        sb = vote_sign_bytes(
            "golden-chain",
            SignedMsgType.PRECOMMIT,
            12345,
            2,
            BID,
            1_700_000_000_123_456_789,
        )
        assert sb.hex() == (
            "79080211393000000000000019020000000000000022480a200001020304050607"
            "08090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f1224080312202021"
            "22232425262728292a2b2c2d2e2f303132333435363738393a3b3c3d3e3f2a0b"
            "0880e2cfaa0610959aef3a320c676f6c64656e2d636861696e"
        )

    def test_nil_prevote_sign_bytes(self):
        sb = vote_sign_bytes("golden-chain", SignedMsgType.PREVOTE, 1, 0, BlockID(), 0)
        assert sb.hex() == "1b08011101000000000000002a00320c676f6c64656e2d636861696e"

    def test_sign_bytes_sensitivity(self):
        """Every field must perturb the bytes (catches a dropped field)."""
        base = vote_sign_bytes(
            "c", SignedMsgType.PRECOMMIT, 5, 1, BID, 1000
        )
        variants = [
            vote_sign_bytes("d", SignedMsgType.PRECOMMIT, 5, 1, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PREVOTE, 5, 1, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 6, 1, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 5, 2, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 5, 1, BlockID(), 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 5, 1, BID, 1001),
        ]
        assert len({base, *variants}) == 7

    def test_proposal_sign_bytes_stable(self):
        sb = proposal_sign_bytes("golden-chain", 9, 1, -1, BID, 777)
        # structural freeze: length-prefixed, chain id trailing
        assert sb.endswith(b"golden-chain")
        assert sb == proposal_sign_bytes("golden-chain", 9, 1, -1, BID, 777)


class TestHashVectors:
    def test_header_hash(self):
        hdr = Header(
            chain_id="golden-chain",
            height=7,
            time_ns=1_700_000_000_000_000_001,
            last_block_id=BID,
            last_commit_hash=sha256(b"lc"),
            data_hash=sha256(b"d"),
            validators_hash=sha256(b"v"),
            next_validators_hash=sha256(b"nv"),
            consensus_hash=sha256(b"c"),
            app_hash=sha256(b"a"),
            last_results_hash=sha256(b"r"),
            evidence_hash=b"",
            proposer_address=b"\x11" * 20,
        )
        assert hdr.hash().hex() == (
            "5b763475895b7f93e69f7a603ab2e4cc9fe6ce521370cf9d7d792cb3e1578809"
        )

    def test_commit_encoding(self):
        commit = Commit(
            7,
            1,
            BID,
            (
                CommitSig.for_block(
                    b"\x22" * 20, 1_700_000_000_000_000_002, b"\x33" * 64
                ),
                CommitSig.absent(),
            ),
        )
        enc = commit.encode()
        assert len(enc) == 200
        assert sha256(enc).hex() == (
            "d6d0c69441fb46a0b7377e81d0bcc81c425c8cf4af6202c391eec6089ee3a0c5"
        )
        assert Commit.decode(enc).encode() == enc
