"""Golden-vector freeze of the consensus-critical byte formats.

The bespoke deterministic codec (libs/protoenc.py + types/canonical.py)
defines sign-bytes and hashes — consensus-critical bytes with no protobuf
schema pinning them. These vectors freeze the CURRENT wire format: any
refactor that silently reorders a dataclass field or changes a tag now
fails here instead of hard-forking a running network (the reference
freezes the same surface with generated protobuf + types/canonical.go:56;
its own golden tests live in types/*_test.go).

If a vector changes INTENTIONALLY (a deliberate wire format revision),
update it here in the same commit and call the break out loudly.
"""

from tendermint_tpu.crypto.hashes import sha256
from tendermint_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
)
from tendermint_tpu.types.canonical import proposal_sign_bytes, vote_sign_bytes
from tendermint_tpu.types.keys import SignedMsgType

BID = BlockID(bytes(range(32)), PartSetHeader(3, bytes(range(32, 64))))


class TestSignBytesVectors:
    def test_precommit_sign_bytes(self):
        sb = vote_sign_bytes(
            "golden-chain",
            SignedMsgType.PRECOMMIT,
            12345,
            2,
            BID,
            1_700_000_000_123_456_789,
        )
        assert sb.hex() == (
            "79080211393000000000000019020000000000000022480a200001020304050607"
            "08090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f1224080312202021"
            "22232425262728292a2b2c2d2e2f303132333435363738393a3b3c3d3e3f2a0b"
            "0880e2cfaa0610959aef3a320c676f6c64656e2d636861696e"
        )

    def test_nil_prevote_sign_bytes(self):
        sb = vote_sign_bytes("golden-chain", SignedMsgType.PREVOTE, 1, 0, BlockID(), 0)
        assert sb.hex() == "1b08011101000000000000002a00320c676f6c64656e2d636861696e"

    def test_sign_bytes_sensitivity(self):
        """Every field must perturb the bytes (catches a dropped field)."""
        base = vote_sign_bytes(
            "c", SignedMsgType.PRECOMMIT, 5, 1, BID, 1000
        )
        variants = [
            vote_sign_bytes("d", SignedMsgType.PRECOMMIT, 5, 1, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PREVOTE, 5, 1, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 6, 1, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 5, 2, BID, 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 5, 1, BlockID(), 1000),
            vote_sign_bytes("c", SignedMsgType.PRECOMMIT, 5, 1, BID, 1001),
        ]
        assert len({base, *variants}) == 7

    def test_proposal_sign_bytes_stable(self):
        sb = proposal_sign_bytes("golden-chain", 9, 1, -1, BID, 777)
        # structural freeze: length-prefixed, chain id trailing
        assert sb.endswith(b"golden-chain")
        assert sb == proposal_sign_bytes("golden-chain", 9, 1, -1, BID, 777)


class TestHashVectors:
    def test_header_hash(self):
        hdr = Header(
            chain_id="golden-chain",
            height=7,
            time_ns=1_700_000_000_000_000_001,
            last_block_id=BID,
            last_commit_hash=sha256(b"lc"),
            data_hash=sha256(b"d"),
            validators_hash=sha256(b"v"),
            next_validators_hash=sha256(b"nv"),
            consensus_hash=sha256(b"c"),
            app_hash=sha256(b"a"),
            last_results_hash=sha256(b"r"),
            evidence_hash=b"",
            proposer_address=b"\x11" * 20,
        )
        # r5: INTENTIONAL break — header hashing moved to the reference's
        # cdcEncode form (proto-wrapped fields) and is now byte-exact with
        # the reference implementation, proven against its MBT vectors
        # (tests/test_light_mbt.py + test_header_hash_reference_vector)
        assert hdr.hash().hex() == (
            "5bf1504b6695e89cae69290ecc174a8c30c53e0cc6a3f369208600653845f25a"
        )

    def test_header_hash_reference_vector(self):
        """Byte-exact against a header hashed by the REFERENCE Go
        implementation (from its MBT trace data:
        /root/reference/light/mbt/json/MC4_4_faulty_TestFailure.json,
        initial header — commit.block_id.hash is Go's Header.Hash())."""
        hdr = Header(
            chain_id="test-chain",
            height=1,
            time_ns=1_000_000_000,
            last_block_id=BlockID(),
            validators_hash=bytes.fromhex(
                "5A69ACB73672274A2C020C7FAE539B2086D30F3B7E5B168A8031A21931FCA07D"
            ),
            next_validators_hash=bytes.fromhex(
                "C8F8530F1A2E69409F2E0B4F86BB568695BC9790BA77EAC1505600D5506E22DA"
            ),
            consensus_hash=bytes.fromhex(
                "5A69ACB73672274A2C020C7FAE539B2086D30F3B7E5B168A8031A21931FCA07D"
            ),
            proposer_address=bytes.fromhex(
                "0616A636E7D0579A632EC37ED3C3F2B7E8522A0A"
            ),
            version=11,
        )
        assert hdr.hash().hex().upper() == (
            "658DEEC010B33EDB1977FA7B38087A8C547D65272F6A63854959E517AAD20597"
        )

    def test_validator_set_hash_reference_vector(self):
        """Byte-exact against a validator-set hash produced by the
        reference (same MBT trace: next_validator_set of the initial
        state hashes to the header's next_validators_hash)."""
        import base64

        from tendermint_tpu.crypto.ed25519 import Ed25519PubKey
        from tendermint_tpu.types.validator_set import Validator, ValidatorSet

        pk = Ed25519PubKey(
            base64.b64decode("kwd8trZ8t5ASwgUbBEAnDq49nRRrrKvt2onhS4JSfQM=")
        )
        vs = ValidatorSet([Validator(pk, 50)])
        assert vs.hash().hex().upper() == (
            "C8F8530F1A2E69409F2E0B4F86BB568695BC9790BA77EAC1505600D5506E22DA"
        )

    def test_params_hash_frozen(self):
        from tendermint_tpu.types.params import ConsensusParams

        assert ConsensusParams().hash().hex() == (
            ConsensusParams().hash().hex()
        )
        # self-frozen vector: a params change that would hard-fork must
        # show up as a diff here
        assert ConsensusParams().hash().hex() == (
            "cdb662f2099157f885dba0f4bff72bedf16b0241e259a9b1aa23ec45ba9586b4"
        )

    def test_evidence_hash_frozen(self):
        from tendermint_tpu.types.evidence import DuplicateVoteEvidence
        from tendermint_tpu.types.keys import SignedMsgType
        from tendermint_tpu.types.vote import Vote

        def vote(bid):
            return Vote(
                type=SignedMsgType.PRECOMMIT,
                height=5,
                round=0,
                block_id=bid,
                timestamp_ns=1_700_000_000_000_000_000,
                validator_address=b"\x44" * 20,
                validator_index=2,
                signature=b"\x55" * 64,
            )

        ev = DuplicateVoteEvidence(
            vote_a=vote(BID),
            vote_b=vote(BlockID(sha256(b"other"), PartSetHeader(1, sha256(b"o")))),
            total_voting_power=100,
            validator_power=10,
            timestamp_ns=1_700_000_000_000_000_000,
        )
        assert ev.hash().hex() == (
            "1cd2029d1d5d25b629195087d073d1d5e54c2ddb64b6ff6d2950740563102a15"
        )

    def test_commit_encoding(self):
        commit = Commit(
            7,
            1,
            BID,
            (
                CommitSig.for_block(
                    b"\x22" * 20, 1_700_000_000_000_000_002, b"\x33" * 64
                ),
                CommitSig.absent(),
            ),
        )
        enc = commit.encode()
        assert len(enc) == 200
        assert sha256(enc).hex() == (
            "d6d0c69441fb46a0b7377e81d0bcc81c425c8cf4af6202c391eec6089ee3a0c5"
        )
        assert Commit.decode(enc).encode() == enc
