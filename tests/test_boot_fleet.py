"""BootFleet — mass statesync snapshot serving (statesync/fleet.py) and
hub-verified backfill, plus the mass-onboarding scenario
(consensus/scenarios.run_boot_wave).

Tier-1 carries: the BootD serving discipline (shared chunk cache
amortization, same-chunk coalescing, busy-shed as explicit
backpressure — never a queue), manifest commit/prune hygiene, the
backfill verification semantics (per-sig batches and one-pairing
aggregate commits on the VerifyHub backfill lane, tampered commits
rejected with InvalidCommitError), the bootd metrics fold and boot.*
trace spans, the in-process join wave (N joiners amortized onto one
donor store read per chunk), and the live RouterNet wave with its two
fault variants: donor crash mid-chunk (re-fetch from survivors) and
poisoned donors (bounded failure, never a wedge). The 150-validator
wave soak is slow-marked."""

import asyncio
import dataclasses
import subprocess
import sys
import time

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.config import BootDConfig
from tendermint_tpu.consensus import scenarios as sc
from tendermint_tpu.libs import trace
from tendermint_tpu.light.types import LightBlock, SignedHeader
from tendermint_tpu.statesync.fleet import (
    BootD,
    BootDBusyError,
    verify_backfill_batch,
)
from tendermint_tpu.testing import (
    make_light_chain,
    make_validator_set,
    statesync_fleet_scenario,
)
from tendermint_tpu.types.block import aggregate_commit
from tendermint_tpu.types.validation import InvalidCommitError

CHAIN = "boot-fleet-chain"


# ---------------------------------------------------------------------------
# fixtures: a gateable snapshot store stub (the donor's app connection)


class _SnapshotConn:
    """The snapshot-connection surface BootD talks to, with a gate so a
    test can hold a store read in flight (the coalesce/shed fixture)."""

    def __init__(self, snapshots=(), chunks=None):
        self.snapshots = tuple(snapshots)
        self.chunks = dict(chunks or {})
        self.gate = asyncio.Event()
        self.gate.set()
        self.loads = 0

    async def list_snapshots(self):
        return abci.ResponseListSnapshots(self.snapshots)

    async def load_snapshot_chunk(self, req):
        await self.gate.wait()
        self.loads += 1
        chunk = self.chunks.get((req.height, req.format, req.chunk), b"")
        return abci.ResponseLoadSnapshotChunk(chunk)


class _Conns:
    def __init__(self, snapshot):
        self.snapshot = snapshot


def make_bootd(**cfg):
    snap = abci.Snapshot(height=10, format=1, chunks=3, hash=b"\x01" * 32)
    conn = _SnapshotConn(
        snapshots=(snap,),
        chunks={(10, 1, i): bytes([i]) * 64 for i in range(3)},
    )
    d = BootD(_Conns(conn), config=BootDConfig(refresh_s=0.05, **cfg))
    return d, conn


def ed_blocks(n=6, n_vals=4):
    vals, keys = make_validator_set(n_vals)
    return make_light_chain(n, vals, keys, CHAIN), vals


def bls_blocks(n=4, n_vals=4):
    vals, keys = make_validator_set(n_vals, key_types=("bls12381",))
    chain = make_light_chain(n, vals, keys, CHAIN)
    folded = [
        LightBlock(
            SignedHeader(
                lb.header, aggregate_commit(lb.signed_header.commit, vals)
            ),
            lb.validators,
        )
        for lb in chain
    ]
    return folded, vals


# ---------------------------------------------------------------------------
# the serving discipline: cache, coalescing, busy-shed, manifest hygiene


class TestBootDServing:
    @pytest.mark.asyncio
    async def test_shared_cache_amortizes_store_reads(self):
        d, conn = make_bootd()
        await d.start()
        try:
            a = await d.serve_chunk(10, 1, 0)
            b = await d.serve_chunk(10, 1, 0)
            c = await d.serve_chunk(10, 1, 0)
        finally:
            await d.stop()
        assert a == b == c == b"\x00" * 64
        assert conn.loads == 1
        assert d.stats["store_reads"] == 1
        assert d.stats["cache_hits"] == 2
        assert d.stats["chunks_served"] == 3
        assert d.stats["chunk_bytes"] == 3 * 64
        assert d.cache_hit_rate() == pytest.approx(2 / 3)

    @pytest.mark.asyncio
    async def test_concurrent_same_chunk_loads_coalesce(self):
        """N concurrent first-touch requests for the SAME chunk make
        ONE store read — the join-wave amortization, one level up."""
        d, conn = make_bootd()
        await d.start()
        try:
            conn.gate.clear()
            tasks = [
                asyncio.ensure_future(d.serve_chunk(10, 1, 1))
                for _ in range(4)
            ]
            await asyncio.sleep(0.05)  # all four arrive while loading
            conn.gate.set()
            out = await asyncio.gather(*tasks)
        finally:
            await d.stop()
        assert all(c == bytes([1]) * 64 for c in out)
        assert conn.loads == 1
        assert d.stats["coalesced"] == 3
        assert d.stats["cache_misses"] == 1
        assert d.stats["sheds"] == 0

    @pytest.mark.asyncio
    async def test_busy_shed_beyond_max_sessions(self):
        """The ingress backpressure contract: a DISTINCT cold chunk
        beyond max_sessions is rejected with busy, never queued — while
        warm chunks keep serving from the cache and same-chunk arrivals
        keep coalescing."""
        d, conn = make_bootd(max_sessions=1)
        await d.start()
        try:
            warm = await d.serve_chunk(10, 1, 0)  # fills the cache
            conn.gate.clear()
            t1 = asyncio.ensure_future(d.serve_chunk(10, 1, 1))
            await asyncio.sleep(0.05)  # t1 occupies the only session
            with pytest.raises(BootDBusyError, match="busy"):
                await d.serve_chunk(10, 1, 2)
            assert d.stats["sheds"] == 1
            # cache hits are not sessions and never shed
            assert await d.serve_chunk(10, 1, 0) == warm
            # a same-chunk arrival coalesces instead of shedding
            t2 = asyncio.ensure_future(d.serve_chunk(10, 1, 1))
            await asyncio.sleep(0.05)
            conn.gate.set()
            assert (await t1) == (await t2) == bytes([1]) * 64
            assert d.stats["coalesced"] == 1
            assert d.stats["sheds"] == 1
        finally:
            await d.stop()

    @pytest.mark.asyncio
    async def test_manifest_prunes_dead_snapshots_and_their_chunks(self):
        d, conn = make_bootd()
        await d.start()
        try:
            assert len(await d.serve_snapshots()) == 1
            await d.serve_chunk(10, 1, 0)
            await d.serve_chunk(10, 1, 1)
            # the app drops snapshot 10 and takes 20
            conn.snapshots = (
                abci.Snapshot(height=20, format=1, chunks=1, hash=b"\x02" * 32),
            )
            manifest = await d.refresh_manifest()
            assert [s.height for s in manifest] == [20]
            assert d._chunks == {}  # dead snapshot's bytes went with it
            assert d.stats["pruned_chunks"] == 2
        finally:
            await d.stop()

    @pytest.mark.asyncio
    async def test_snapshot_interval_filters_served_set(self):
        d, conn = make_bootd(snapshot_interval=20)
        conn.snapshots += (
            abci.Snapshot(height=20, format=1, chunks=1, hash=b"\x02" * 32),
        )
        await d.start()
        try:
            manifest = await d.serve_snapshots()
        finally:
            await d.stop()
        assert [s.height for s in manifest] == [20]  # 10 % 20 != 0

    @pytest.mark.asyncio
    async def test_missing_chunk_is_empty_not_an_error(self):
        d, _conn = make_bootd()
        await d.start()
        try:
            assert await d.serve_chunk(99, 1, 0) == b""
        finally:
            await d.stop()


# ---------------------------------------------------------------------------
# backfill verification: the hub backfill lane + the aggregate trade


class TestBackfillVerify:
    @pytest.mark.asyncio
    async def test_per_sig_batch_counts_every_signature(self):
        blocks, vals = ed_blocks(n=5, n_vals=4)
        d, _ = make_bootd()
        n_sigs = await verify_backfill_batch(CHAIN, blocks, bootd=d)
        assert n_sigs == 5 * 4
        assert d.stats["backfill_heights"] == 5
        assert d.stats["backfill_sigs"] == 20
        assert d.stats["backfill_agg_heights"] == 0
        assert d.stats["backfill_batches"] == 1

    @pytest.mark.asyncio
    async def test_aggregate_commit_verifies_as_one_pairing_per_height(self):
        blocks, _vals = bls_blocks(n=3, n_vals=4)
        assert all(lb.signed_header.commit.is_aggregate() for lb in blocks)
        d, _ = make_bootd()
        n_sigs = await verify_backfill_batch(CHAIN, blocks, bootd=d)
        assert n_sigs == 3 * 4  # signatures COVERED, not pairings done
        assert d.stats["backfill_agg_heights"] == 3

    @pytest.mark.asyncio
    async def test_tampered_backfill_commit_rejected(self):
        """A forged-but-hash-linked header can't ride backfill: the
        batch dies on signature verification with the failing height
        attributed, and nothing is counted as verified."""
        blocks, _vals = ed_blocks(n=4, n_vals=4)
        sigs = list(blocks[2].signed_header.commit.signatures)
        bad = sigs[0].signature[:-1] + bytes([sigs[0].signature[-1] ^ 0x01])
        sigs[0] = dataclasses.replace(sigs[0], signature=bad)
        commit = dataclasses.replace(
            blocks[2].signed_header.commit, signatures=tuple(sigs)
        )
        blocks[2] = LightBlock(
            SignedHeader(blocks[2].header, commit), blocks[2].validators
        )
        d, _ = make_bootd()
        with pytest.raises(InvalidCommitError):
            await verify_backfill_batch(CHAIN, blocks, bootd=d)
        assert d.stats["backfill_heights"] == 0
        assert d.stats["backfill_batches"] == 0

    @pytest.mark.asyncio
    async def test_empty_batch_is_a_noop(self):
        assert await verify_backfill_batch(CHAIN, []) == 0


# ---------------------------------------------------------------------------
# observability: the metrics fold + boot.* trace spans


class TestBootDObservability:
    @pytest.mark.asyncio
    async def test_bootd_stats_fold_into_node_metrics(self):
        from tendermint_tpu.libs.metrics import NodeMetrics

        d, _conn = make_bootd()
        await d.start()
        try:
            await d.serve_chunk(10, 1, 0)
            await d.serve_chunk(10, 1, 0)
            d.record_synced(0.7)
            rendered = NodeMetrics().render()
        finally:
            await d.stop()
        assert "tendermint_tpu_bootd_chunks_served 2" in rendered
        assert "tendermint_tpu_bootd_cache_hits 1" in rendered
        assert "tendermint_tpu_bootd_store_reads 1" in rendered
        assert "tendermint_tpu_bootd_synced 1" in rendered
        assert "tendermint_tpu_bootd_cache_hit_rate 0.5" in rendered
        assert "bootd_time_to_synced_seconds_count 1" in rendered
        assert 'backfill_by_scheme{scheme="per-sig"}' in rendered
        assert 'backfill_by_scheme{scheme="bls-aggregate"}' in rendered

    @pytest.mark.asyncio
    async def test_serve_and_backfill_emit_boot_spans(self):
        old = trace.RECORDER.enabled
        trace.RECORDER.enabled = True
        trace.RECORDER.clear()
        try:
            d, _conn = make_bootd()
            await d.start()
            try:
                await d.serve_chunk(10, 1, 0)
                await d.serve_chunk(10, 1, 0)
                blocks, _ = ed_blocks(n=2, n_vals=4)
                await verify_backfill_batch(CHAIN, blocks, bootd=d)
            finally:
                await d.stop()
        finally:
            trace.RECORDER.enabled = old
        spans = trace.RECORDER.dump(subsystem="boot")
        outcomes = [
            s["attrs"].get("outcome")
            for s in spans
            if s["name"] == "serve_chunk"
        ]
        assert outcomes == ["served", "cache_hit"]
        bf = [s for s in spans if s["name"] == "backfill_verify"]
        assert len(bf) == 1
        assert bf[0]["attrs"]["outcome"] == "verified"
        assert bf[0]["attrs"]["sigs"] == 8


# ---------------------------------------------------------------------------
# the in-process join wave: N joiners, one donor, real wire frames


class TestJoinWave:
    @pytest.mark.asyncio
    async def test_wave_amortizes_chunks_and_verifies_backfill(self):
        """Three concurrent cold joiners against one donor: every chunk
        is read from the donor's store ONCE (cache + coalescing), every
        joiner restores and backfill-verifies, inside a wall-time
        budget."""
        t0 = time.perf_counter()
        r = await statesync_fleet_scenario(
            24, 4, n_joiners=3, backfill_blocks=6, sync_timeout_s=60.0
        )
        wall = time.perf_counter() - t0
        assert r["joined"] == 3, r["join_errors"]
        assert r["join_errors"] == []
        assert all(h > 0 for h in r["headers_held"])
        st = r["server_stats"]
        # the amortization claim: chunks served > store round-trips
        assert st["chunks_served"] >= 3
        assert st["store_reads"] < st["chunks_served"]
        assert st["cache_hits"] + st["coalesced"] >= 2
        # backfill runs joiner-side, through the hub backfill lane
        bf = r["joiner_backfill"]
        assert bf["backfill_batches"] >= 3
        assert bf["backfill_sigs"] > 0
        assert all(t < 60.0 for t in r["time_to_synced_s"])
        assert wall < 90.0, f"join wave took {wall:.1f}s"

    @pytest.mark.asyncio
    async def test_wave_with_single_session_donor_still_converges(self):
        """max_sessions=1 and no chunk cache: the donor sheds/coalesces
        instead of queueing, and every joiner still converges (busy is
        backpressure the joining side absorbs, not failure)."""
        r = await statesync_fleet_scenario(
            12,
            4,
            n_joiners=3,
            backfill_blocks=4,
            bootd_config=BootDConfig(max_sessions=1, chunk_cache=0),
            sync_timeout_s=60.0,
        )
        assert r["joined"] == 3, r["join_errors"]
        st = r["server_stats"]
        assert st["chunk_requests"] >= st["chunks_served"]


# ---------------------------------------------------------------------------
# the live scenario: a wave joins a RouterNet committee


class TestBootWaveScenario:
    @pytest.mark.asyncio
    async def test_boot_wave_over_routernet(self):
        r = await sc.run_boot_wave(
            n_vals=4, n_joiners=2, seed=3, timeout_s=120.0, join_timeout_s=90.0
        )
        assert r["outcome"] == "ok", r
        assert r["honest_chain_ok"]
        assert r["joined"] == 2 and r["join_errors"] == []
        # restored at least to the served snapshot (kvstore snapshots
        # land on multiples of 10; consensus catch-up closes the rest
        # after the wave is scored)
        assert all(h >= 10 for h in r["joiner_heights"]), r["joiner_heights"]
        assert r["chunks_served"] > 0
        assert r["backfill_sigs"] > 0  # backfill rode the hub lane
        assert all(t < 90.0 for t in r["time_to_synced_s"])
        assert r["elapsed_s"] < 110.0, r["elapsed_s"]

    @pytest.mark.asyncio
    async def test_boot_wave_survives_donor_crash(self):
        """A donor dies mid-wave — under link chaos: joiners re-fetch
        from survivors (chunk timeout → breaker → rotation) and the 3/4
        committee keeps committing. Chaos lives on the fast 4-val wave
        because per-envelope shaping is cheap here; the 150-val soak
        runs clean (see TestBootWave150)."""
        r = await sc.run_boot_wave(
            n_vals=4,
            n_joiners=2,
            seed=5,
            donor_crash=True,
            chaos_cfg=sc.ChaosConfig(seed=5, delay_ms=1.0, drop_rate=0.01),
            timeout_s=150.0,
            join_timeout_s=120.0,
        )
        assert r["outcome"] == "ok", r
        assert r["crashed"] == [3]
        assert r["honest_chain_ok"]
        assert r["joined"] == 2, r["join_errors"]

    @pytest.mark.asyncio
    async def test_boot_wave_poisoned_donor_never_wedges_joiner(self):
        """One Byzantine donor serves corrupted chunk bytes: the
        restore's hash check rejects the state and bans the server; the
        wave still lands on the honest chain."""
        r = await sc.run_boot_wave(
            n_vals=4,
            n_joiners=2,
            seed=7,
            poison_donors=(1,),
            timeout_s=150.0,
            join_timeout_s=120.0,
        )
        assert r["outcome"] == "ok", r
        assert r["honest_chain_ok"]
        assert r["joined"] == 2, r["join_errors"]

    @pytest.mark.asyncio
    async def test_all_donors_poisoned_fails_bounded_not_wedged(self):
        """Every donor Byzantine: the joiner deterministically rejects
        every candidate (bounded same-snapshot retries), costs each
        server a ban, and FAILS with SyncAborted well inside the join
        timeout — a wedge, not a failure, is the defect."""
        t0 = time.perf_counter()
        r = await sc.run_boot_wave(
            n_vals=4,
            n_joiners=1,
            seed=9,
            poison_donors=(0, 1, 2, 3),
            timeout_s=120.0,
            join_timeout_s=90.0,
        )
        wall = time.perf_counter() - t0
        assert r["joined"] == 0
        assert r["join_errors"], r
        assert any("SyncAborted" in e for e in r["join_errors"]), r["join_errors"]
        assert r["poisoned_rejects"] > 0
        assert wall < 110.0, f"poisoned wave took {wall:.1f}s (wedged?)"


# ---------------------------------------------------------------------------
# containment: production wiring never reaches the poisoned donor app


class TestContainment:
    def test_production_import_graph_never_reaches_poisoned_donor(self):
        code = (
            "import sys\n"
            "import tendermint_tpu.node, tendermint_tpu.cli\n"
            "import tendermint_tpu.statesync.fleet\n"
            "import tendermint_tpu.statesync.reactor\n"
            "bad = [m for m in sys.modules if 'byzantine' in m]\n"
            "assert not bad, f'production wiring reaches {bad}'\n"
            "print('CONTAINED')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "CONTAINED" in out.stdout


@pytest.mark.slow
class TestBootWave150:
    @pytest.mark.asyncio
    async def test_boot_wave_150_validator_soak(self):
        """The committee-scale soak: a wave of cold nodes joins a live
        150-validator committee; the audit asserts the honest app-hash
        chain and the backfill lane carries the signature load."""
        # committee-scale feasibility on one core: heights at 150 vals
        # take ~100s each even unshaped (the light-attack soak's rate),
        # and per-envelope chaos shaping multiplies that several-fold
        # (the taxonomy soak needs 1200s for height 2) — so this soak
        # runs clean like the light-attack one, shrinks the snapshot
        # cadence, anchors at height 2, and borrows the taxonomy soak's
        # gossip pacing (degree 6, 0.4 s); the chaos-shaped wave is
        # covered at 4 vals where shaping is cheap
        r = await sc.run_boot_wave(
            n_vals=150,
            n_joiners=2,
            seed=11,
            snapshot_height=2,
            snapshot_interval=2,
            degree=6,
            gossip_sleep=0.4,
            timeout_s=1500.0,
            join_timeout_s=900.0,
        )
        assert r["outcome"] == "ok", (
            r.get("error"), r.get("audit"), r.get("heights"), r.get("elapsed_s"),
        )
        assert r["honest_chain_ok"]
        assert r["joined"] == 2, r["join_errors"]
        assert r["backfill_sigs"] > 0
