"""Tools: signer acceptance harness (reference
tools/tm-signer-harness/internal/test_harness_test.go) and abci-cli
(reference abci/cmd/abci-cli)."""

import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu.privval import FilePV, PrivValidator
from tendermint_tpu.privval_remote import GrpcSignerServer, ThreadedSignerServer
from tendermint_tpu.tools import signer_harness as sh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def file_pv(tmp_path):
    return FilePV.generate(str(tmp_path / "key.json"), str(tmp_path / "state.json"))


def test_signer_harness_socket_pass(file_pv):
    srv = ThreadedSignerServer(file_pv)
    port = srv.start()
    try:
        rc = sh.run_harness(
            f"tcp://127.0.0.1:{port}", expected_pub_key=file_pv.get_pub_key()
        )
        assert rc == sh.OK
    finally:
        srv.stop()


def test_signer_harness_grpc_pass_and_identity_mismatch(file_pv, tmp_path):
    other = FilePV.generate(str(tmp_path / "k2.json"), str(tmp_path / "s2.json"))
    srv = GrpcSignerServer(file_pv)
    port = srv.start()
    try:
        assert (
            sh.run_harness(
                f"grpc://127.0.0.1:{port}", expected_pub_key=file_pv.get_pub_key()
            )
            == sh.OK
        )
        assert (
            sh.run_harness(
                f"grpc://127.0.0.1:{port}", expected_pub_key=other.get_pub_key()
            )
            == sh.ERR_TEST_PUBLIC_KEY_FAILED
        )
    finally:
        srv.stop()


class _EquivocatingPV(PrivValidator):
    """Signs anything — the broken signer the harness exists to catch."""

    def __init__(self, inner):
        self.inner = inner

    def get_pub_key(self):
        return self.inner.get_pub_key()

    def sign_vote(self, chain_id, vote):
        sig = self.inner.priv_key.sign(vote.sign_bytes(chain_id))
        from dataclasses import replace

        return replace(vote, signature=sig)

    def sign_proposal(self, chain_id, proposal):
        sig = self.inner.priv_key.sign(proposal.sign_bytes(chain_id))
        from dataclasses import replace

        return replace(proposal, signature=sig)


def test_signer_harness_catches_double_signer(file_pv):
    srv = ThreadedSignerServer(_EquivocatingPV(file_pv))
    port = srv.start()
    try:
        rc = sh.run_harness(f"tcp://127.0.0.1:{port}")
        assert rc == sh.ERR_DOUBLE_SIGN_NOT_REFUSED
    finally:
        srv.stop()


# -- abci-cli ---------------------------------------------------------------


def _wait_listening(proc, timeout=30.0):
    t0 = time.time()
    line = proc.stdout.readline()
    assert "listening" in line, line
    assert time.time() - t0 < timeout


@pytest.mark.parametrize("scheme", ["tcp", "grpc"])
def test_abci_cli_conformance(scheme, unused_tcp_port_factory=None):
    port = 37000 + (os.getpid() + (0 if scheme == "tcp" else 1)) % 2000
    addr = f"{scheme}://127.0.0.1:{port}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    server = subprocess.Popen(
        [sys.executable, "-m", "tendermint_tpu.abci.cli", "--address", addr, "kvstore"],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    try:
        _wait_listening(server)
        out = subprocess.run(
            [sys.executable, "-m", "tendermint_tpu.abci.cli", "--address", addr, "test"],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert '"failures": 0' in out.stdout
        # proven query roundtrip over the wire (ProofOp codec)
        out = subprocess.run(
            [
                sys.executable, "-m", "tendermint_tpu.abci.cli",
                "--address", addr, "query", "abci",
            ],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert out.returncode == 0 and "code: OK" in out.stdout, out.stdout + out.stderr
    finally:
        server.terminate()
        server.wait(timeout=10)
