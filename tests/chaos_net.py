"""Shared chaos-net test harness: a deterministic pre-built chain served
over REAL routers + chaos-wrapped in-memory transports to N block-syncing
nodes. Used by the seeded chaos matrix in test_p2p_robustness.py and the
crash-under-chaos tests in test_crash_recovery.py.

Why blocksync (not live consensus) for the reproducibility assertions:
the source chain is built with deterministic keys and timestamps, so the
protocol OUTPUT — the block hashes every node converges to — is
bit-identical across invocations regardless of fault timing; live
consensus embeds wall-clock vote timestamps in the hashes and cannot
make that promise."""

from __future__ import annotations

import asyncio

from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.blocksync import BLOCKSYNC_CHANNEL
from tendermint_tpu.blocksync import messages as bsm
from tendermint_tpu.blocksync.reactor import BlockSyncReactor
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork
from tendermint_tpu.p2p.memory import MemoryNetwork
from tendermint_tpu.p2p.testing import RouterShell
from tendermint_tpu.proxy import AppConns
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.state.state import state_from_genesis
from tendermint_tpu.state.store import StateStore
from tendermint_tpu.store.blockstore import BlockStore
from tendermint_tpu.store.db import MemDB


class ChaosNode(RouterShell):
    """One router + blocksync reactor over a chaos-wrapped transport.
    The p2p shell (key, transport, peer manager, router) is the shared
    RouterShell — the same wiring consensus/routernet.py uses — with the
    blocksync channel and stores layered on top."""

    def __init__(self, net: "ChaosSyncNet", index: int, chain_id: str):
        super().__init__(
            net.memory,
            index,
            chain_id,
            chaos=net.chaos,
            key_seed="chaos-sync",
            moniker=f"chaos{index}",
        )
        self.channel = self.router.open_channel(
            BLOCKSYNC_CHANNEL,
            name="blocksync",
            priority=5,
            encode=bsm.encode_message,
            decode=bsm.decode_message,
        )
        self.reactor: BlockSyncReactor | None = None
        self.app_conns: AppConns | None = None
        self.block_store: BlockStore | None = None
        self.state_store: StateStore | None = None


class ChaosSyncNet:
    """Node 0 serves `src_store`; nodes 1..n_sync block-sync it under the
    fault plan in `chaos_cfg`."""

    def __init__(
        self,
        genesis,
        src_store,
        src_state,
        chaos_cfg: ChaosConfig,
        *,
        n_sync: int = 3,
        window: int = 8,
    ):
        self.genesis = genesis
        self.src_store = src_store
        self.src_state = src_state
        self.memory = MemoryNetwork()
        self.chaos = ChaosNetwork(chaos_cfg)
        self.window = window
        self.nodes = [
            ChaosNode(self, i, genesis.chain_id) for i in range(n_sync + 1)
        ]

    @property
    def source(self) -> ChaosNode:
        return self.nodes[0]

    @property
    def sync_nodes(self) -> list[ChaosNode]:
        return self.nodes[1:]

    async def start(self) -> None:
        # source: serve-only reactor over the pre-built store
        src = self.source
        src.block_store = self.src_store
        src.reactor = BlockSyncReactor(
            self.src_state,
            None,  # block_exec unused when inactive
            self.src_store,
            src.channel,
            src.peer_manager.subscribe(),
            active=False,
        )
        for node in self.sync_nodes:
            await self._setup_sync_node(node)
        for node in self.nodes:
            await node.router.start()
            await node.reactor.start()
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1 :]:
                a.peer_manager.add_address(b.address())
        # the harness analog of node.py's _lag_monitor: a reactor that
        # declared caught-up while a taller peer exists (possible when the
        # source's status responses were delayed/dropped at startup) is
        # resumed — production nodes do exactly this switch-back
        self._lag_tasks = [
            asyncio.get_running_loop().create_task(self._lag_monitor(i))
            for i in range(1, len(self.nodes))
        ]

    async def _lag_monitor(self, idx: int) -> None:
        while True:
            await asyncio.sleep(0.5)
            node = self.nodes[idx]  # restart_sync_node swaps the object
            r = node.reactor
            if (
                r is not None
                and r.synced.is_set()
                and r.pool.max_peer_height() > node.block_store.height()
            ):
                r.resume(r.state)

    async def _setup_sync_node(self, node: ChaosNode) -> None:
        app = KVStoreApp()
        node.app_conns = AppConns.local(app)
        await node.app_conns.start()
        node.block_store = BlockStore(MemDB())
        node.state_store = StateStore(MemDB())
        state = await Handshaker(
            node.state_store,
            state_from_genesis(self.genesis),
            node.block_store,
            self.genesis,
        ).handshake(node.app_conns)
        node.state_store.save(state)
        block_exec = BlockExecutor(
            node.state_store,
            node.app_conns.consensus,
            block_store=node.block_store,
        )
        node.reactor = BlockSyncReactor(
            state,
            block_exec,
            node.block_store,
            node.channel,
            node.peer_manager.subscribe(),
            window=self.window,
            active=True,
        )

    async def restart_sync_node(self, node: ChaosNode) -> ChaosNode:
        """Crash-and-restart: stop the node's reactor+router, then bring a
        NEW reactor up on the SAME stores/app under a fresh router task set
        (the in-process analog of a process restart mid-sync)."""
        await node.reactor.stop()
        await node.router.stop()
        fresh = ChaosNode(self, node.index, self.genesis.chain_id)
        fresh.app_conns = node.app_conns
        fresh.block_store = node.block_store
        fresh.state_store = node.state_store
        state = node.state_store.load()
        block_exec = BlockExecutor(
            fresh.state_store,
            fresh.app_conns.consensus,
            block_store=fresh.block_store,
        )
        fresh.reactor = BlockSyncReactor(
            state,
            block_exec,
            fresh.block_store,
            fresh.channel,
            fresh.peer_manager.subscribe(),
            window=self.window,
            active=True,
        )
        self.nodes[self.nodes.index(node)] = fresh
        await fresh.router.start()
        await fresh.reactor.start()
        for other in self.nodes:
            if other is not fresh:
                fresh.peer_manager.add_address(other.address())
                other.peer_manager.add_address(fresh.address())
        return fresh

    async def wait_synced(self, target: int, timeout: float = 90.0) -> None:
        async def one(node: ChaosNode):
            while node.block_store.height() < target:
                await asyncio.sleep(0.05)

        await asyncio.wait_for(
            asyncio.gather(*(one(n) for n in self.sync_nodes)), timeout
        )

    def hashes_at(self, target: int) -> list[bytes]:
        """Block hash at `target` per sync node (the reproducibility
        fingerprint)."""
        return [
            n.block_store.load_block(target).hash() for n in self.sync_nodes
        ]

    async def stop(self) -> None:
        for t in getattr(self, "_lag_tasks", []):
            t.cancel()
        for node in self.nodes:
            if node.reactor is not None:
                await node.reactor.stop()
            await node.router.stop()
            if node.app_conns is not None:
                await node.app_conns.stop()


async def run_chaos_sync(
    chaos_cfg: ChaosConfig,
    *,
    n_blocks: int = 16,
    n_sync: int = 3,
    window: int = 8,
    partition_cycle: bool = False,
    partition_at: float = 0.3,
    partition_for: float = 1.2,
    timeout: float = 90.0,
):
    """Build a deterministic chain, sync it through the chaos net, return
    (target_height, per-node hashes at target, chaos fault counters).

    With partition_cycle=True, one partition-and-heal cycle is injected
    mid-sync: {source, node1} | {node2, node3, ...} for `partition_for`
    seconds starting `partition_at` seconds after the net comes up."""
    from tendermint_tpu.testing import build_kvstore_chain

    bstore, sstore, conns, genesis, _keys = await build_kvstore_chain(
        n_blocks, 3, chain_id="chaos-chain"
    )
    src_state = sstore.load()
    net = ChaosSyncNet(
        genesis, bstore, src_state, chaos_cfg, n_sync=n_sync, window=window
    )
    target = n_blocks - 1  # the tip needs its successor's commit to apply
    await net.start()
    try:
        if partition_cycle:
            ids = [n.node_id for n in net.nodes]
            # let some progress happen, then split the net and heal it
            await asyncio.sleep(partition_at)
            net.chaos.partition(set(ids[:2]), set(ids[2:]))
            await asyncio.sleep(partition_for)
            net.chaos.heal()
        await net.wait_synced(target, timeout)
        hashes = net.hashes_at(target)
    finally:
        await net.stop()
        await conns.stop()
    return target, hashes, dict(net.chaos.faults)
