"""Unit suite for the shared resilience primitives (libs/retry.py):
backoff growth + full-jitter bounds, deadline/attempt budgets, and the
circuit breaker's closed → open → half-open → closed/open lifecycle."""

import asyncio
import random

import pytest

from tendermint_tpu.libs.retry import (
    BackoffPolicy,
    CircuitBreaker,
    CircuitOpenError,
    RetriesExhaustedError,
    retry,
)


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


class TestBackoffPolicy:
    def test_full_jitter_bounds_and_growth(self):
        policy = BackoffPolicy(base=0.1, cap=5.0, multiplier=2.0)
        rng = random.Random(42)
        for attempt in range(12):
            ceiling = min(5.0, 0.1 * 2**attempt)
            for _ in range(50):
                s = policy.sleep_for(attempt, rng)
                assert 0.0 <= s <= ceiling, (attempt, s)

    def test_cap_applies(self):
        policy = BackoffPolicy(base=1.0, cap=2.0)
        rng = random.Random(0)
        assert all(policy.sleep_for(50, rng) <= 2.0 for _ in range(100))

    def test_seeded_sequence_is_deterministic(self):
        policy = BackoffPolicy(base=0.1, cap=5.0)
        a = [policy.sleep_for(i, random.Random(7)) for i in range(8)]
        b = [policy.sleep_for(i, random.Random(7)) for i in range(8)]
        assert a == b

    def test_sleeps_respects_max_attempts(self):
        policy = BackoffPolicy(base=0.01, max_attempts=4)
        assert len(list(policy.sleeps(random.Random(1)))) == 4


class TestRetry:
    @pytest.mark.asyncio
    async def test_succeeds_after_transients(self):
        calls = []

        async def fn():
            calls.append(1)
            if len(calls) < 3:
                raise ValueError("flake")
            return "ok"

        out = await retry(
            fn, BackoffPolicy(base=0.0001, max_attempts=10), rng=random.Random(0)
        )
        assert out == "ok" and len(calls) == 3

    @pytest.mark.asyncio
    async def test_attempt_budget_exhausted(self):
        async def fn():
            raise ValueError("always")

        with pytest.raises(RetriesExhaustedError) as ei:
            await retry(
                fn, BackoffPolicy(base=0.0001, max_attempts=3), rng=random.Random(0)
            )
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last, ValueError)

    @pytest.mark.asyncio
    async def test_unlisted_exception_propagates(self):
        async def fn():
            raise KeyError("bug, not flake")

        with pytest.raises(KeyError):
            await retry(
                fn,
                BackoffPolicy(base=0.0001, max_attempts=5),
                retry_on=(ValueError,),
            )

    @pytest.mark.asyncio
    async def test_give_up_on_wins_over_retry_on(self):
        class Transient(Exception):
            pass

        class Definitive(Transient):
            pass

        calls = []

        async def fn():
            calls.append(1)
            raise Definitive("not found")

        with pytest.raises(Definitive):
            await retry(
                fn,
                BackoffPolicy(base=0.0001, max_attempts=5),
                retry_on=(Transient,),
                give_up_on=(Definitive,),
            )
        assert len(calls) == 1  # no retries for a definitive answer

    @pytest.mark.asyncio
    async def test_deadline_enforced_without_sleeping(self):
        clock = FakeClock()

        async def fn():
            clock.advance(3.0)  # each attempt "costs" 3 virtual seconds
            raise ValueError("slow flake")

        with pytest.raises(RetriesExhaustedError) as ei:
            await retry(
                fn,
                BackoffPolicy(base=0.0001, deadline=5.0),
                rng=random.Random(0),
                clock=clock,
            )
        # attempt 1 at t=3, attempt 2 would start past the 5s budget
        assert ei.value.attempts == 2

    @pytest.mark.asyncio
    async def test_on_retry_callback_sees_errors(self):
        seen = []

        async def fn():
            if len(seen) < 2:
                raise ValueError(f"e{len(seen)}")
            return 1

        await retry(
            fn,
            BackoffPolicy(base=0.0001, max_attempts=10),
            rng=random.Random(0),
            on_retry=lambda attempt, err: seen.append((attempt, str(err))),
        )
        assert [a for a, _ in seen] == [1, 2]


class TestCircuitBreaker:
    def make(self, **kw) -> tuple[CircuitBreaker, FakeClock]:
        clock = FakeClock()
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("reset_timeout", 10.0)
        return CircuitBreaker(clock=clock, **kw), clock

    def test_opens_at_threshold(self):
        br, _ = self.make()
        for _ in range(2):
            br.record_failure()
            assert br.state == "closed" and br.allow()
        br.record_failure()
        assert br.state == "open" and not br.allow()
        assert br.opens == 1

    def test_success_resets_failure_count(self):
        br, _ = self.make()
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"

    def test_half_open_single_probe(self):
        br, clock = self.make()
        for _ in range(3):
            br.record_failure()
        clock.advance(10.0)
        assert br.state == "half-open"
        assert br.allow()  # claims the only probe slot
        assert not br.allow()  # no second probe in this window
        assert br.half_opens == 1

    def test_probe_success_closes(self):
        br, clock = self.make()
        for _ in range(3):
            br.record_failure()
        clock.advance(10.0)
        assert br.allow()
        br.record_success()
        assert br.state == "closed" and br.allow()

    def test_probe_failure_reopens_with_doubled_timeout(self):
        br, clock = self.make()
        for _ in range(3):
            br.record_failure()
        clock.advance(10.0)
        assert br.allow()
        br.record_failure()
        assert br.state == "open" and br.opens == 2
        clock.advance(10.0)  # first timeout elapsed, but it doubled to 20
        assert br.state == "open" and not br.allow()
        clock.advance(10.0)
        assert br.state == "half-open"

    def test_reopen_timeout_capped(self):
        br, clock = self.make(reset_timeout=10.0, max_reset_timeout=15.0)
        for _ in range(3):
            br.record_failure()
        for _ in range(5):  # repeated failed probes keep doubling
            clock.advance(1000.0)
            assert br.allow()
            br.record_failure()
        clock.advance(15.0)  # capped at max_reset_timeout
        assert br.state == "half-open"

    def test_straggler_failure_while_open_ignored(self):
        br, clock = self.make()
        for _ in range(3):
            br.record_failure()
        br.record_failure()  # call that was in flight when the circuit tripped
        assert br.opens == 1
        clock.advance(10.0)
        assert br.state == "half-open"

    def test_guard_context_manager(self):
        br, clock = self.make(failure_threshold=1)
        with pytest.raises(ValueError):
            with br.guard():
                raise ValueError("boom")
        assert br.state == "open"
        with pytest.raises(CircuitOpenError):
            with br.guard():
                pass
        clock.advance(10.0)
        with br.guard():
            pass  # half-open probe succeeds
        assert br.state == "closed"


class TestRetryingProvider:
    """light/provider.py adoption of the shared policy."""

    @pytest.mark.asyncio
    async def test_transient_errors_retried_then_success(self):
        from tendermint_tpu.light.provider import ProviderError, RetryingProvider

        class Flaky:
            def __init__(self):
                self.calls = 0

            def chain_id(self):
                return "t"

            async def light_block(self, height):
                self.calls += 1
                if self.calls < 3:
                    raise ProviderError("transient")
                return f"lb{height}"

            async def report_evidence(self, ev):
                pass

        inner = Flaky()
        p = RetryingProvider(
            inner,
            policy=BackoffPolicy(base=0.0001, max_attempts=5),
            rng=random.Random(0),
        )
        assert await p.light_block(7) == "lb7"
        assert inner.calls == 3

    @pytest.mark.asyncio
    async def test_not_found_is_definitive_and_does_not_trip(self):
        from tendermint_tpu.light.provider import (
            LightBlockNotFoundError,
            RetryingProvider,
        )

        class Lacking:
            def __init__(self):
                self.calls = 0

            def chain_id(self):
                return "t"

            async def light_block(self, height):
                self.calls += 1
                raise LightBlockNotFoundError(str(height))

            async def report_evidence(self, ev):
                pass

        inner = Lacking()
        p = RetryingProvider(
            inner, policy=BackoffPolicy(base=0.0001, max_attempts=5)
        )
        for _ in range(6):
            with pytest.raises(LightBlockNotFoundError):
                await p.light_block(3)
        assert inner.calls == 6  # one call each: never retried
        assert p.breaker.state == "closed"  # and never counted as failure

    @pytest.mark.asyncio
    async def test_breaker_opens_and_fails_fast(self):
        from tendermint_tpu.light.provider import ProviderError, RetryingProvider

        class Dead:
            def __init__(self):
                self.calls = 0

            def chain_id(self):
                return "t"

            async def light_block(self, height):
                self.calls += 1
                raise ProviderError("down")

            async def report_evidence(self, ev):
                pass

        inner = Dead()
        clock = FakeClock()
        p = RetryingProvider(
            inner,
            policy=BackoffPolicy(base=0.0001, max_attempts=2),
            breaker=CircuitBreaker(
                failure_threshold=2, reset_timeout=5.0, clock=clock
            ),
            rng=random.Random(0),
        )
        for _ in range(2):
            with pytest.raises(ProviderError):
                await p.light_block(1)
        assert p.breaker.state == "open"
        calls_before = inner.calls
        with pytest.raises(ProviderError):
            await p.light_block(1)  # fails fast
        assert inner.calls == calls_before  # inner never touched
        clock.advance(5.0)  # half-open: the probe reaches the provider
        with pytest.raises(ProviderError):
            await p.light_block(1)
        assert inner.calls > calls_before
