"""LightClientAttackEvidence: attribution (lunatic / equivocation /
amnesia branches of GetByzantineValidators, reference types/evidence.go),
encode/decode round-trip, hash stability, validate_basic."""

import pytest

from tendermint_tpu.light.types import LightBlock, SignedHeader
from tendermint_tpu.testing import make_commit, make_validator_set
from tendermint_tpu.types.block import BlockID, Header, PartSetHeader
from tendermint_tpu.types.evidence import (
    LightClientAttackEvidence,
    decode_evidence,
)
from tendermint_tpu.crypto.hashes import sha256

CHAIN = "lc-attack-chain"
TS = 1_700_000_000_000_000_000


def _header(vals, height=10, app_hash=b"\x01" * 32, data_hash=b"\x02" * 32):
    return Header(
        chain_id=CHAIN,
        height=height,
        time_ns=TS,
        last_block_id=BlockID(sha256(b"prev"), PartSetHeader(1, sha256(b"pp"))),
        last_commit_hash=sha256(b"lc"),
        data_hash=data_hash,
        validators_hash=vals.hash(),
        next_validators_hash=vals.hash(),
        consensus_hash=sha256(b"consensus"),
        app_hash=app_hash,
        last_results_hash=sha256(b"results"),
        evidence_hash=b"",
        proposer_address=vals.validators[0].address,
    )


def _signed_light_block(vals, keys, header, round_=0):
    bid = BlockID(header.hash(), PartSetHeader(1, sha256(b"parts")))
    commit = make_commit(CHAIN, header.height, round_, bid, vals, keys)
    return LightBlock(SignedHeader(header, commit), vals)


@pytest.fixture()
def net():
    vals, keys = make_validator_set(4)
    trusted_header = _header(vals)
    trusted = _signed_light_block(vals, keys, trusted_header)
    return vals, keys, trusted


def _evidence(conflicting, vals, byz=()):
    return LightClientAttackEvidence(
        conflicting_block=conflicting,
        common_height=5,
        byzantine_validators=tuple(byz),
        total_voting_power=vals.total_voting_power(),
        timestamp_ns=TS,
    )


class TestAttribution:
    def test_lunatic_attribution(self, net):
        """Forged app_hash → lunatic: every common-set validator that
        signed the conflicting block is byzantine."""
        vals, keys, trusted = net
        forged = _header(vals, app_hash=b"\xff" * 32)
        conflicting = _signed_light_block(vals, keys, forged)
        ev = _evidence(conflicting, vals)
        assert ev.conflicting_header_is_invalid(trusted.header)
        byz = ev.get_byzantine_validators(vals, trusted.signed_header)
        assert {v.address for v in byz} == {v.address for v in vals.validators}

    def test_lunatic_attribution_skips_non_common_validators(self, net):
        """Only validators in the common (trusted) set are attributable."""
        vals, keys, trusted = net
        other_vals, other_keys = make_validator_set(4, seed=b"other")
        forged = _header(other_vals, app_hash=b"\xff" * 32)
        conflicting = _signed_light_block(other_vals, other_keys, forged)
        ev = _evidence(conflicting, other_vals)
        byz = ev.get_byzantine_validators(vals, trusted.signed_header)
        assert byz == []  # disjoint set: nothing attributable to common vals

    def test_equivocation_attribution(self, net):
        """Valid state fields, same round, different block → validators who
        signed BOTH blocks equivocated."""
        vals, keys, trusted = net
        # same derived-state fields, different data_hash → different hash
        other = _header(vals, data_hash=b"\xaa" * 32)
        conflicting = _signed_light_block(vals, keys, other, round_=0)
        ev = _evidence(conflicting, vals)
        assert not ev.conflicting_header_is_invalid(trusted.header)
        byz = ev.get_byzantine_validators(vals, trusted.signed_header)
        assert {v.address for v in byz} == {v.address for v in vals.validators}

    def test_amnesia_not_attributable(self, net):
        """Different rounds with valid state fields → amnesia: empty."""
        vals, keys, trusted = net
        other = _header(vals, data_hash=b"\xaa" * 32)
        conflicting = _signed_light_block(vals, keys, other, round_=1)
        ev = _evidence(conflicting, vals)
        byz = ev.get_byzantine_validators(vals, trusted.signed_header)
        assert byz == []


class TestCodecAndValidation:
    def test_encode_decode_hash_roundtrip(self, net):
        vals, keys, trusted = net
        forged = _header(vals, app_hash=b"\xff" * 32)
        conflicting = _signed_light_block(vals, keys, forged)
        ev = _evidence(conflicting, vals, byz=vals.validators[:2])
        ev.validate_basic()
        data = ev.encode()
        ev2 = decode_evidence(data)
        assert isinstance(ev2, LightClientAttackEvidence)
        assert ev2.common_height == ev.common_height
        assert ev2.total_voting_power == ev.total_voting_power
        assert ev2.timestamp_ns == ev.timestamp_ns
        assert len(ev2.byzantine_validators) == 2
        assert ev2.conflicting_block.header.hash() == forged.hash()
        assert ev2.hash() == ev.hash()
        assert ev2.encode() == data

    def test_hash_ignores_attribution(self, net):
        """The same attack reported with different byzantine attributions
        must dedupe to one evidence entry."""
        vals, keys, trusted = net
        forged = _header(vals, app_hash=b"\xff" * 32)
        conflicting = _signed_light_block(vals, keys, forged)
        a = _evidence(conflicting, vals, byz=())
        b = _evidence(conflicting, vals, byz=vals.validators[:1])
        assert a.hash() == b.hash()

    def test_validate_basic_rejects_bad_fields(self, net):
        vals, keys, trusted = net
        forged = _header(vals, app_hash=b"\xff" * 32)
        conflicting = _signed_light_block(vals, keys, forged)
        with pytest.raises(ValueError):
            _evidence(None, vals).validate_basic()
        bad = LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=0,
            byzantine_validators=(),
            total_voting_power=40,
            timestamp_ns=TS,
        )
        with pytest.raises(ValueError):
            bad.validate_basic()
        beyond = LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=conflicting.height + 1,
            byzantine_validators=(),
            total_voting_power=40,
            timestamp_ns=TS,
        )
        with pytest.raises(ValueError):
            beyond.validate_basic()


class TestEndToEnd:
    @pytest.mark.asyncio
    async def test_forged_witness_header_becomes_block_evidence(self):
        """Full pipeline (reference light/detector.go:215 +
        internal/evidence/verify.go:159): a witness serves a forged header
        -> light client forms LightClientAttackEvidence and reports it to
        the primary -> the node's evidence pool verifies it -> consensus
        commits it in a block."""
        import asyncio
        from dataclasses import replace as drep

        from tendermint_tpu.consensus.harness import LocalNetwork
        from tendermint_tpu.light.client import (
            Divergence,
            LightClient,
            TrustOptions,
            TrustedStore,
        )
        from tendermint_tpu.light.provider import BlockStoreProvider
        from tendermint_tpu.light.types import LightBlock as LB, SignedHeader as SH
        from tendermint_tpu.testing import make_commit
        from tendermint_tpu.types.block import BlockID as BID, PartSetHeader as PSH

        net = LocalNetwork(3)
        await net.start()
        try:
            await net.wait_for_height(5, timeout=60)
            node = net.nodes[0]
            primary = BlockStoreProvider(
                net.genesis.chain_id,
                node.block_store,
                node.state_store,
                evidence_pool=node.evidence_pool,
            )
            target = 4

            class ForgingWitness:
                def __init__(self, base):
                    self.base = base

                async def light_block(self, height):
                    lb = await self.base.light_block(height)
                    if height != target:
                        return lb
                    hdr = drep(lb.header, data_hash=b"\xdd" * 32)
                    keys = {k.pub_key().address(): k for k in net.keys}
                    bid = BID(hdr.hash(), PSH(1, b"\x02" * 32))
                    commit = make_commit(
                        net.genesis.chain_id, height, 0, bid, lb.validators, keys
                    )
                    return LB(SH(hdr, commit), lb.validators)

                async def report_evidence(self, evidence):
                    pass

                def __repr__(self):
                    return "ForgingWitness"

            lb1 = await primary.light_block(1)
            client = LightClient(
                net.genesis.chain_id,
                TrustOptions(period_ns=10**18, height=1, hash=lb1.header.hash()),
                primary,
                [ForgingWitness(primary)],
                store=TrustedStore(),
                sequential=True,
            )
            with pytest.raises(Divergence):
                await client.verify_light_block_at_height(target)

            # evidence reached the primary's pool and verified
            assert primary.reported, "no evidence was reported"
            ev = primary.reported[0]
            assert isinstance(ev, LightClientAttackEvidence)
            assert len(ev.byzantine_validators) == 3  # equivocation: all signed
            pending, _ = node.evidence_pool.pending_evidence(1 << 20)
            assert any(e.hash() == ev.hash() for e in pending)

            # the running chain commits it into a block
            deadline = asyncio.get_running_loop().time() + 30
            committed = None
            while asyncio.get_running_loop().time() < deadline:
                for h in range(1, node.block_store.height() + 1):
                    blk = node.block_store.load_block(h)
                    if blk and blk.evidence:
                        committed = (h, blk.evidence)
                        break
                if committed:
                    break
                await asyncio.sleep(0.2)
            assert committed, "attack evidence never committed in a block"
            h, evs = committed
            assert any(e.hash() == ev.hash() for e in evs)
        finally:
            await net.stop()
