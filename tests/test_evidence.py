"""Evidence pool tests (modeled on reference internal/evidence/pool_test.go
and verify_test.go), plus the consensus-equivocation end-to-end path."""

import asyncio

import pytest

from tendermint_tpu.consensus.harness import LocalNetwork
from tendermint_tpu.evidence.pool import EvidenceError, EvidencePool
from tendermint_tpu.store.db import MemDB
from tendermint_tpu.testing import make_block_id, make_vote
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.keys import SignedMsgType


async def _committed_net(heights=2):
    """A 2-validator network that has committed a couple of blocks —
    gives us real historical validator sets + block metas to verify
    evidence against."""
    net = LocalNetwork(2)
    await net.start()
    await net.wait_for_height(heights, timeout=30)
    return net


def _equivocation(net, height):
    node = net.nodes[0]
    chain_id = net.genesis.chain_id
    vals = node.state_store.load_validators(height)
    meta = node.block_store.load_block_meta(height)
    # validator 1 signs two different blocks at (height, 0, precommit)
    key = net.keys[1]
    idx, _val = vals.get_by_address(key.pub_key().address())
    va = make_vote(
        chain_id, key, idx, height, 0, SignedMsgType.PRECOMMIT,
        make_block_id(b"fork-a"), timestamp_ns=meta.header.time_ns,
    )
    vb = make_vote(
        chain_id, key, idx, height, 0, SignedMsgType.PRECOMMIT,
        make_block_id(b"fork-b"), timestamp_ns=meta.header.time_ns,
    )
    return DuplicateVoteEvidence.from_votes(va, vb, meta.header.time_ns, vals), va, vb


class TestEvidencePool:
    @pytest.mark.asyncio
    async def test_add_verify_reap(self):
        net = await _committed_net()
        try:
            node = net.nodes[0]
            pool = node.evidence_pool
            ev, _, _ = _equivocation(net, 1)
            pool.add_evidence(ev)
            pending, size = pool.pending_evidence(1 << 20)
            assert len(pending) == 1 and size > 0
            assert pending[0].hash() == ev.hash()
            # adding again is a no-op
            pool.add_evidence(ev)
            assert len(pool.pending_evidence(1 << 20)[0]) == 1
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_rejects_tampered_evidence(self):
        net = await _committed_net()
        try:
            pool = net.nodes[0].evidence_pool
            ev, va, vb = _equivocation(net, 1)
            # wrong power
            bad = DuplicateVoteEvidence(
                ev.vote_a, ev.vote_b, ev.total_voting_power, ev.validator_power + 5,
                ev.timestamp_ns,
            )
            with pytest.raises(EvidenceError):
                pool.add_evidence(bad)
            # future height
            future_a = make_vote(
                net.genesis.chain_id, net.keys[1], 1, 99, 0,
                SignedMsgType.PRECOMMIT, make_block_id(b"x"),
            )
            futur_b = make_vote(
                net.genesis.chain_id, net.keys[1], 1, 99, 0,
                SignedMsgType.PRECOMMIT, make_block_id(b"y"),
            )
            bad2 = DuplicateVoteEvidence.from_votes(
                future_a, futur_b, ev.timestamp_ns,
                net.nodes[0].state_store.load_validators(1),
            )
            with pytest.raises(EvidenceError):
                pool.add_evidence(bad2)
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_consensus_report_flows_to_pending(self):
        net = await _committed_net()
        try:
            node = net.nodes[0]
            pool = node.evidence_pool
            _, va, vb = _equivocation(net, 1)
            pool.report_conflicting_votes(va, vb)
            # simulate the next committed block triggering the buffer
            state = node.state_store.load()
            pool.update(state, ())
            pending, _ = pool.pending_evidence(1 << 20)
            assert len(pending) == 1
            assert pending[0].vote_a.validator_address == va.validator_address
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_committed_evidence_not_repended(self):
        net = await _committed_net()
        try:
            node = net.nodes[0]
            pool = node.evidence_pool
            ev, _, _ = _equivocation(net, 1)
            pool.add_evidence(ev)
            state = node.state_store.load()
            pool.update(state, (ev,))  # committed in a block
            assert pool.pending_evidence(1 << 20)[0] == []
            with pytest.raises(EvidenceError):
                pool.check_evidence((ev,))
        finally:
            await net.stop()


class TestEquivocationEndToEnd:
    @pytest.mark.asyncio
    async def test_byzantine_votes_become_block_evidence(self):
        """Inject conflicting votes into a running network; the evidence
        must end up inside a committed block (reference
        byzantine_test.go flavor)."""
        net = await _committed_net(heights=1)
        try:
            node = net.nodes[0]
            _, va, vb = _equivocation(net, 1)
            await node.cs.add_vote(va, "byz")
            await node.cs.add_vote(vb, "byz")
            # wait until some committed block carries the evidence
            deadline = 20
            found = False
            for _ in range(deadline * 10):
                h = node.block_store.height()
                for height in range(1, h + 1):
                    blk = node.block_store.load_block(height)
                    if blk is not None and blk.evidence:
                        found = True
                        break
                if found:
                    break
                await asyncio.sleep(0.1)
            assert found, "equivocation evidence never committed in a block"
        finally:
            await net.stop()
