"""Multi-process e2e: four validators as separate OS processes over real
TCP with a kill+restart perturbation and app-hash convergence assertions
(reference test/e2e/runner/{main,perturb}.go — containers replaced by
plain processes; same black-box method: drive and observe over RPC only).
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from tendermint_tpu.cli import main as cli_main
from tendermint_tpu.config import Config, config_from_toml, config_to_toml

N_VALS = 4
BASE_PORT = 28600


def _rpc(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path}", timeout=5
    ) as resp:
        return json.loads(resp.read())["result"]


def _spawn(home: str, extra_env: dict | None = None) -> subprocess.Popen:
    env = dict(
        os.environ,
        TMTPU_DISABLE_TPU="1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    env.update(extra_env or {})
    return subprocess.Popen(
        [
            sys.executable,
            "-c",
            "from tendermint_tpu.cli import main; import sys; "
            f"sys.exit(main(['--home', {home!r}, 'start']))",
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )


def _spawn_verifyd(sock: str) -> subprocess.Popen:
    """The verification sidecar: ONE process owns the backend attach for
    the whole host. JAX stays CPU-pinned (CI has no TPU) but the probe
    runs — the attach it records is the one the telemetry assertion
    counts. TMTPU_MAX_BUCKET keeps the background warm compiles at the
    floor shape so they don't starve the 4 node processes."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        TMTPU_MAX_BUCKET="64",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    env.pop("TMTPU_DISABLE_TPU", None)
    return subprocess.Popen(
        [
            sys.executable,
            "-c",
            "from tendermint_tpu.cli import main; import sys; "
            f"sys.exit(main(['verifyd', '--sock', {sock!r}]))",
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )


def _verifyd_telemetry(sock: str) -> dict | None:
    from tendermint_tpu.crypto.verifyd import VerifydClient

    client = VerifydClient(sock)
    try:
        return client.remote_stats()
    finally:
        client.close()


def _wait_verifyd(sock: str, timeout: float = 60.0) -> dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        stats = _verifyd_telemetry(sock)
        if stats is not None:
            return stats
        time.sleep(0.25)
    raise TimeoutError(f"verifyd on {sock} never came up")


def _wait_height(port: int, height: int, timeout: float) -> None:
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            st = _rpc(port, "status")
            last = int(st["sync_info"]["latest_block_height"])
            if last >= height:
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"node on :{port} stuck at {last} (wanted {height})")


@pytest.mark.slow
def test_four_process_testnet_with_kill_restart(tmp_path):
    base = str(tmp_path / "net")
    # generous timeout windows: starved proposers on the 1-core CI host
    # churn rounds under tight ones (same rationale as e2e_manifest.py)
    rpc_ports = _gen_testnet(base, BASE_PORT)
    procs: dict[int, subprocess.Popen] = {}
    try:
        for i in range(N_VALS):
            procs[i] = _spawn(os.path.join(base, f"node{i}"))

        # the network must make progress with all 4 up
        for port in rpc_ports:
            _wait_height(port, 3, timeout=120)

        # perturbation: SIGKILL validator 3 (reference perturb.go kill)
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)

        # 3-of-4 keeps committing (2/3+ still online)
        h_before = int(_rpc(rpc_ports[0], "status")["sync_info"]["latest_block_height"])
        _wait_height(rpc_ports[0], h_before + 2, timeout=120)

        # restart on the same stores; it must catch up (WAL + handshake +
        # block-sync recovery path)
        procs[3] = _spawn(os.path.join(base, "node3"))
        h_target = int(_rpc(rpc_ports[0], "status")["sync_info"]["latest_block_height"])
        _wait_height(rpc_ports[3], h_target, timeout=180)

        # app-hash convergence at a common committed height
        common = min(
            int(_rpc(p, "status")["sync_info"]["latest_block_height"])
            for p in rpc_ports
        )
        hashes = {
            _rpc(p, f"block?height={common}")["block"]["header"]["app_hash"]
            for p in rpc_ports
        }
        assert len(hashes) == 1, f"app hash divergence at {common}: {hashes}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def _gen_testnet(base: str, base_port: int) -> list[int]:
    """Generate a 4-validator testnet with test-speed timeouts; returns
    the RPC ports."""
    rc = cli_main(
        [
            "testnet",
            "--validators",
            str(N_VALS),
            "--output",
            base,
            "--base-port",
            str(base_port),
        ]
    )
    assert rc == 0
    for i in range(N_VALS):
        toml_path = os.path.join(base, f"node{i}", "config", "config.toml")
        with open(toml_path) as f:
            cfg = config_from_toml(f.read())
        MS = 1_000_000
        cfg.consensus.timeout_propose_ns = 3000 * MS
        cfg.consensus.timeout_prevote_ns = 1000 * MS
        cfg.consensus.timeout_precommit_ns = 1000 * MS
        cfg.consensus.timeout_commit_ns = 300 * MS
        with open(toml_path, "w") as f:
            f.write(config_to_toml(cfg))
    return [base_port + 2 * i + 1 for i in range(N_VALS)]


def _app_hashes(port: int, upto: int) -> list[str]:
    return [
        _rpc(port, f"block?height={h}")["block"]["header"]["app_hash"]
        for h in range(1, upto + 1)
    ]


@pytest.mark.slow
def test_four_process_testnet_over_verifyd_sidecar(tmp_path):
    """The sidecar shape (ISSUE 11): verifyd spawned FIRST, all 4 node
    processes pointed at its socket via TMTPU_VERIFYD_SOCK. Asserts,
    from the daemon's telemetry (never log tails):

      * exactly ONE backend_attach happened host-wide (the daemon's;
        the nodes route remotely and never touch a backend);
      * the daemon actually served the nodes' verification traffic;
      * SIGKILL-ing the daemon mid-consensus costs NOTHING but latency —
        the chain keeps committing on inline-local verification — and a
        restarted daemon is re-adopted by every node (its fresh request
        counter moves again);
      * the committed app-state chain is identical to a sidecar-less
        control run of the same shape (the sidecar changes where
        signatures are checked, never what is committed — full
        block-byte identity is pinned by the in-process frozen-clock
        test in tests/test_verifyd.py, which real wall-clock processes
        cannot reproduce).
    """
    sock = os.path.join(str(tmp_path), "vd.sock")
    TARGET = 3

    # control run: the plain testnet, no sidecar
    ctrl_ports = _gen_testnet(str(tmp_path / "ctrl"), BASE_PORT + 100)
    procs: dict = {}
    daemon = None
    try:
        for i in range(N_VALS):
            procs[f"c{i}"] = _spawn(os.path.join(str(tmp_path / "ctrl"), f"node{i}"))
        for port in ctrl_ports:
            _wait_height(port, TARGET, timeout=120)
        ctrl_hashes = _app_hashes(ctrl_ports[0], TARGET)
        for key in list(procs):
            os.killpg(procs[key].pid, signal.SIGKILL)
            procs.pop(key).wait(timeout=10)

        # sidecar run: daemon first, then the nodes
        daemon = _spawn_verifyd(sock)
        _wait_verifyd(sock)
        ports = _gen_testnet(str(tmp_path / "net"), BASE_PORT + 200)
        node_env = {
            "TMTPU_VERIFYD_SOCK": sock,
            # quick half-open probes so the restart re-adoption below
            # lands inside the test budget
            "TMTPU_VERIFYD_BREAKER_RESET": "2",
        }
        for i in range(N_VALS):
            procs[i] = _spawn(
                os.path.join(str(tmp_path / "net"), f"node{i}"), node_env
            )
        for port in ports:
            _wait_height(port, TARGET, timeout=180)

        stats = _wait_verifyd(sock)
        # exactly one attach, host-wide, read from telemetry: the
        # daemon's probe attached the (CPU-pinned) backend once; every
        # node held TMTPU_DISABLE_TPU=1 and routed its batches here
        assert stats["backend"]["attach_attempts"] == 1, stats["backend"]
        assert stats["backend"]["attach_failures"] == 0, stats["backend"]
        assert stats["daemon"]["requests"] > 0, "nodes never used the sidecar"
        assert stats["daemon"]["sigs"] > 0
        assert stats["hub"]["verify_errors"] == 0

        # identical app-state chain vs the control run
        assert _app_hashes(ports[0], TARGET) == ctrl_hashes

        # SIGKILL the daemon mid-consensus: liveness must not flinch
        os.killpg(daemon.pid, signal.SIGKILL)
        daemon.wait(timeout=10)
        daemon = None
        h = max(
            int(_rpc(p, "status")["sync_info"]["latest_block_height"])
            for p in ports
        )
        _wait_height(ports[0], h + 2, timeout=180)

        # restart on the same socket: the nodes' half-open probes must
        # re-adopt the remote route — the FRESH daemon's verify_batch
        # counter moving is the proof, per-process breakers included
        daemon = _spawn_verifyd(sock)
        _wait_verifyd(sock)
        deadline = time.time() + 120
        readopted = False
        while time.time() < deadline:
            stats = _verifyd_telemetry(sock)
            if stats is not None and stats["daemon"]["requests"] > 0:
                readopted = True
                break
            time.sleep(1.0)
        assert readopted, "no node re-adopted the restarted daemon"

        # and the chain still converges across all four nodes
        common = min(
            int(_rpc(p, "status")["sync_info"]["latest_block_height"])
            for p in ports
        )
        hashes = {
            _rpc(p, f"block?height={common}")["block"]["header"]["app_hash"]
            for p in ports
        }
        assert len(hashes) == 1, f"app hash divergence at {common}: {hashes}"
    finally:
        if daemon is not None and daemon.poll() is None:
            try:
                os.killpg(daemon.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                daemon.kill()
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
