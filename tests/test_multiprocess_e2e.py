"""Multi-process e2e: four validators as separate OS processes over real
TCP with a kill+restart perturbation and app-hash convergence assertions
(reference test/e2e/runner/{main,perturb}.go — containers replaced by
plain processes; same black-box method: drive and observe over RPC only).
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from tendermint_tpu.cli import main as cli_main
from tendermint_tpu.config import Config, config_from_toml, config_to_toml

N_VALS = 4
BASE_PORT = 28600


def _rpc(port: int, path: str) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path}", timeout=5
    ) as resp:
        return json.loads(resp.read())["result"]


def _spawn(home: str) -> subprocess.Popen:
    env = dict(
        os.environ,
        TMTPU_DISABLE_TPU="1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return subprocess.Popen(
        [
            sys.executable,
            "-c",
            "from tendermint_tpu.cli import main; import sys; "
            f"sys.exit(main(['--home', {home!r}, 'start']))",
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,
    )


def _wait_height(port: int, height: int, timeout: float) -> None:
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            st = _rpc(port, "status")
            last = int(st["sync_info"]["latest_block_height"])
            if last >= height:
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"node on :{port} stuck at {last} (wanted {height})")


@pytest.mark.slow
def test_four_process_testnet_with_kill_restart(tmp_path):
    base = str(tmp_path / "net")
    rc = cli_main(
        [
            "testnet",
            "--validators",
            str(N_VALS),
            "--output",
            base,
            "--base-port",
            str(BASE_PORT),
        ]
    )
    assert rc == 0

    # speed the chain up: rewrite each generated config with test timeouts
    for i in range(N_VALS):
        toml_path = os.path.join(base, f"node{i}", "config", "config.toml")
        with open(toml_path) as f:
            cfg = config_from_toml(f.read())
        MS = 1_000_000
        # generous windows: starved proposers on the 1-core CI host churn
        # rounds under tight timeouts (same rationale as e2e_manifest.py)
        cfg.consensus.timeout_propose_ns = 3000 * MS
        cfg.consensus.timeout_prevote_ns = 1000 * MS
        cfg.consensus.timeout_precommit_ns = 1000 * MS
        cfg.consensus.timeout_commit_ns = 300 * MS
        with open(toml_path, "w") as f:
            f.write(config_to_toml(cfg))

    rpc_ports = [BASE_PORT + 2 * i + 1 for i in range(N_VALS)]
    procs: dict[int, subprocess.Popen] = {}
    try:
        for i in range(N_VALS):
            procs[i] = _spawn(os.path.join(base, f"node{i}"))

        # the network must make progress with all 4 up
        for port in rpc_ports:
            _wait_height(port, 3, timeout=120)

        # perturbation: SIGKILL validator 3 (reference perturb.go kill)
        procs[3].send_signal(signal.SIGKILL)
        procs[3].wait(timeout=10)

        # 3-of-4 keeps committing (2/3+ still online)
        h_before = int(_rpc(rpc_ports[0], "status")["sync_info"]["latest_block_height"])
        _wait_height(rpc_ports[0], h_before + 2, timeout=120)

        # restart on the same stores; it must catch up (WAL + handshake +
        # block-sync recovery path)
        procs[3] = _spawn(os.path.join(base, "node3"))
        h_target = int(_rpc(rpc_ports[0], "status")["sync_info"]["latest_block_height"])
        _wait_height(rpc_ports[3], h_target, timeout=180)

        # app-hash convergence at a common committed height
        common = min(
            int(_rpc(p, "status")["sync_info"]["latest_block_height"])
            for p in rpc_ports
        )
        hashes = {
            _rpc(p, f"block?height={common}")["block"]["header"]["app_hash"]
            for p in rpc_ports
        }
        assert len(hashes) == 1, f"app hash divergence at {common}: {hashes}"
    finally:
        for p in procs.values():
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
