"""Crash-recovery matrix (reference replay_test.go + FAIL_TEST_INDEX
crash points): simulate a crash at EVERY commit sub-step and verify the
node recovers via WAL replay + ABCI handshake and keeps committing."""

import asyncio
import tempfile

import pytest

from tendermint_tpu.consensus.harness import Node, make_genesis
from tendermint_tpu.libs import fail
from tendermint_tpu.proxy import AppConns

CRASH_POINTS = [1, 2, 3, 4, 5]


class TestCrashMatrix:
    @pytest.mark.asyncio
    @pytest.mark.parametrize("point", CRASH_POINTS)
    async def test_crash_at_point_then_recover(self, point):
        genesis, keys = make_genesis(1)
        crashed = asyncio.Event()

        def crash(p):
            crashed.set()
            raise fail.InjectedCrash(p)

        with tempfile.TemporaryDirectory() as wal_dir:
            node = Node(genesis, keys[0], wal_dir=wal_dir)
            await node.start()
            # let one height commit cleanly, then arm the crash point
            await node.cs.wait_for_height(1, timeout=20)
            fail.set_crash_callback(crash, index=point)
            try:
                await asyncio.wait_for(crashed.wait(), 20)
            finally:
                fail.reset()
            # the receive task is dead — this is our "crashed process"
            await node.stop()
            h_before = node.block_store.height()

            # restart on the same stores/WAL/app
            node2 = Node(genesis, keys[0], wal_dir=wal_dir)
            node2.block_store = node.block_store
            node2.state_store = node.state_store
            node2.app = node.app
            node2.app_conns = AppConns.local(node.app)
            await node2.start()
            try:
                await node2.cs.wait_for_height(h_before + 2, timeout=30)
                # app and store agree after recovery
                from tendermint_tpu.abci import types as abci

                info = node.app.info(abci.RequestInfo())
                state = node2.state_store.load()
                assert info.last_block_height <= node2.block_store.height()
                assert state.last_block_height >= h_before
            finally:
                await node2.stop()
