"""Crash-recovery matrix (reference replay_test.go + FAIL_TEST_INDEX
crash points): simulate a crash at EVERY commit sub-step and verify the
node recovers via WAL replay + ABCI handshake and keeps committing."""

import asyncio
import tempfile

import pytest

from tendermint_tpu.consensus.harness import Node, make_genesis
from tendermint_tpu.libs import fail
from tendermint_tpu.proxy import AppConns

CRASH_POINTS = [1, 2, 3, 4, 5]


class TestCrashMatrix:
    @pytest.mark.asyncio
    @pytest.mark.parametrize("point", CRASH_POINTS)
    async def test_crash_at_point_then_recover(self, point):
        genesis, keys = make_genesis(1)
        crashed = asyncio.Event()

        def crash(p):
            crashed.set()
            raise fail.InjectedCrash(p)

        with tempfile.TemporaryDirectory() as wal_dir:
            node = Node(genesis, keys[0], wal_dir=wal_dir)
            await node.start()
            # let one height commit cleanly, then arm the crash point
            await node.cs.wait_for_height(1, timeout=20)
            fail.set_crash_callback(crash, index=point)
            try:
                await asyncio.wait_for(crashed.wait(), 20)
            finally:
                fail.reset()
            # the receive task is dead — this is our "crashed process"
            await node.stop()
            h_before = node.block_store.height()

            # restart on the same stores/WAL/app
            node2 = Node(genesis, keys[0], wal_dir=wal_dir)
            node2.block_store = node.block_store
            node2.state_store = node.state_store
            node2.app = node.app
            node2.app_conns = AppConns.local(node.app)
            await node2.start()
            try:
                await node2.cs.wait_for_height(h_before + 2, timeout=30)
                # app and store agree after recovery
                from tendermint_tpu.abci import types as abci

                info = node.app.info(abci.RequestInfo())
                state = node2.state_store.load()
                assert info.last_block_height <= node2.block_store.height()
                assert state.last_block_height >= h_before
            finally:
                await node2.stop()


class TestCrashUnderChaos:
    @pytest.mark.asyncio
    async def test_crash_and_resume_mid_sync_under_chaos(self):
        """Seeded chaos matrix × crash-recovery: a node block-syncing
        through a lossy, slow net is crashed mid-sync (reactor+router torn
        down) and restarted on the SAME stores; it must resume from where
        it stopped — with the first block applied after the restart taking
        the full verification path — and the whole net must converge on
        the source chain's hashes."""
        from tendermint_tpu.libs.chaos import ChaosConfig
        from tendermint_tpu.testing import build_kvstore_chain
        from tests.chaos_net import ChaosSyncNet

        bstore, sstore, conns, genesis, _ = await build_kvstore_chain(
            24, 3, chain_id="chaos-chain"
        )
        net = ChaosSyncNet(
            genesis,
            bstore,
            sstore.load(),
            ChaosConfig(seed=77, drop_rate=0.05, delay_ms=30.0),
            n_sync=2,
            window=6,
        )
        target = 23
        await net.start()
        try:
            victim = net.sync_nodes[0]
            # crash once it has made real progress but is not done
            deadline = asyncio.get_running_loop().time() + 60
            while victim.block_store.height() < 6:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            h_crash = victim.block_store.height()
            applied_cv: list[tuple[int, bool]] = []
            reborn = await net.restart_sync_node(victim)
            # spy AFTER restart: record apply-time verification decisions
            orig_apply = reborn.reactor.block_exec.apply_block

            async def spy(state, block_id, block, commit_verified=False):
                applied_cv.append((block.header.height, commit_verified))
                return await orig_apply(
                    state, block_id, block, commit_verified=commit_verified
                )

            reborn.reactor.block_exec.apply_block = spy
            await net.wait_synced(target, timeout=75)
            assert reborn.block_store.height() >= target >= h_crash
            assert len(set(net.hashes_at(target))) == 1
            # restart regression: the first post-restart apply was
            # full-verified (no stale batch-proof carried across the crash)
            assert applied_cv and applied_cv[0][1] is False
        finally:
            await net.stop()
            await conns.stop()
