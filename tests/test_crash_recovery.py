"""Crash-recovery matrix (reference replay_test.go + FAIL_TEST_INDEX
crash points): simulate a crash at EVERY commit sub-step and verify the
node recovers via WAL replay + ABCI handshake and keeps committing.

The storage half (chaos-fs): kill the node at seeded WAL fault points —
record boundaries, mid-record torn writes, post-write/pre-fsync, and
disk-full mid-record — and assert the restarted node repairs the WAL and
replays to a chain bit-identical to an uncrashed control node (frozen
injectable clocks make both runs' timestamps deterministic)."""

import asyncio
import tempfile

import pytest

from tendermint_tpu.consensus.harness import Node, make_genesis
from tendermint_tpu.libs import fail
from tendermint_tpu.libs.chaosfs import ChaosFS, ChaosFSConfig
from tendermint_tpu.libs.clock import ManualClock
from tendermint_tpu.proxy import AppConns

CRASH_POINTS = [1, 2, 3, 4, 5]


class TestCrashMatrix:
    @pytest.mark.asyncio
    @pytest.mark.parametrize("point", CRASH_POINTS)
    async def test_crash_at_point_then_recover(self, point):
        genesis, keys = make_genesis(1)
        crashed = asyncio.Event()

        def crash(p):
            crashed.set()
            raise fail.InjectedCrash(p)

        with tempfile.TemporaryDirectory() as wal_dir:
            node = Node(genesis, keys[0], wal_dir=wal_dir)
            await node.start()
            # let one height commit cleanly, then arm the crash point
            await node.cs.wait_for_height(1, timeout=20)
            fail.set_crash_callback(crash, index=point)
            try:
                await asyncio.wait_for(crashed.wait(), 20)
            finally:
                fail.reset()
            # the receive task is dead — this is our "crashed process"
            await node.stop()
            h_before = node.block_store.height()

            # restart on the same stores/WAL/app
            node2 = Node(genesis, keys[0], wal_dir=wal_dir)
            node2.block_store = node.block_store
            node2.state_store = node.state_store
            node2.app = node.app
            node2.app_conns = AppConns.local(node.app)
            await node2.start()
            try:
                await node2.cs.wait_for_height(h_before + 2, timeout=30)
                # app and store agree after recovery
                from tendermint_tpu.abci import types as abci

                info = node.app.info(abci.RequestInfo())
                state = node2.state_store.load()
                assert info.last_block_height <= node2.block_store.height()
                assert state.last_block_height >= h_before
            finally:
                await node2.stop()


async def _run_control(genesis, key, target: int, wal_dir: str):
    """Uncrashed control node on a frozen clock: the reference chain as
    (block_hash, header_time_ns, app_hash) per height."""
    node = Node(
        genesis, key, wal_dir=wal_dir,
        clock=ManualClock(genesis.genesis_time_ns - 1_000_000_000),
    )
    await node.start()
    try:
        await node.cs.wait_for_height(target, timeout=30)
    finally:
        await node.stop()
    return [
        (b.hash(), b.header.time_ns, b.header.app_hash)
        for b in (node.block_store.load_block(h) for h in range(1, target + 1))
    ]


async def _restart_on_same_stores(node, genesis, key, wal_dir: str, fs):
    reborn = Node(
        genesis, key, wal_dir=wal_dir, fs=fs,
        clock=ManualClock(genesis.genesis_time_ns - 1_000_000_000),
    )
    reborn.block_store = node.block_store
    reborn.state_store = node.state_store
    reborn.app = node.app
    reborn.app_conns = AppConns.local(node.app)
    await reborn.start()
    return reborn


class TestWALFaultMatrix:
    """Seeded kill points in the WAL write path. Every fault class must
    end the same way: restart with no manual intervention, WAL repaired,
    replay + handshake reconverge, and the recovered chain carries the
    SAME app state and timestamps as an uncrashed control (frozen clocks
    make both deterministic). Full block-hash equality is deliberately
    NOT asserted here: a crash that tears a record the SM had already
    acted on legitimately costs a round, and the commit round is part of
    the next block's hash — whether that happens depends on where the
    real-time halt lands relative to the 80ms height cadence. Chain
    bit-reproducibility under a fixed seed is asserted where the crash
    instant itself is deterministic (the ENOSPC test: armed at an exact
    cumulative byte)."""

    TARGET = 4
    CRASH_AT = 2

    FAULTS = {
        # clean kill: the un-fsynced buffered tail vanishes at a record
        # boundary (the durable watermark is always post-fsync = aligned)
        "record_boundary": ChaosFSConfig(seed=21),
        # the un-fsynced tail survives only partially, cut mid-record
        "torn_mid_record": ChaosFSConfig(seed=22, torn_write_rate=1.0),
        # post-write/pre-fsync: half the fsyncs are acked but lost, so
        # the crash tears away records consensus believed were durable
        "pre_fsync_lost": ChaosFSConfig(seed=23, lost_fsync_rate=0.5, torn_write_rate=0.5),
    }

    @pytest.mark.asyncio
    @pytest.mark.parametrize("fault", list(FAULTS))
    async def test_killed_at_wal_fault_point_matches_control(self, fault, tmp_path):
        genesis, keys = make_genesis(1)
        control = await _run_control(
            genesis, keys[0], self.TARGET, str(tmp_path / "ctl")
        )

        fs = ChaosFS(self.FAULTS[fault])
        wal_dir = str(tmp_path / "wal")
        node = Node(
            genesis, keys[0], wal_dir=wal_dir, fs=fs,
            clock=ManualClock(genesis.genesis_time_ns - 1_000_000_000),
        )
        await node.start()
        await node.cs.wait_for_height(self.CRASH_AT, timeout=30)
        fs.halt()  # the process dies HERE; teardown below is post-mortem
        await node.stop()
        fs.simulate_crash()

        reborn = await _restart_on_same_stores(node, genesis, keys[0], wal_dir, fs)
        try:
            await reborn.cs.wait_for_height(self.TARGET, timeout=30)
        finally:
            await reborn.stop()
        got = [
            (b.header.time_ns, b.header.app_hash)
            for b in (
                reborn.block_store.load_block(h)
                for h in range(1, self.TARGET + 1)
            )
        ]
        assert got == [(t, a) for _, t, a in control], (
            f"{fault}: replayed app state/timestamps diverged from control"
        )
        state = reborn.state_store.load()
        assert state.last_block_height >= self.TARGET

    async def _crash_on_enospc(self, genesis, key, wal_dir: str):
        """One seeded disk-full run: arm ENOSPC at a fixed cumulative
        byte (it fires mid-height-2, inside the proposal's block-part WAL
        write), crash there, restart, run to TARGET. Returns the
        recovered chain's (hash, header_time) pairs."""
        fs = ChaosFS(ChaosFSConfig(seed=31, enospc_at_byte=1200))
        node = Node(
            genesis, key, wal_dir=wal_dir, fs=fs,
            clock=ManualClock(genesis.genesis_time_ns - 1_000_000_000),
        )
        await node.start()
        deadline = asyncio.get_running_loop().time() + 30
        while fs.faults["enospc"] == 0:
            assert asyncio.get_running_loop().time() < deadline, "ENOSPC never hit"
            await asyncio.sleep(0.02)
        fs.halt()
        await node.stop()
        fs.simulate_crash()

        reborn = await _restart_on_same_stores(node, genesis, key, wal_dir, fs)
        try:
            await reborn.cs.wait_for_height(self.TARGET, timeout=30)
        finally:
            await reborn.stop()
        return [
            (b.hash(), b.header.time_ns, b.header.app_hash)
            for b in (
                reborn.block_store.load_block(h)
                for h in range(1, self.TARGET + 1)
            )
        ]

    @pytest.mark.asyncio
    async def test_enospc_mid_record_kills_then_recovers(self, tmp_path):
        """Disk-full mid-record: the WAL write raises ENOSPC mid-proposal
        (the crash), the partial frame is rolled back, and the restarted
        node recovers unaided. The lost block parts legitimately cost a
        round, so the commit ROUND may differ from an uncrashed control —
        what must match is the app state (app_hash chain) and the
        timestamps; and the whole crashed run must be bit-reproducible
        under the same chaos seed."""
        genesis, keys = make_genesis(1)
        control = await _run_control(
            genesis, keys[0], self.TARGET, str(tmp_path / "ctl")
        )
        run_a = await self._crash_on_enospc(genesis, keys[0], str(tmp_path / "a"))
        run_b = await self._crash_on_enospc(genesis, keys[0], str(tmp_path / "b"))
        assert run_a == run_b, "same chaos seed must reproduce the run bit-for-bit"
        # identical app state + timestamps vs the uncrashed control (the
        # commit round is allowed to differ — the crash cost one round)
        assert [(t, a) for _, t, a in run_a] == [(t, a) for _, t, a in control]

    @pytest.mark.asyncio
    @pytest.mark.slow
    async def test_repeated_crash_restart_soak(self, tmp_path):
        """Soak: crash the same validator at every height for a while
        under combined torn-write + lost-fsync faults; it must keep
        recovering and keep extending the control chain."""
        genesis, keys = make_genesis(1)
        target = 8
        control = await _run_control(
            genesis, keys[0], target, str(tmp_path / "ctl")
        )
        fs = ChaosFS(ChaosFSConfig(seed=77, torn_write_rate=0.7, lost_fsync_rate=0.3))
        wal_dir = str(tmp_path / "wal")
        node = Node(
            genesis, keys[0], wal_dir=wal_dir, fs=fs,
            clock=ManualClock(genesis.genesis_time_ns - 1_000_000_000),
        )
        await node.start()
        for crash_at in range(1, target):
            await node.cs.wait_for_height(crash_at, timeout=30)
            fs.halt()
            await node.stop()
            fs.simulate_crash()
            node = await _restart_on_same_stores(
                node, genesis, keys[0], wal_dir, fs
            )
        try:
            await node.cs.wait_for_height(target, timeout=30)
        finally:
            await node.stop()
        got = [
            (b.header.time_ns, b.header.app_hash)
            for b in (node.block_store.load_block(h) for h in range(1, target + 1))
        ]
        assert got == [(t, a) for _, t, a in control]


class TestCrashUnderChaos:
    @pytest.mark.asyncio
    async def test_crash_and_resume_mid_sync_under_chaos(self):
        """Seeded chaos matrix × crash-recovery: a node block-syncing
        through a lossy, slow net is crashed mid-sync (reactor+router torn
        down) and restarted on the SAME stores; it must resume from where
        it stopped — with the first block applied after the restart taking
        the full verification path — and the whole net must converge on
        the source chain's hashes."""
        from tendermint_tpu.libs.chaos import ChaosConfig
        from tendermint_tpu.testing import build_kvstore_chain
        from tests.chaos_net import ChaosSyncNet

        bstore, sstore, conns, genesis, _ = await build_kvstore_chain(
            24, 3, chain_id="chaos-chain"
        )
        net = ChaosSyncNet(
            genesis,
            bstore,
            sstore.load(),
            ChaosConfig(seed=77, drop_rate=0.05, delay_ms=30.0),
            n_sync=2,
            window=6,
        )
        target = 23
        await net.start()
        try:
            victim = net.sync_nodes[0]
            # crash once it has made real progress but is not done
            deadline = asyncio.get_running_loop().time() + 60
            while victim.block_store.height() < 6:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            h_crash = victim.block_store.height()
            applied_cv: list[tuple[int, bool]] = []
            reborn = await net.restart_sync_node(victim)
            # spy AFTER restart: record apply-time verification decisions
            orig_apply = reborn.reactor.block_exec.apply_block

            async def spy(state, block_id, block, commit_verified=False):
                applied_cv.append((block.header.height, commit_verified))
                return await orig_apply(
                    state, block_id, block, commit_verified=commit_verified
                )

            reborn.reactor.block_exec.apply_block = spy
            await net.wait_synced(target, timeout=75)
            assert reborn.block_store.height() >= target >= h_crash
            assert len(set(net.hashes_at(target))) == 1
            # restart regression: the first post-restart apply was
            # full-verified (no stale batch-proof carried across the crash)
            assert applied_cv and applied_cv[0][1] is False
        finally:
            await net.stop()
            await conns.stop()
