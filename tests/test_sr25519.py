"""sr25519 (schnorrkel/ristretto255/merlin) tests — reference
crypto/sr25519/sr25519_test.go plus RFC 9496 ristretto255 test vectors,
and the mixed-key validator set coverage of crypto/batch/batch.go dispatch.
"""

import pytest

import tendermint_tpu.crypto.ed25519_math as em
from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto import sr25519 as sr
from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey


class TestKeccakStrobe:
    def test_keccak_f1600_vs_hashlib_sha3(self):
        """Drive a one-block SHA3-256 sponge through our permutation and
        compare against hashlib — a full known-answer test of keccak-f."""
        import hashlib

        rate = 136  # SHA3-256 rate in bytes
        block = bytearray(rate)
        block[0] ^= 0x06  # SHA3 domain padding
        block[rate - 1] ^= 0x80
        lanes = [
            int.from_bytes(bytes(block[8 * i : 8 * i + 8]), "little")
            if 8 * i < rate
            else 0
            for i in range(25)
        ]
        out = sr.keccak_f1600(lanes)
        digest = b"".join(lane.to_bytes(8, "little") for lane in out)[:32]
        assert digest == hashlib.sha3_256(b"").digest()

    def test_merlin_transcript_determinism(self):
        a = sr.MerlinTranscript(b"test")
        b = sr.MerlinTranscript(b"test")
        a.append_message(b"l", b"m")
        b.append_message(b"l", b"m")
        assert a.challenge_bytes(b"c", 32) == b.challenge_bytes(b"c", 32)
        # domain separation: different label -> different challenge
        c = sr.MerlinTranscript(b"test2")
        c.append_message(b"l", b"m")
        assert c.challenge_bytes(b"c", 32) != sr.MerlinTranscript(
            b"test"
        ).challenge_bytes(b"c", 32)


RFC9496_SMALL_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
    "e882b131016b52c1d3337080187cf768423efccbb517bb495ab812c4160ff44e",
]


class TestRistretto:
    def test_rfc9496_small_multiples(self):
        for k, expect in enumerate(RFC9496_SMALL_MULTIPLES):
            p = em.Point.identity() if k == 0 else em.BASE.scalar_mul(k)
            assert sr.ristretto_encode(p).hex() == expect

    def test_decode_encode_roundtrip(self):
        for k in (1, 2, 7, 99, 31337):
            enc = sr.ristretto_encode(em.BASE.scalar_mul(k))
            p = sr.ristretto_decode(enc)
            assert p is not None
            assert sr.ristretto_encode(p) == enc

    def test_decode_rejects_invalid(self):
        # non-canonical (>= p)
        assert sr.ristretto_decode((sr.P + 3).to_bytes(32, "little")) is None
        # negative s (odd)
        assert sr.ristretto_decode((3).to_bytes(32, "little")) is None
        # not on curve / no square root: try a few garbage values
        bad = 0
        for v in (8, 10, 12, 14, 16, 18, 20, 22):
            if sr.ristretto_decode(int(v).to_bytes(32, "little")) is None:
                bad += 1
        assert bad > 0
        assert sr.ristretto_decode(b"\x01" * 31) is None  # wrong length

    def test_torsion_safety(self):
        """Encodings quotient torsion: P and P+T (T 4-torsion) encode
        equal — decode must give a representative encoding back to the
        same bytes."""
        p = em.BASE.scalar_mul(5)
        enc = sr.ristretto_encode(p)
        dec = sr.ristretto_decode(enc)
        assert dec.mul_by_cofactor().equals(p.mul_by_cofactor())


class TestSignVerify:
    def test_roundtrip(self):
        priv = sr.Sr25519PrivKey(b"\x07" * 32)
        pub = priv.pub_key()
        sig = priv.sign(b"msg")
        assert len(sig) == 64
        assert sig[63] & 0x80  # schnorrkel marker
        assert pub.verify_signature(b"msg", sig)
        assert not pub.verify_signature(b"msG", sig)

    def test_tamper_rejection(self):
        priv = sr.Sr25519PrivKey.generate()
        pub = priv.pub_key()
        sig = priv.sign(b"payload")
        for i in (0, 31, 32, 63):
            bad = bytearray(sig)
            bad[i] ^= 0x04
            assert not pub.verify_signature(b"payload", bytes(bad))

    def test_unmarked_signature_rejected(self):
        priv = sr.Sr25519PrivKey.generate()
        pub = priv.pub_key()
        sig = bytearray(priv.sign(b"x"))
        sig[63] &= 0x7F  # strip the schnorrkel marker
        assert not pub.verify_signature(b"x", bytes(sig))

    def test_wrong_key(self):
        a, b = sr.Sr25519PrivKey.generate(), sr.Sr25519PrivKey.generate()
        sig = a.sign(b"x")
        assert not b.pub_key().verify_signature(b"x", sig)

    def test_deterministic_signing(self):
        priv = sr.Sr25519PrivKey(b"\x11" * 32)
        assert priv.sign(b"m") == priv.sign(b"m")
        assert priv.sign(b"m") != priv.sign(b"n")


class TestBatchDispatch:
    def test_supports_batch(self):
        assert crypto_batch.supports_batch_verifier(
            sr.Sr25519PrivKey.generate().pub_key()
        )
        bv = crypto_batch.create_batch_verifier(
            sr.Sr25519PrivKey.generate().pub_key()
        )
        assert bv is not None

    def test_mixed_ed25519_sr25519_batch(self):
        """One verifier accepts both key types and produces a correct
        bitmap (TPU disabled in tests -> CPU loop; the TPU path is
        covered in test_tpu_crypto.py)."""
        bv = crypto_batch.AdaptiveBatchVerifier()
        msgs = []
        for i in range(3):
            priv = Ed25519PrivKey(bytes([i]) * 32)
            msg = b"ed-%d" % i
            bv.add(priv.pub_key(), msg, priv.sign(msg))
        for i in range(3):
            priv = sr.Sr25519PrivKey(bytes([0x40 + i]) * 32)
            msg = b"sr-%d" % i
            bv.add(priv.pub_key(), msg, priv.sign(msg))
        ok, bitmap = bv.verify()
        assert ok and all(bitmap) and len(bitmap) == 6

    def test_mixed_batch_pinpoints_bad_sig(self):
        bv = crypto_batch.AdaptiveBatchVerifier()
        ed = Ed25519PrivKey(b"\x01" * 32)
        srk = sr.Sr25519PrivKey(b"\x02" * 32)
        bv.add(ed.pub_key(), b"a", ed.sign(b"a"))
        bv.add(srk.pub_key(), b"b", srk.sign(b"WRONG"))
        bv.add(srk.pub_key(), b"c", srk.sign(b"c"))
        ok, bitmap = bv.verify()
        assert not ok
        assert bitmap == [True, False, True]


class TestMixedCommit:
    @pytest.mark.asyncio
    async def test_verify_commit_mixed_keys(self):
        """A validator set mixing ed25519 and sr25519 keys passes
        verify_commit (reference: verifyCommitBatch over the sr25519
        BatchVerifier, crypto/sr25519/batch.go:14-46)."""
        from tendermint_tpu.testing import make_block_id
        from tendermint_tpu.types.canonical import vote_sign_bytes
        from tendermint_tpu.types.block import Commit, CommitSig
        from tendermint_tpu.types.keys import SignedMsgType
        from tendermint_tpu.types.validation import (
            verify_commit,
            verify_commit_light,
        )
        from tendermint_tpu.types.validator_set import Validator, ValidatorSet

        chain_id = "mixed-chain"
        keys = [
            Ed25519PrivKey(b"\x01" * 32),
            sr.Sr25519PrivKey(b"\x02" * 32),
            Ed25519PrivKey(b"\x03" * 32),
            sr.Sr25519PrivKey(b"\x04" * 32),
        ]
        vals = ValidatorSet([Validator(k.pub_key(), 10) for k in keys])
        by_addr = {k.pub_key().address(): k for k in keys}
        bid = make_block_id(b"mixed")
        sigs = []
        for val in vals.validators:
            sb = vote_sign_bytes(
                chain_id, SignedMsgType.PRECOMMIT, 5, 0, bid, 1000
            )
            sigs.append(
                CommitSig.for_block(val.address, 1000, by_addr[val.address].sign(sb))
            )
        commit = Commit(5, 0, bid, tuple(sigs))
        verify_commit(chain_id, vals, bid, 5, commit)
        verify_commit_light(chain_id, vals, bid, 5, commit)

        # a tampered sr25519 signature must fail verification
        bad_sigs = list(sigs)
        tampered = bytearray(sigs[1].signature)
        tampered[2] ^= 1
        bad_sigs[1] = CommitSig.for_block(
            vals.validators[1].address, 1000, bytes(tampered)
        )
        with pytest.raises(Exception):
            verify_commit(chain_id, vals, bid, 5, Commit(5, 0, bid, tuple(bad_sigs)))
