"""Model-based light-client conformance: replay the reference's
TLA+-derived verification traces (public test data shipped at
/root/reference/light/mbt/json/, driver shape from
/root/reference/light/mbt/driver_test.go:18) against our verifier.

Every trace carries reference-produced headers, validator sets, and REAL
ed25519 signatures — passing them end-to-end proves, cross-implementation:
  * header hashing (commit.block_id.hash == header.hash())
  * validator-set hashing (header.validators_hash == vals.hash())
  * canonical vote sign-bytes (the signatures verify)
  * the skipping-verification trust calculus (the verdicts match)
"""

from __future__ import annotations

import base64
import datetime
import glob
import json
import os

import pytest

from tendermint_tpu.crypto.ed25519 import Ed25519PubKey
from tendermint_tpu.light import verifier
from tendermint_tpu.light.types import LightBlock, SignedHeader
from tendermint_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
)
from tendermint_tpu.types.validator_set import Validator, ValidatorSet

MBT_DIR = "/root/reference/light/mbt/json"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(MBT_DIR), reason="reference MBT traces not present"
)


def _parse_time_ns(s: str) -> int:
    """RFC3339 with optional fractional seconds -> unix ns."""
    if "." in s:
        base, rest = s.split(".")
        ns = int(rest.rstrip("Z").ljust(9, "0")[:9])
    else:
        base, ns = s.rstrip("Z"), 0
    dt = datetime.datetime.fromisoformat(base).replace(
        tzinfo=datetime.timezone.utc
    )
    return int(dt.timestamp()) * 10**9 + ns


def _hx(v) -> bytes:
    return bytes.fromhex(v) if v else b""


def _parse_header(h: dict) -> Header:
    lbi = h.get("last_block_id")
    if lbi:
        last_bid = BlockID(
            _hx(lbi.get("hash")),
            PartSetHeader(
                int(lbi.get("parts", {}).get("total", 0) or 0),
                _hx(lbi.get("parts", {}).get("hash")),
            ),
        )
    else:
        last_bid = BlockID()
    return Header(
        chain_id=h["chain_id"],
        height=int(h["height"]),
        time_ns=_parse_time_ns(h["time"]),
        last_block_id=last_bid,
        last_commit_hash=_hx(h.get("last_commit_hash")),
        data_hash=_hx(h.get("data_hash")),
        validators_hash=_hx(h["validators_hash"]),
        next_validators_hash=_hx(h["next_validators_hash"]),
        consensus_hash=_hx(h.get("consensus_hash")),
        app_hash=_hx(h.get("app_hash")),
        last_results_hash=_hx(h.get("last_results_hash")),
        evidence_hash=_hx(h.get("evidence_hash")),
        proposer_address=_hx(h["proposer_address"]),
        version=int(h["version"]["block"]),
    )


def _parse_commit(c: dict) -> Commit:
    bid = BlockID(
        _hx(c["block_id"]["hash"]),
        PartSetHeader(
            int(c["block_id"]["parts"]["total"]),
            _hx(c["block_id"]["parts"]["hash"]),
        ),
    )
    sigs = []
    for s in c["signatures"] or []:
        flag = int(s["block_id_flag"])
        addr = _hx(s.get("validator_address"))
        ts = _parse_time_ns(s["timestamp"]) if s.get("timestamp") else 0
        sig = base64.b64decode(s["signature"]) if s.get("signature") else b""
        sigs.append(CommitSig(flag, addr, ts, sig))
    return Commit(int(c["height"]), int(c["round"]), bid, tuple(sigs))


def _parse_valset(v: dict) -> ValidatorSet:
    vals = []
    for val in v["validators"]:
        assert val["pub_key"]["type"] == "tendermint/PubKeyEd25519"
        pk = Ed25519PubKey(base64.b64decode(val["pub_key"]["value"]))
        vals.append(Validator(pk, int(val["voting_power"])))
    return ValidatorSet(vals)


def _parse_signed_header(sh: dict) -> SignedHeader:
    return SignedHeader(_parse_header(sh["header"]), _parse_commit(sh["commit"]))


def _trace_files():
    return sorted(glob.glob(os.path.join(MBT_DIR, "*.json")))


@pytest.mark.parametrize(
    "path", _trace_files(), ids=[os.path.basename(p) for p in _trace_files()]
)
def test_mbt_trace(path):
    with open(path) as f:
        tc = json.load(f)

    chain_id = tc["initial"]["signed_header"]["header"]["chain_id"]
    # the trusted state pairs the signed header with its NEXT validator
    # set — the set the reference's Verify() anchors trust on
    trusted = LightBlock(
        _parse_signed_header(tc["initial"]["signed_header"]),
        _parse_valset(tc["initial"]["next_validator_set"]),
    )
    trusting_period_ns = int(tc["initial"]["trusting_period"])

    for step in tc["input"]:
        untrusted = LightBlock(
            _parse_signed_header(step["block"]["signed_header"]),
            _parse_valset(step["block"]["validator_set"]),
        )
        now_ns = _parse_time_ns(step["now"])
        err: Exception | None = None
        try:
            verifier.verify(
                chain_id,
                trusted,
                untrusted,
                trusting_period_ns,
                now_ns,
                max_clock_drift_ns=1_000_000_000,  # driver_test.go uses 1s
            )
        except (verifier.VerificationError, ValueError) as e:
            err = e

        verdict = step["verdict"]
        if verdict == "SUCCESS":
            assert err is None, f"{path}: expected SUCCESS, got {err!r}"
        elif verdict == "NOT_ENOUGH_TRUST":
            assert isinstance(err, verifier.ErrNewValSetCantBeTrusted), (
                f"{path}: expected NOT_ENOUGH_TRUST, got {err!r}"
            )
        elif verdict == "INVALID":
            assert err is not None and not isinstance(
                err, verifier.ErrNewValSetCantBeTrusted
            ), f"{path}: expected INVALID, got {err!r}"
        else:
            pytest.fail(f"unknown verdict {verdict!r}")

        if err is None:  # advance the trusted state as the driver does
            trusted = LightBlock(
                untrusted.signed_header,
                _parse_valset(step["block"]["next_validator_set"]),
            )
