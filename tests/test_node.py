"""Full-node integration tests: N nodes over the in-memory transport with
real reactor gossip (no test shortcuts) — the analog of the reference's
reactor tests over p2ptest.Network plus blocksync reactor tests."""

import asyncio

import pytest

from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.config import ConsensusConfig
from tendermint_tpu.consensus.harness import fast_config, make_genesis
from tendermint_tpu.node import Node, NodeConfig
from tendermint_tpu.p2p.memory import MemoryNetwork
from tendermint_tpu.p2p.types import NodeAddress, node_id_from_pubkey
from tendermint_tpu.privval import MockPV


class NodeNet:
    """N full nodes over one MemoryNetwork. Validators are the first
    n_vals nodes; extra nodes are non-validator full nodes."""

    def __init__(self, n_vals: int, n_full: int = 0):
        self.genesis, self.keys = make_genesis(n_vals)
        self.memory = MemoryNetwork()
        self.nodes: list[Node] = []
        for i in range(n_vals + n_full):
            key = self.keys[i] if i < n_vals else None
            self.nodes.append(self._make_node(i, key))

    def _make_node(self, i: int, val_key) -> Node:
        from tendermint_tpu.crypto import ed25519

        node_key = ed25519.Ed25519PrivKey(bytes([0x40 + i]) * 32)
        transport = self.memory.create_transport(
            node_id_from_pubkey(node_key.pub_key())
        )
        app = KVStoreApp()
        node = Node(
            NodeConfig(consensus=fast_config(), moniker=f"n{i}"),
            self.genesis,
            app,
            node_key,
            [transport],
            priv_validator=MockPV(val_key) if val_key is not None else None,
        )
        node.app = app  # test hook
        return node

    async def start(self, *, connect: bool = True) -> None:
        for n in self.nodes:
            await n.start()
        if connect:
            self.connect_all()

    def connect_all(self) -> None:
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1 :]:
                a.peer_manager.add_address(
                    NodeAddress(node_id=b.node_id, protocol="memory")
                )

    async def stop(self) -> None:
        await asyncio.gather(*(n.stop() for n in self.nodes), return_exceptions=True)

    async def wait_for_height(self, h: int, timeout: float = 60.0) -> None:
        await asyncio.gather(*(n.wait_for_height(h, timeout) for n in self.nodes))


class TestFullNodeNetwork:
    @pytest.mark.asyncio
    async def test_four_validators_gossip_consensus(self):
        """4 validators reach consensus purely through reactor gossip."""
        net = NodeNet(4)
        await net.start()
        try:
            await net.wait_for_height(3, timeout=60)
            hashes = {n.block_store.load_block(2).hash() for n in net.nodes}
            assert len(hashes) == 1
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_tx_gossip_and_commit(self):
        """A tx submitted to one node's mempool is gossiped and committed
        network-wide."""
        net = NodeNet(3)
        await net.start()
        try:
            await net.wait_for_height(1, timeout=60)
            await net.nodes[2].mempool.check_tx(b"mercury=planet")
            deadline = asyncio.get_running_loop().time() + 30
            found = False
            while not found:
                assert asyncio.get_running_loop().time() < deadline, "tx never committed"
                for h in range(1, net.nodes[0].block_store.height() + 1):
                    blk = net.nodes[0].block_store.load_block(h)
                    if blk and b"mercury=planet" in blk.txs:
                        found = True
                await asyncio.sleep(0.1)
            # every node's app executed it
            from tendermint_tpu.abci import types as abci

            await net.wait_for_height(net.nodes[0].block_store.height(), 30)
            for node in net.nodes:
                res = node.app.query(abci.RequestQuery(data=b"mercury"))
                assert res.value == b"planet"
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_late_joiner_blocksyncs(self):
        """A node that joins after the chain has advanced catches up via
        the range-batched blocksync pipeline, then participates."""
        net = NodeNet(3, n_full=0)
        await net.start()
        try:
            await net.wait_for_height(5, timeout=60)
            # late full node joins
            late = net._make_node(7, None)
            net.nodes.append(late)
            await late.start()
            for peer in net.nodes[:3]:
                late.peer_manager.add_address(
                    NodeAddress(node_id=peer.node_id, protocol="memory")
                )
            await late.wait_for_height(4, timeout=60)
            assert late.blocksync_reactor.metrics["blocks_applied"] >= 1
            assert late.blocksync_reactor.metrics["sigs_verified"] > 0
            # identical chain
            b3 = late.block_store.load_block(3)
            assert b3.hash() == net.nodes[0].block_store.load_block(3).hash()
        finally:
            await net.stop()


class TestNodeWatchdog:
    @pytest.mark.asyncio
    async def test_watchdog_wired_and_clean_shutdown(self, tmp_path):
        """watchdog_dir config starts the loop watchdog with the node and
        stops it on shutdown without wedging the stop path itself."""
        net = NodeNet(1)
        node = net.nodes[0]
        node.config.watchdog_dir = str(tmp_path / "wd")
        node.config.watchdog_threshold_s = 30.0  # never fires in-test
        await node.start()
        try:
            assert node.watchdog is not None
            assert node.watchdog._thread.is_alive()
        finally:
            await node.stop()
        assert not node.watchdog._thread.is_alive()
        assert node.watchdog.reports == []
