"""TxIngress — the staged tx-admission front door (mempool/ingress.py)
plus the satellites that ride with it: the PriorityMempool admission
race fix, batched post-commit recheck, gossip no-echo/fan-out, the
drop-on-full event fan-out, and the RPC busy mapping.

Covers the ISSUE 7 acceptance points: priority eviction under a full
pool mid-flood, nonce-gap park/expiry (on a frozen ManualClock),
duplicate handling across lanes, recheck-after-commit priority updates,
trace-span tiling of the admission path, and a same-seed flood through
a live (threaded) VerifyHub asserting bit-identical admitted-tx order.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.application import BaseApplication
from tendermint_tpu.abci.client import LocalClient
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.crypto import verify_hub as vh
from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
from tendermint_tpu.libs import trace
from tendermint_tpu.libs.clock import ManualClock
from tendermint_tpu.libs.pubsub import PubSub, Query
from tendermint_tpu.mempool.ingress import (
    IngressBusyError,
    TxEnvelope,
    TxIngress,
    decode_envelope,
    encode_envelope,
    make_signed_tx,
)
from tendermint_tpu.mempool.pool import (
    PriorityMempool,
    TxInCacheError,
    TxRejectedError,
)


class PrioApp(BaseApplication):
    """Priority = leading integer of `N:payload` txs (0 otherwise, and
    for envelope txs); rejects txs containing b'bad'; on RECHECK,
    rejects txs containing b'stale' and re-prices `N:reprice*` txs to
    priority 100."""

    def check_tx(self, req):
        if b"bad" in req.tx:
            return abci.ResponseCheckTx(code=1, log="bad tx")
        if req.type == abci.CheckTxType.RECHECK and b"stale" in req.tx:
            return abci.ResponseCheckTx(code=2, log="stale")
        if req.type == abci.CheckTxType.RECHECK and b"reprice" in req.tx:
            return abci.ResponseCheckTx(priority=100, gas_wanted=1)
        try:
            prio = int(req.tx.split(b":")[0])
        except ValueError:
            prio = 0
        return abci.ResponseCheckTx(priority=prio, gas_wanted=1)


def make_pool(**cfg) -> PriorityMempool:
    return PriorityMempool(MempoolConfig(**cfg), LocalClient(PrioApp()))


async def make_ingress(pool=None, clock=None, **knobs):
    pool = pool or make_pool()
    cfg = pool.config.ingress
    for k, v in knobs.items():
        setattr(cfg, k, v)
    ing = TxIngress(cfg, pool, clock=clock)
    await ing.start()
    return ing, pool


# ---------------------------------------------------------------------------
# envelope codec


def test_envelope_roundtrip_and_bare_passthrough():
    k = Ed25519PrivKey.generate()
    tx = make_signed_tx(k, 7, b"payload")
    env = decode_envelope(tx)
    assert env is not None
    assert env.nonce == 7 and env.payload == b"payload"
    assert env.key_type == k.TYPE and env.pub_key_bytes == k.pub_key().bytes()
    assert env.pub_key().verify_signature(env.sign_bytes(), env.signature)
    # re-encode is byte-identical (deterministic field order)
    assert encode_envelope(env) == tx
    # bare txs pass through as None
    assert decode_envelope(b"k=v") is None


def test_envelope_malformed_raises():
    k = Ed25519PrivKey.generate()
    tx = make_signed_tx(k, 0, b"p")
    with pytest.raises(ValueError):
        decode_envelope(tx[:10])  # truncated body
    with pytest.raises(ValueError):
        # prefix present, garbage body
        decode_envelope(b"stx1" + b"\xff\xff\xff")
    # missing signature field
    env = TxEnvelope(k.TYPE, k.pub_key().bytes(), 0, b"p", b"")
    with pytest.raises(ValueError):
        decode_envelope(encode_envelope(env))


# ---------------------------------------------------------------------------
# admission pipeline basics


class TestAdmission:
    @pytest.mark.asyncio
    async def test_bare_and_envelope_admission(self):
        ing, pool = await make_ingress()
        try:
            await ing.submit_nowait(b"5:a")
            k = Ed25519PrivKey.generate()
            await ing.submit_nowait(make_signed_tx(k, 0, b"p0"))
            assert pool.size() == 2
            assert ing.stats["submitted"] == 2
            assert pool.stats["admitted"] == 2
            assert ing.occupancy == 0
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_bad_signature_rejected_before_checktx(self):
        ing, pool = await make_ingress()
        try:
            k = Ed25519PrivKey.generate()
            tx = make_signed_tx(k, 0, b"p0")
            tx = tx[:-1] + bytes([tx[-1] ^ 1])
            with pytest.raises(TxRejectedError):
                await ing.submit_nowait(tx)
            assert ing.stats["sig_failed"] == 1
            assert pool.size() == 0  # never reached the ABCI round-trip
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_app_rejection_and_size_cap(self):
        ing, pool = await make_ingress()
        try:
            with pytest.raises(TxRejectedError):
                await ing.submit_nowait(b"1:bad")
            with pytest.raises(TxRejectedError):
                await ing.submit_nowait(b"1:" + b"x" * pool.config.max_tx_bytes)
            assert pool.size() == 0
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_duplicate_dedup_before_any_work(self):
        ing, pool = await make_ingress()
        try:
            await ing.submit_nowait(b"5:a")
            with pytest.raises(TxInCacheError):
                await ing.submit_nowait(b"5:a")
            assert ing.stats["dedup_drops"] == 1
            # concurrent duplicate: second joins while first in pipeline
            f1 = ing.submit_nowait(b"6:b", source="peer1")
            f2 = ing.submit_nowait(b"6:b", source="peer2")
            await f1
            with pytest.raises(TxInCacheError):
                await f2
            # the extra gossip source was recorded on the admitted tx:
            # gossip will never echo the tx back to either peer
            import tendermint_tpu.crypto.hashes as hashes

            wtx = pool._txs[hashes.sha256(b"6:b")]
            assert wtx.peers == {"peer1", "peer2"}
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_committed_tx_dedup_at_stage_zero(self):
        """A gossip echo of a committed tx is dropped at submit — before
        it costs a pipeline slot or a signature verify — even when the
        mempool tx cache has churned the entry out."""
        pool = make_pool(cache_size=2)
        ing, pool = await make_ingress(pool)
        try:
            await ing.submit_nowait(b"5:committed")
            async with pool.lock():
                await pool.update(
                    2, [b"5:committed"], [abci.ResponseDeliverTx()], recheck=False
                )
            # churn the LRU tx cache so only the committed LRU remembers
            await ing.submit_nowait(b"1:churn-a")
            await ing.submit_nowait(b"1:churn-b")
            assert not pool.cache.has(b"5:committed")
            before = ing.stats["submitted"]
            with pytest.raises(TxInCacheError, match="committed"):
                await ing.submit_nowait(b"5:committed")
            assert ing.stats["submitted"] == before  # no slot consumed
            assert ing.stats["dedup_drops"] >= 1
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_backpressure_sheds_never_buffers(self):
        """A full pipeline rejects-with-busy synchronously; occupancy
        stays bounded by depth (the never-unbounded-buffering edge)."""
        pool = make_pool()
        ing, pool = await make_ingress(pool, depth=4, verify_workers=1)
        try:
            # hold the releaser hostage: replace the pool's app client
            # with one that parks until released
            gate = asyncio.Event()
            real = pool.app

            class Gate:
                async def check_tx(self, req):
                    await gate.wait()
                    return await real.check_tx(req)

            pool.app = Gate()
            futs = [ing.submit_nowait(b"1:tx%d" % i) for i in range(4)]
            assert ing.occupancy == 4
            with pytest.raises(IngressBusyError):
                await ing.submit_nowait(b"1:overflow")
            assert ing.stats["shed"] == 1
            assert ing.occupancy == 4  # the shed tx took no slot
            gate.set()
            await asyncio.gather(*futs)
            assert pool.size() == 4
            # capacity released: the same-bytes tx is now a cache dup,
            # a fresh one admits
            await ing.submit_nowait(b"1:after")
            assert pool.size() == 5
        finally:
            await ing.stop()


# ---------------------------------------------------------------------------
# nonce lanes


class TestNonceLanes:
    @pytest.mark.asyncio
    async def test_out_of_order_parks_then_drains(self):
        clock = ManualClock()
        ing, pool = await make_ingress(clock=clock)
        try:
            k = Ed25519PrivKey.generate()
            f2 = ing.submit_nowait(make_signed_tx(k, 2, b"p2"))
            f1 = ing.submit_nowait(make_signed_tx(k, 1, b"p1"))
            await asyncio.sleep(0.05)
            # fresh lane: both park (nonce 0 never seen)
            assert ing.parked_count() == 2
            assert pool.size() == 0
            f0 = ing.submit_nowait(make_signed_tx(k, 0, b"p0"))
            await asyncio.gather(f0, f1, f2)
            assert pool.size() == 3
            # admitted in nonce order despite reversed arrival
            order = [w.tx for w in sorted(pool._txs.values(), key=lambda w: w.seq)]
            assert [decode_envelope(t).nonce for t in order] == [0, 1, 2]
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_stale_nonce_rejected(self):
        ing, pool = await make_ingress()
        try:
            k = Ed25519PrivKey.generate()
            await ing.submit_nowait(make_signed_tx(k, 0, b"p0"))
            await ing.submit_nowait(make_signed_tx(k, 1, b"p1"))
            with pytest.raises(TxRejectedError, match="stale nonce"):
                await ing.submit_nowait(make_signed_tx(k, 0, b"again"))
            assert ing.stats["stale_nonce"] == 1
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_duplicate_nonce_across_payloads_parks_once(self):
        """Two different txs claiming the same (sender, nonce): the
        first parks, the second is rejected as a dup of the parked slot;
        after the gap fills only the first admits."""
        ing, pool = await make_ingress()
        try:
            k = Ed25519PrivKey.generate()
            f2a = ing.submit_nowait(make_signed_tx(k, 2, b"first"))
            await asyncio.sleep(0.02)
            with pytest.raises(TxRejectedError, match="already parked"):
                await ing.submit_nowait(make_signed_tx(k, 2, b"second"))
            await ing.submit_nowait(make_signed_tx(k, 0, b"p0"))
            await ing.submit_nowait(make_signed_tx(k, 1, b"p1"))
            await f2a
            assert pool.size() == 3
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_lane_depth_bound(self):
        ing, pool = await make_ingress(nonce_lane_depth=2)
        try:
            k = Ed25519PrivKey.generate()
            ing.submit_nowait(make_signed_tx(k, 0, b"p0"))  # establishes lane
            await asyncio.sleep(0.02)
            ing.submit_nowait(make_signed_tx(k, 5, b"p5"))
            ing.submit_nowait(make_signed_tx(k, 6, b"p6"))
            await asyncio.sleep(0.05)
            assert ing.parked_count() == 2
            with pytest.raises(IngressBusyError, match="lane full"):
                await ing.submit_nowait(make_signed_tx(k, 7, b"p7"))
            assert ing.stats["lane_full"] == 1
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_global_park_capacity_bound(self):
        """Fresh-sender floods must not sidestep the depth bound through
        the parked set: total parked txs across ALL lanes is capped at
        `depth` (shed busy beyond), so the ingress holds at most depth
        in flight plus depth parked."""
        ing, pool = await make_ingress(depth=3, nonce_lane_depth=8)
        try:
            futs = []
            for i in range(3):  # 3 distinct senders, all gap-parked
                k = Ed25519PrivKey(bytes([0x10 + i]) * 32)
                futs.append(ing.submit_nowait(make_signed_tx(k, 5, b"gap")))
            await asyncio.sleep(0.05)
            assert ing.parked_count() == 3
            k = Ed25519PrivKey(bytes([0x7F]) * 32)
            with pytest.raises(IngressBusyError, match="park capacity"):
                await ing.submit_nowait(make_signed_tx(k, 5, b"over"))
            assert ing.stats["shed"] == 1
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_gap_park_expires_on_injected_clock(self):
        clock = ManualClock()
        ing, pool = await make_ingress(clock=clock, nonce_park_timeout_ms=1000.0)
        try:
            k = Ed25519PrivKey.generate()
            await ing.submit_nowait(make_signed_tx(k, 0, b"p0"))
            f5 = ing.submit_nowait(make_signed_tx(k, 5, b"p5"))
            await asyncio.sleep(0.05)
            assert ing.parked_count() == 1
            # frozen clock: nothing expires no matter how long we wait
            await asyncio.sleep(0.15)
            assert ing.parked_count() == 1 and not f5.done()
            clock.advance(2_000_000_000)  # 2s > 1s park timeout
            await ing.submit_nowait(b"1:tick")  # release path runs expiry
            with pytest.raises(TxRejectedError, match="gap timed out"):
                await f5
            assert ing.stats["park_expired"] == 1
            # the lane watermark did NOT advance past the gap
            with pytest.raises(TxRejectedError, match="stale nonce"):
                await ing.submit_nowait(make_signed_tx(k, 0, b"re"))
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_fresh_lane_adopts_lowest_parked_on_timeout(self):
        """A sender whose txs start above nonce 0 (or whose nonce-0 was
        lost in transit): the lane parks, then adopts the lowest parked
        nonce as its start when the park times out, instead of wedging
        the sender forever."""
        clock = ManualClock()
        ing, pool = await make_ingress(clock=clock)
        try:
            k = Ed25519PrivKey.generate()
            f5 = ing.submit_nowait(make_signed_tx(k, 5, b"p5"))
            f6 = ing.submit_nowait(make_signed_tx(k, 6, b"p6"))
            await asyncio.sleep(0.05)
            assert ing.parked_count() == 2
            clock.advance(5_000_000_000)
            await ing.submit_nowait(b"1:tick")
            await asyncio.gather(f5, f6)
            assert pool.size() == 3
            assert ing.stats["park_adopted"] == 1
            # watermark adopted at 7 now
            with pytest.raises(TxRejectedError, match="stale nonce"):
                await ing.submit_nowait(make_signed_tx(k, 5, b"re"))
        finally:
            await ing.stop()


# ---------------------------------------------------------------------------
# pool satellites: eviction mid-flood, admission race, batched recheck


class TestPoolUnderFlood:
    @pytest.mark.asyncio
    async def test_priority_eviction_under_full_pool_mid_flood(self):
        pool = make_pool(size=8)
        ing, pool = await make_ingress(pool)
        try:
            errs = 0
            for i in range(100):
                try:
                    await ing.submit_nowait(b"%d:flood" % i)
                except ValueError:
                    errs += 1
            assert pool.size() == 8
            # the 8 highest-priority txs survived the flood
            kept = sorted(int(w.tx.split(b":")[0]) for w in pool._txs.values())
            assert kept == list(range(92, 100))
            assert pool.stats["evicted"] == 92
            assert pool.stats["admitted"] == 100
            assert errs == 0  # eviction, not rejection, for ascending prio
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_admission_race_cannot_resurrect_committed_tx(self):
        """The satellite race fix: a commit-time update() interleaving
        with an in-flight CheckTx must not let the admission re-insert
        the just-committed tx or corrupt _bytes accounting."""
        gate = asyncio.Event()
        reached = asyncio.Event()

        class RaceApp(PrioApp):
            async def slow(self, req):
                reached.set()
                await gate.wait()
                return abci.ResponseCheckTx(priority=1, gas_wanted=1)

        pool = make_pool()
        real = pool.app

        class GateClient:
            def __init__(self):
                self.app = RaceApp()

            async def check_tx(self, req):
                if req.tx == b"1:racer":
                    return await self.app.slow(req)
                return await real.check_tx(req)

        pool.app = GateClient()
        task = asyncio.get_running_loop().create_task(pool.check_tx(b"1:racer"))
        await asyncio.wait_for(reached.wait(), 2.0)
        # the block executor commits the same tx while CheckTx is in
        # flight (it holds the pool lock across update, as execution.py
        # does)
        async with pool.lock():
            await pool.update(2, [b"1:racer"], [abci.ResponseDeliverTx()], recheck=False)
        gate.set()
        with pytest.raises(TxInCacheError, match="committed during admission"):
            await task
        assert pool.size() == 0
        assert pool.size_bytes() == 0  # no double-count from the race
        # and a later resubmission is still a committed-cache rejection
        with pytest.raises(TxInCacheError):
            await pool.check_tx(b"1:racer")

    @pytest.mark.asyncio
    async def test_batched_recheck_matches_sequential_and_reprices(self):
        """Post-commit recheck in concurrent slices: the surviving set
        and the updated priorities are identical whatever the batch
        width (1 == sequential semantics)."""
        results = {}
        for width in (1, 3, 64):
            pool = make_pool(recheck_batch=width)
            await pool.check_tx(b"5:keep")
            await pool.check_tx(b"4:stale-soon")
            await pool.check_tx(b"3:reprice-me")
            await pool.check_tx(b"2:gone")
            async with pool.lock():
                await pool.update(2, [b"2:gone"], [abci.ResponseDeliverTx()])
            results[width] = pool.reap_max_txs(-1)
            assert pool.stats["recheck_failed"] == 1  # stale-soon dropped
        # reprice-me jumped to priority 100 on recheck in every width
        assert results[1] == results[3] == results[64]
        assert results[1][0] == b"3:reprice-me"


# ---------------------------------------------------------------------------
# stage-B release slices (checktx_batch: the _recheck shape at admission)


class TestStageBSlices:
    @pytest.mark.asyncio
    async def test_slice_widths_agree_with_serial(self):
        """checktx_batch > 1 prefetches the slice's CheckTx calls
        concurrently but admits strictly in release order: the admitted
        set AND order are identical to width 1 (today's serial
        semantics) for any width, including with rejections and
        duplicates mixed in."""
        results = {}
        for width in (1, 4, 64):
            ing, pool = await make_ingress(checktx_batch=width)
            try:
                assert ing.checktx_batch == width
                k = Ed25519PrivKey(b"\x09" * 32)
                txs = [b"5:a", b"3:bad-tx", b"7:c", b"5:a", b"2:d"]
                txs += [make_signed_tx(k, n, b"e-%d" % n) for n in range(3)]
                futs = [ing.submit_nowait(tx) for tx in txs]
                outcomes = []
                for f in futs:
                    try:
                        await f
                        outcomes.append("ok")
                    except ValueError as e:
                        outcomes.append(type(e).__name__)
                results[width] = (
                    outcomes,
                    [w.tx for w in sorted(pool._txs.values(), key=lambda w: w.seq)],
                )
            finally:
                await ing.stop()
        assert results[1] == results[4] == results[64]
        outcomes, admitted = results[1]
        assert outcomes.count("ok") == len(admitted) == 6
        assert "TxRejectedError" in outcomes and "TxInCacheError" in outcomes

    @pytest.mark.asyncio
    async def test_parked_entry_drops_slice_prefetch(self):
        """A nonce-gap park can admit whole blocks later: its
        slice-prefetched CheckTx verdict must NOT be consumed at drain
        time (stale by design) — the drain path re-issues."""
        calls = []

        class CountingApp(PrioApp):
            def check_tx(self, req):
                calls.append(bytes(req.tx))
                return super().check_tx(req)

        pool = PriorityMempool(MempoolConfig(), LocalClient(CountingApp()))
        ing, pool = await make_ingress(pool=pool, checktx_batch=8)
        try:
            k = Ed25519PrivKey(b"\x0a" * 32)
            gap = make_signed_tx(k, 1, b"later")
            first = make_signed_tx(k, 0, b"first")
            f_gap = ing.submit_nowait(gap)
            f_first = ing.submit_nowait(first)
            await f_first
            await f_gap  # drained behind nonce 0
            assert pool.size() == 2
            # nonce 1 was prefetched in a slice, parked (prefetch
            # dropped), then re-CheckTx'd at drain: if both entries rode
            # one slice, `gap` appears twice in the app call log
            assert calls.count(gap) >= 1 and calls.count(first) >= 1
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_prefetch_failure_degrades_to_inline(self):
        """A prefetch RTT failure leaves the entry without a stashed
        verdict; the serial path re-issues inline and admission
        proceeds — the prefetch is a latency optimization, never a
        correctness gate."""
        state = {"fail": True}

        class FlakyApp(PrioApp):
            def check_tx(self, req):
                if state["fail"]:
                    state["fail"] = False
                    raise RuntimeError("transient app hiccup")
                return super().check_tx(req)

        pool = PriorityMempool(MempoolConfig(), LocalClient(FlakyApp()))
        ing, pool = await make_ingress(pool=pool, checktx_batch=4)
        try:
            futs = [ing.submit_nowait(b"5:x"), ing.submit_nowait(b"6:y")]
            outcomes = []
            for f in futs:
                try:
                    await f
                    outcomes.append("ok")
                except ValueError:
                    outcomes.append("rejected")
            # at most one tx can have been hit by the single transient
            # failure, and nothing wedged the releaser
            assert outcomes.count("ok") >= 1
            assert pool.size() == outcomes.count("ok")
            await ing.submit_nowait(b"7:z")
            assert pool.size() == outcomes.count("ok") + 1
        finally:
            await ing.stop()


# ---------------------------------------------------------------------------
# determinism: same-seed flood through a live (threaded) hub


class TestDeterminism:
    @pytest.mark.asyncio
    async def test_same_seed_flood_bit_identical_admitted_order(self):
        """The reorder buffer restores strict arrival order behind the
        concurrent verify stage: two same-seed floods through a LIVE
        VerifyHub (worker threads interleave nondeterministically)
        admit byte-identical tx sequences."""

        async def run_flood(seed: int) -> list[bytes]:
            rng = random.Random(seed)
            keys = [Ed25519PrivKey(bytes([i + 1]) * 32) for i in range(4)]
            txs = []
            for ci, k in enumerate(keys):
                for nonce in range(6):
                    txs.append(make_signed_tx(k, nonce, b"d-%d-%d" % (ci, nonce)))
            txs += [b"%d:bare-%d" % (rng.randrange(9), i) for i in range(8)]
            rng.shuffle(txs)
            hub = vh.acquire_hub(max_batch=64, window_ms=1.0, cache_size=0)
            try:
                ing, pool = await make_ingress(verify_workers=4)
                try:
                    futs = [ing.submit_nowait(tx) for tx in txs]
                    for f in futs:
                        try:
                            await f
                        except ValueError:
                            pass
                    return [
                        w.tx
                        for w in sorted(pool._txs.values(), key=lambda w: w.seq)
                    ]
                finally:
                    await ing.stop()
            finally:
                vh.release_hub()

        a = await run_flood(1234)
        b = await run_flood(1234)
        assert a == b and len(a) > 0


# ---------------------------------------------------------------------------
# trace spans tile the admission path


class TestTracing:
    @pytest.mark.asyncio
    async def test_ingress_spans_tile_admit_exactly(self):
        old = trace.RECORDER.enabled
        trace.RECORDER.enabled = True
        trace.RECORDER.clear()
        try:
            ing, pool = await make_ingress()
            try:
                k = Ed25519PrivKey.generate()
                await ing.submit_nowait(make_signed_tx(k, 0, b"traced"))
            finally:
                await ing.stop()
        finally:
            trace.RECORDER.enabled = old
        spans = [
            s
            for s in trace.RECORDER.dump(subsystem="mempool.ingress")
        ]
        by_name = {s["name"]: s for s in spans}
        stages = ["intake", "verify", "nonce_lane", "checktx", "insert"]
        assert set(by_name) == set(stages) | {"admit"}
        root = by_name["admit"]
        assert root["attrs"]["outcome"] == "admitted"
        # stages share boundaries: each starts where the previous ended
        prev_end = root["start_s"]
        for name in stages:
            s = by_name[name]
            assert s["trace_id"] == root["trace_id"]
            assert abs(s["start_s"] - prev_end) < 2e-5
            prev_end = s["start_s"] + s["duration_ms"] / 1e3
        # ... and tile the root exactly
        assert abs(prev_end - (root["start_s"] + root["duration_ms"] / 1e3)) < 2e-5
        stage_sum = sum(by_name[n]["duration_ms"] for n in stages)
        assert abs(stage_sum - root["duration_ms"]) < 2e-2  # ms


# ---------------------------------------------------------------------------
# event fan-out + RPC busy mapping + gossip fan-out


class TestFanOut:
    @pytest.mark.asyncio
    async def test_drop_on_full_subscription_drops_with_counter(self):
        from tendermint_tpu.libs import pubsub as ps

        bus = PubSub()
        base = ps.DROPPED["events"]
        q = Query.parse("tm.event='Tx'")
        slow = bus.subscribe("slow-ws", q, buffer=2, drop_on_full=True)
        for i in range(5):
            bus.publish({"i": i}, {"tm.event": ["Tx"]})
        # two delivered, three dropped; the subscription survives
        assert slow.dropped == 3
        assert ps.DROPPED["events"] == base + 3
        assert slow.cancelled is None
        assert (await slow.next()).data == {"i": 0}
        # the legacy contract still cancels laggards without the flag
        fast = bus.subscribe("strict-ws", q, buffer=2)
        for i in range(5):
            bus.publish({"i": i}, {"tm.event": ["Tx"]})
        assert fast.cancelled is not None

    @pytest.mark.asyncio
    async def test_rpc_broadcast_maps_busy(self):
        from tendermint_tpu.rpc.core import MEMPOOL_BUSY_CODE, Environment

        pool = make_pool()
        ing, pool = await make_ingress(pool, depth=2, verify_workers=1)
        try:
            gate = asyncio.Event()
            real = pool.app

            class Gate:
                async def check_tx(self, req):
                    await gate.wait()
                    return await real.check_tx(req)

            pool.app = Gate()
            env = Environment(chain_id="t", mempool=pool, ingress=ing)
            asyncio.get_running_loop()  # (env handlers need a loop)
            f1 = asyncio.get_running_loop().create_task(
                env.broadcast_tx_sync(b"1:a".hex())
            )
            f2 = asyncio.get_running_loop().create_task(
                env.broadcast_tx_sync(b"2:b".hex())
            )
            await asyncio.sleep(0.05)
            busy = await env.broadcast_tx_sync(b"3:c".hex())
            assert busy["code"] == MEMPOOL_BUSY_CODE
            assert "busy" in busy["log"]
            gate.set()
            assert (await f1)["code"] == 0
            assert (await f2)["code"] == 0
            # async mode never errors, even shed (fire-and-forget)
            res = await env.broadcast_tx_async(b"4:d".hex())
            assert res["code"] == 0
        finally:
            await ing.stop()

    @pytest.mark.asyncio
    async def test_gossip_never_echoes_to_source_and_caps_fanout(self):
        from types import SimpleNamespace

        from tendermint_tpu.mempool.reactor import MempoolReactor

        pool = make_pool(gossip_fanout=2)
        # the tx arrived from peerA: peers={peerA} at admission
        await pool.check_tx(b"7:gossip", sender="peerA")
        out_q: asyncio.Queue = asyncio.Queue(64)
        reactor = MempoolReactor(
            pool,
            SimpleNamespace(out_q=out_q),
            asyncio.Queue(4),
        )
        peers = ["peerA", "peerB", "peerC", "peerD"]
        tasks = []
        for p in peers:
            reactor._sent[p] = set()
            tasks.append(
                asyncio.get_running_loop().create_task(reactor._broadcast_to(p))
            )
        await asyncio.sleep(0.2)
        for t in tasks:
            t.cancel()
        sent_to = []
        while not out_q.empty():
            env = out_q.get_nowait()
            sent_to.append(env.to)
        # never echoed to its source …
        assert "peerA" not in sent_to
        # … and fan-out capped at 2 of the 3 eligible peers
        assert len(sent_to) == 2
        wtx = next(iter(pool._txs.values()))
        assert wtx.gossiped == 2


# ---------------------------------------------------------------------------
# /metrics exposition


class TestMetrics:
    @pytest.mark.asyncio
    async def test_flood_is_diagnosable_from_metrics_render(self):
        from tendermint_tpu.libs.metrics import NodeMetrics

        import gc

        gc.collect()  # drop earlier tests' pools from the weak registry
        ing, pool = await make_ingress()
        try:
            await ing.submit_nowait(b"5:m1")
            with pytest.raises(TxRejectedError):
                await ing.submit_nowait(b"1:bad")
            text = NodeMetrics().render()
        finally:
            await ing.stop()
        for needle in (
            "tendermint_tpu_mempool_size 1",
            "tendermint_tpu_mempool_bytes 4",
            "tendermint_tpu_mempool_tx_admitted 1",
            "tendermint_tpu_mempool_tx_rejected 1",
            "tendermint_tpu_mempool_tx_shed 0",
            "tendermint_tpu_ingress_submitted 2",
            "tendermint_tpu_ingress_admit_latency_seconds_count 1",
            "tendermint_tpu_pubsub_dropped_events",
        ):
            assert needle in text, needle
