"""Stage-3 tests: ABCI app, stores, block executor, WAL, handshake replay.

Mirrors the reference's internal/state/{execution,validation,store}_test.go
and internal/consensus/{wal,replay}_test.go shapes: build a real chain
against the kvstore app, crash it at different points, and check recovery.
"""

from __future__ import annotations

import asyncio
import os

import pytest

from tendermint_tpu import testing as tt
from tendermint_tpu.abci import types as abci
from tendermint_tpu.abci.kvstore import KVStoreApp
from tendermint_tpu.consensus.replay import Handshaker
from tendermint_tpu.consensus.wal import WAL, KIND_END_HEIGHT
from tendermint_tpu.libs.pubsub import Query
from tendermint_tpu.mempool import Mempool, _NullLock
from tendermint_tpu.proxy import AppConns
from tendermint_tpu.state import BlockExecutor, StateStore, state_from_genesis
from tendermint_tpu.state.validation import BlockValidationError
from tendermint_tpu.store.blockstore import BlockStore
from tendermint_tpu.store.db import MemDB, SQLiteDB
from tendermint_tpu.types.block import BlockID
from tendermint_tpu.types.events import EventBus, query_for_event, EVENT_NEW_BLOCK
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator


class ListMempool(Mempool):
    """Minimal mempool: a FIFO the tests stuff txs into."""

    def __init__(self):
        self.txs: list[bytes] = []

    async def check_tx(self, tx, sender=""):
        self.txs.append(tx)

    def reap_max_bytes_max_gas(self, max_bytes, max_gas):
        return list(self.txs)

    def reap_max_txs(self, max_txs):
        return self.txs[:max_txs]

    def lock(self):
        return _NullLock()

    async def update(self, height, txs, results, *, recheck=True):
        self.txs = [t for t in self.txs if t not in set(txs)]

    def size(self):
        return len(self.txs)

    def size_bytes(self):
        return sum(len(t) for t in self.txs)

    async def flush(self):
        self.txs = []


def make_genesis(n_vals=4, chain_id="exec-chain"):
    vals, keys = tt.make_validator_set(n_vals)
    doc = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(v.pub_key, v.voting_power) for v in vals.validators
        ],
    )
    return doc, vals, keys


class Harness:
    """One in-process node: app + stores + executor (no consensus SM yet —
    commits are forged by signing with all validator keys)."""

    def __init__(self, tmp=None, suffix=""):
        self.doc, self.vals, self.keys = make_genesis()
        if tmp is None:
            self.app_db, self.block_db, self.state_db = MemDB(), MemDB(), MemDB()
        else:
            self.app_db = SQLiteDB(os.path.join(tmp, f"app{suffix}.db"))
            self.block_db = SQLiteDB(os.path.join(tmp, f"blocks{suffix}.db"))
            self.state_db = SQLiteDB(os.path.join(tmp, f"state{suffix}.db"))
        self.reopen()

    def reopen(self):
        self.app = KVStoreApp(self.app_db)
        self.conns = AppConns.local(self.app)
        self.block_store = BlockStore(self.block_db)
        self.state_store = StateStore(self.state_db)
        self.mempool = ListMempool()
        self.event_bus = EventBus()
        self.executor = BlockExecutor(
            self.state_store,
            self.conns.consensus,
            self.mempool,
            block_store=self.block_store,
            event_bus=self.event_bus,
        )

    async def handshake(self):
        state = self.state_store.load()
        if state is None:
            state = state_from_genesis(self.doc)
        hs = Handshaker(self.state_store, state, self.block_store, self.doc)
        return await hs.handshake(self.conns)

    def forge_commit(self, state, block, part_set):
        bid = BlockID(block.hash(), part_set.header)
        return bid, tt.make_commit(
            state.chain_id, block.header.height, 0, bid, self.vals, self.keys
        )

    async def advance(self, state, last_commit, txs=()):
        """Propose + 'decide' + apply one block; returns (state, commit)."""
        for tx in txs:
            await self.mempool.check_tx(tx)
        height = state.last_block_height + 1 if state.last_block_height else state.initial_height
        proposer = state.validators.get_proposer().address
        block, parts = self.executor.create_proposal_block(
            height, state, last_commit, proposer
        )
        bid, commit = self.forge_commit(state, block, parts)
        self.block_store.save_block(block, parts, commit)
        state, _ = await self.executor.apply_block(state, bid, block)
        return state, commit


# ---------------------------------------------------------------------------


def test_kvstore_app_basics():
    app = KVStoreApp()
    assert app.check_tx(abci.RequestCheckTx(b"k=v")).is_ok()
    assert not app.check_tx(abci.RequestCheckTx(b"a=b=c")).is_ok()
    app.begin_block(abci.RequestBeginBlock(b"", None, abci.LastCommitInfo(0)))
    assert app.deliver_tx(abci.RequestDeliverTx(b"name=satoshi")).is_ok()
    app.end_block(abci.RequestEndBlock(1))
    res = app.commit()
    assert res.data
    q = app.query(abci.RequestQuery(data=b"name"))
    assert q.value == b"satoshi"
    assert app.query(abci.RequestQuery(data=b"missing")).code == 1
    # validator tx
    pk = bytes(range(32))
    res = app.check_tx(abci.RequestCheckTx(b"val:" + pk.hex().encode() + b"!5"))
    assert res.is_ok()
    assert not app.check_tx(abci.RequestCheckTx(b"val:zz!5")).is_ok()


def test_chain_advances_and_persists():
    async def run():
        h = Harness()
        state = await h.handshake()
        assert state.last_block_height == 0

        sub = h.event_bus.subscribe("test", query_for_event(EVENT_NEW_BLOCK))
        commit = None
        state, commit = await h.advance(state, commit, [b"a=1", b"b=2"])
        state, commit = await h.advance(state, commit, [b"c=3"])
        state, commit = await h.advance(state, commit)
        assert state.last_block_height == 3
        assert h.block_store.height() == 3

        # app executed the txs
        assert h.app.items[b"a"] == b"1"
        assert h.app.items[b"c"] == b"3"
        # header chains to app state
        b3 = h.block_store.load_block(3)
        assert b3.header.app_hash  # app hash of height 2
        b2 = h.block_store.load_block(2)
        assert b3.header.app_hash != b2.header.app_hash
        assert b3.header.last_block_id.hash == b2.hash()
        # canonical commit for height 2 comes from block 3's LastCommit
        c2 = h.block_store.load_block_commit(2)
        assert c2.block_id.hash == b2.hash()
        # lookup by hash
        assert h.block_store.load_block_by_hash(b2.hash()).header.height == 2
        # state store: validators at each height
        for height in (1, 2, 3):
            vs = h.state_store.load_validators(height)
            assert vs is not None and vs.hash() == h.vals.hash()
        # abci responses persisted
        r1 = h.state_store.load_abci_responses(1)
        assert len(r1.deliver_txs) == 2
        # events fired
        msg = await asyncio.wait_for(sub.next(), 1)
        assert msg.data.block.header.height == 1
        # mempool drained
        assert h.mempool.size() == 0

    asyncio.run(run())


def test_validate_block_rejects_tampering():
    async def run():
        h = Harness()
        state = await h.handshake()
        state, commit = await h.advance(state, None, [b"x=1"])

        proposer = state.validators.get_proposer().address
        block, parts = h.executor.create_proposal_block(2, state, commit, proposer)

        import dataclasses

        bad = dataclasses.replace(
            block, header=dataclasses.replace(block.header, app_hash=b"\x00" * 32)
        )
        with pytest.raises(BlockValidationError):
            h.executor.validate_block(state, bad)

        bad2 = dataclasses.replace(
            block, header=dataclasses.replace(block.header, height=5)
        )
        with pytest.raises(BlockValidationError):
            h.executor.validate_block(state, bad2)

        # good block passes
        h.executor.validate_block(state, block)

    asyncio.run(run())


def test_validator_update_via_tx():
    async def run():
        h = Harness()
        state = await h.handshake()
        new_key = tt.det_priv_keys(1, seed=b"new-validator")[0]
        tx = b"val:" + new_key.pub_key().bytes().hex().encode() + b"!7"
        state, commit = await h.advance(state, None, [tx])
        # joins NextValidators two heights later (validators for h+2)
        assert len(state.next_validators) == 5
        assert len(state.validators) == 4
        state, commit = await h.advance(state, commit)
        assert len(state.validators) == 5
        assert state.last_height_validators_changed == 3

    asyncio.run(run())


def test_handshake_replays_app_behind_store(tmp_path):
    async def run():
        tmp = str(tmp_path)
        h = Harness(tmp)
        state = await h.handshake()
        commit = None
        for i in range(5):
            state, commit = await h.advance(state, commit, [b"k%d=v%d" % (i, i)])
        app_hash = state.app_hash
        h.app_db.close(); h.block_db.close(); h.state_db.close()

        # "crash" with the app's disk wiped → app height 0, store height 5
        os.remove(os.path.join(tmp, "app.db"))
        h2 = Harness(tmp)
        state2 = await h2.handshake()
        assert state2.last_block_height == 5
        assert state2.app_hash == app_hash
        assert h2.app.height == 5
        assert h2.app.items[b"k4"] == b"v4"

    asyncio.run(run())


def test_handshake_applies_tip_block(tmp_path):
    async def run():
        tmp = str(tmp_path)
        h = Harness(tmp)
        state = await h.handshake()
        state, commit = await h.advance(state, None, [b"a=1"])

        # crash between SaveBlock and ApplyBlock: block 2 saved, state at 1
        proposer = state.validators.get_proposer().address
        block, parts = h.executor.create_proposal_block(2, state, commit, proposer)
        bid, c2 = h.forge_commit(state, block, parts)
        h.block_store.save_block(block, parts, c2)
        h.app_db.close(); h.block_db.close(); h.state_db.close()

        h2 = Harness(tmp)
        state2 = await h2.handshake()
        assert state2.last_block_height == 2
        assert h2.app.height == 2
        assert state2.app_hash == h2.app.app_hash

    asyncio.run(run())


def test_kvstore_snapshots():
    app = KVStoreApp()
    for height in range(1, 11):
        app.begin_block(abci.RequestBeginBlock(b"", None, abci.LastCommitInfo(0)))
        app.deliver_tx(abci.RequestDeliverTx(b"h%d=v" % height))
        app.end_block(abci.RequestEndBlock(height))
        app.commit()
    snaps = app.list_snapshots().snapshots
    assert len(snaps) == 1 and snaps[0].height == 10

    app2 = KVStoreApp()
    offer = app2.offer_snapshot(abci.RequestOfferSnapshot(snaps[0], app.app_hash))
    assert offer.result == abci.OfferSnapshotResult.ACCEPT
    for i in range(snaps[0].chunks):
        chunk = app.load_snapshot_chunk(
            abci.RequestLoadSnapshotChunk(snaps[0].height, snaps[0].format, i)
        ).chunk
        res = app2.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(i, chunk))
        assert res.result == abci.ApplySnapshotChunkResult.ACCEPT
    assert app2.app_hash == app.app_hash
    assert app2.items == app.items


# -- WAL --------------------------------------------------------------------


def test_wal_roundtrip_and_end_height(tmp_path):
    wal = WAL(str(tmp_path / "wal"))
    wal.write(b"msg-h1-a", time_ns=1)
    wal.write_sync(b"msg-h1-b", time_ns=2)
    wal.write_end_height(1)
    wal.write(b"msg-h2-a", time_ns=3)
    wal.close()

    wal2 = WAL(str(tmp_path / "wal"))
    recs = list(wal2.iter_records())
    assert [r.data for r in recs if r.kind != KIND_END_HEIGHT] == [
        b"msg-h1-a", b"msg-h1-b", b"msg-h2-a",
    ]
    after = wal2.search_for_end_height(1)
    assert [r.data for r in after] == [b"msg-h2-a"]
    assert wal2.search_for_end_height(7) is None
    # height 0 = start of log
    assert len(wal2.search_for_end_height(0)) == 3
    wal2.close()


def test_wal_truncates_torn_tail(tmp_path):
    wal = WAL(str(tmp_path / "wal"))
    wal.write_sync(b"complete", time_ns=1)
    wal.close()
    # simulate a crash mid-write: append garbage half-frame
    with open(tmp_path / "wal" / "wal", "ab") as f:
        f.write(b"\x01\x02\x03")
    wal2 = WAL(str(tmp_path / "wal"))
    recs = list(wal2.iter_records())
    assert len(recs) == 1 and recs[0].data == b"complete"
    wal2.close()


def test_wal_rotation(tmp_path):
    wal = WAL(str(tmp_path / "wal"), head_size_limit=256)
    for i in range(50):
        wal.write_sync(b"x" * 40, time_ns=i)
    assert len(wal._rotated_files()) > 0
    assert len(list(wal.iter_records())) == 50
    wal.close()


# -- pubsub query DSL -------------------------------------------------------


def test_query_parse_and_match():
    q = Query.parse("tm.event='Tx' AND tx.height>5")
    assert q.matches({"tm.event": ["Tx"], "tx.height": ["7"]})
    assert not q.matches({"tm.event": ["Tx"], "tx.height": ["3"]})
    assert not q.matches({"tm.event": ["NewBlock"], "tx.height": ["7"]})

    q2 = Query.parse("app.key EXISTS")
    assert q2.matches({"app.key": ["anything"]})
    assert not q2.matches({"other": ["x"]})

    q3 = Query.parse("tx.hash CONTAINS 'AB'")
    assert q3.matches({"tx.hash": ["ZZAB12"]})
    assert not q3.matches({"tx.hash": ["zz12"]})

    q4 = Query.parse("tx.height=7")
    assert q4.matches({"tx.height": ["7"]})


def test_validate_block_commit_verified_skips_only_signatures():
    """commit_verified=True (block-sync range batches already proved the
    LastCommit on-device) skips ONLY the signature check: structural
    tampering must still be rejected."""

    async def run():
        import dataclasses

        h = Harness()
        state = await h.handshake()
        state, commit = await h.advance(state, None, [b"x=1"])

        proposer = state.validators.get_proposer().address
        block, parts = h.executor.create_proposal_block(2, state, commit, proposer)

        # corrupt one commit signature: default validation rejects,
        # commit_verified accepts (the caller vouches for signatures)
        sigs = list(block.last_commit.signatures)
        s0 = sigs[0]
        sigs[0] = dataclasses.replace(
            s0, signature=s0.signature[:63] + bytes([s0.signature[63] ^ 1])
        )
        bad_commit = dataclasses.replace(
            block.last_commit, signatures=tuple(sigs)
        )
        forged = dataclasses.replace(
            block,
            header=dataclasses.replace(
                block.header, last_commit_hash=bad_commit.hash()
            ),
            last_commit=bad_commit,
        )
        with pytest.raises(Exception):
            h.executor.validate_block(state, forged)
        h.executor.validate_block(state, forged, commit_verified=True)

        # structural damage is still caught with commit_verified=True:
        # height mismatch inside the commit
        wrong_h = dataclasses.replace(block.last_commit, height=99)
        broken = dataclasses.replace(
            block,
            header=dataclasses.replace(
                block.header, last_commit_hash=wrong_h.hash()
            ),
            last_commit=wrong_h,
        )
        with pytest.raises(Exception):
            h.executor.validate_block(state, broken, commit_verified=True)

    asyncio.run(run())
