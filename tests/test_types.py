"""Domain-type tests: canonical sign-bytes, commit verification variants
(pinning the 2/3+ and edge-case semantics, mirroring the reference's
validation_test strategy), proposer rotation, vote sets, part sets."""

import os
from fractions import Fraction

import pytest

os.environ.setdefault("TMTPU_DISABLE_TPU", "1")  # types tests use CPU verify

from tendermint_tpu import testing as tt
from tendermint_tpu.crypto import ed25519
from tendermint_tpu.libs import protoenc as pe
from tendermint_tpu.types import validation
from tendermint_tpu.types.block import (
    Block,
    BlockID,
    Commit,
    CommitSig,
    Header,
    NIL_BLOCK_ID,
    PartSetHeader,
    txs_hash,
)
from tendermint_tpu.types.canonical import vote_sign_bytes
from tendermint_tpu.types.evidence import DuplicateVoteEvidence, decode_evidence
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.keys import SignedMsgType
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.validator_set import Validator, ValidatorSet
from tendermint_tpu.types.vote import Proposal, Vote
from tendermint_tpu.types.vote_set import ConflictingVoteError, VoteSet, VoteSetError

CHAIN = "test-chain"


def test_sign_bytes_deterministic_and_distinct():
    bid = tt.make_block_id()
    a = vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 5, 0, bid, 1000)
    b = vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 5, 0, bid, 1000)
    assert a == b
    # any field change must change the bytes
    variants = [
        vote_sign_bytes("other", SignedMsgType.PRECOMMIT, 5, 0, bid, 1000),
        vote_sign_bytes(CHAIN, SignedMsgType.PREVOTE, 5, 0, bid, 1000),
        vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 6, 0, bid, 1000),
        vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 5, 1, bid, 1000),
        vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 5, 0, None, 1000),
        vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 5, 0, bid, 1001),
    ]
    assert len({a, *variants}) == len(variants) + 1


def test_sign_bytes_fixed_width_height():
    # sfixed64 height: heights 1 and 256 produce equal-length encodings
    bid = tt.make_block_id()
    a = vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 1, 0, bid, 1000)
    b = vote_sign_bytes(CHAIN, SignedMsgType.PRECOMMIT, 256, 0, bid, 1000)
    assert len(a) == len(b)


def test_commit_roundtrip():
    vals, keys = tt.make_validator_set(4)
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 3, 1, bid, vals, keys, nil_indices=frozenset([2]))
    decoded = Commit.decode(commit.encode())
    assert decoded == commit
    assert decoded.hash() == commit.hash()


def test_verify_commit_all_good():
    vals, keys = tt.make_validator_set(10)
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 3, 0, bid, vals, keys)
    validation.verify_commit(CHAIN, vals, bid, 3, commit)
    validation.verify_commit_light(CHAIN, vals, bid, 3, commit)
    validation.verify_commit_light_trusting(CHAIN, vals, commit)


def test_verify_commit_exactly_two_thirds_fails():
    # 10 validators, power 10 each: need > 66; 7 commits = 70 ok, 6 = 60 fails
    vals, keys = tt.make_validator_set(10)
    bid = tt.make_block_id()
    commit_ok = tt.make_commit(
        CHAIN, 3, 0, bid, vals, keys, nil_indices=frozenset([7, 8, 9])
    )
    validation.verify_commit(CHAIN, vals, bid, 3, commit_ok)
    commit_bad = tt.make_commit(
        CHAIN, 3, 0, bid, vals, keys, nil_indices=frozenset([6, 7, 8, 9])
    )
    with pytest.raises(validation.InvalidCommitError, match="insufficient"):
        validation.verify_commit(CHAIN, vals, bid, 3, commit_bad)


def test_verify_commit_bad_signature_detected():
    vals, keys = tt.make_validator_set(6)
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 3, 0, bid, vals, keys)
    sigs = list(commit.signatures)
    bad = sigs[2]
    sigs[2] = CommitSig.for_block(
        bad.validator_address, bad.timestamp_ns, bad.signature[:-1] + b"\x00"
    )
    commit_bad = Commit(3, 0, bid, tuple(sigs))
    with pytest.raises(validation.InvalidCommitError, match="index 2"):
        validation.verify_commit(CHAIN, vals, bid, 3, commit_bad)


def test_verify_commit_nil_vote_with_bad_sig_fails_full_but_not_light():
    # nil votes are verified by verify_commit (count_all) but skipped by light
    vals, keys = tt.make_validator_set(10)
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 3, 0, bid, vals, keys, nil_indices=frozenset([9]))
    sigs = list(commit.signatures)
    nil_sig = sigs[9]
    sigs[9] = CommitSig.for_nil(
        nil_sig.validator_address, nil_sig.timestamp_ns, b"\x01" * 64
    )
    commit_bad = Commit(3, 0, bid, tuple(sigs))
    with pytest.raises(validation.InvalidCommitError):
        validation.verify_commit(CHAIN, vals, bid, 3, commit_bad)
    validation.verify_commit_light(CHAIN, vals, bid, 3, commit_bad)


def test_verify_commit_mismatches():
    vals, keys = tt.make_validator_set(4)
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 3, 0, bid, vals, keys)
    with pytest.raises(validation.InvalidCommitError, match="height"):
        validation.verify_commit(CHAIN, vals, bid, 4, commit)
    with pytest.raises(validation.InvalidCommitError, match="different block"):
        validation.verify_commit(CHAIN, vals, tt.make_block_id(b"other"), 3, commit)
    smaller, _ = tt.make_validator_set(3)
    with pytest.raises(validation.InvalidCommitError, match="size"):
        validation.verify_commit(CHAIN, smaller, bid, 3, commit)


def test_verify_commit_light_trusting_rotated_set():
    # trusting: new set shares 2 of 4 validators; by-address lookup
    vals, keys = tt.make_validator_set(4, seed=b"setA")
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 3, 0, bid, vals, keys)
    vals_b, keys_b = tt.make_validator_set(4, seed=b"setB")
    # trusted set = 2 from A + 2 from B: 2/4 of trusted power signed = 50% > 1/3
    mixed = ValidatorSet(
        [Validator(v.pub_key, v.voting_power) for v in vals.validators[:2]]
        + [Validator(v.pub_key, v.voting_power) for v in vals_b.validators[:2]]
    )
    validation.verify_commit_light_trusting(CHAIN, mixed, commit)
    # with trust level 2/3, 50% is not enough
    with pytest.raises(validation.InvalidCommitError):
        validation.verify_commit_light_trusting(
            CHAIN, mixed, commit, trust_level=Fraction(2, 3)
        )


def test_verify_commit_single_matches_batch():
    vals, keys = tt.make_validator_set(8)
    bid = tt.make_block_id()
    commit = tt.make_commit(CHAIN, 2, 0, bid, vals, keys)
    validation._verify_single(CHAIN, vals, commit, vals.total_voting_power() * 2 // 3, True, True)
    validation._verify_batch(CHAIN, vals, commit, vals.total_voting_power() * 2 // 3, True, True)


def test_proposer_rotation_fair():
    # equal powers: round-robin; each validator proposes once per n rounds
    vals, _ = tt.make_validator_set(5)
    seen = []
    vs = vals.copy()
    for _ in range(5):
        seen.append(vs.get_proposer().address)
        vs.increment_proposer_priority(1)
    assert len(set(seen)) == 5


def test_proposer_rotation_weighted():
    keys = tt.det_priv_keys(3, b"weighted")
    vals = ValidatorSet(
        [
            Validator(keys[0].pub_key(), 1),
            Validator(keys[1].pub_key(), 2),
            Validator(keys[2].pub_key(), 7),
        ]
    )
    counts = {}
    vs = vals.copy()
    for _ in range(100):
        addr = vs.get_proposer().address
        counts[addr] = counts.get(addr, 0) + 1
        vs.increment_proposer_priority(1)
    assert counts[keys[2].pub_key().address()] == 70
    assert counts[keys[1].pub_key().address()] == 20
    assert counts[keys[0].pub_key().address()] == 10


def test_validator_set_update_and_hash():
    vals, _ = tt.make_validator_set(4)
    h0 = vals.hash()
    new_key = ed25519.Ed25519PrivKey.generate()
    vals2 = vals.copy()
    vals2.update_with_change_set([Validator(new_key.pub_key(), 5)])
    assert len(vals2) == 5
    assert vals2.hash() != h0
    # new validator has the -1.125*total penalty → doesn't propose immediately
    _, nv = vals2.get_by_address(new_key.pub_key().address())
    assert nv.proposer_priority < 0
    # removal
    vals2.update_with_change_set([Validator(new_key.pub_key(), 0)])
    assert len(vals2) == 4
    assert vals2.hash() == h0
    # set cannot become empty
    with pytest.raises(ValueError):
        empty_changes = [Validator(v.pub_key, 0) for v in vals2.validators]
        vals2.update_with_change_set(empty_changes)


def test_validator_set_roundtrip():
    vals, _ = tt.make_validator_set(4)
    vals.increment_proposer_priority(3)
    decoded = ValidatorSet.decode(vals.encode())
    assert decoded.hash() == vals.hash()
    assert [v.proposer_priority for v in decoded.validators] == [
        v.proposer_priority for v in vals.validators
    ]
    assert decoded.get_proposer().address == vals.get_proposer().address


def test_vote_set_two_thirds():
    vals, keys = tt.make_validator_set(4)
    vs = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vals)
    bid = tt.make_block_id()
    ordered_keys = [keys[v.address] for v in vals.validators]
    for i in range(3):
        added = vs.add_vote(
            tt.make_vote(CHAIN, ordered_keys[i], i, 5, 0, SignedMsgType.PRECOMMIT, bid)
        )
        assert added
    assert vs.has_two_thirds_majority()
    assert vs.two_thirds_majority() == bid
    commit = vs.make_commit()
    assert commit.size() == 4
    validation.verify_commit_light(CHAIN, vals, bid, 5, commit)


def test_vote_set_rejects_bad_votes():
    vals, keys = tt.make_validator_set(4)
    vs = VoteSet(CHAIN, 5, 0, SignedMsgType.PRECOMMIT, vals)
    bid = tt.make_block_id()
    ordered_keys = [keys[v.address] for v in vals.validators]
    # wrong height
    with pytest.raises(VoteSetError):
        vs.add_vote(tt.make_vote(CHAIN, ordered_keys[0], 0, 6, 0, SignedMsgType.PRECOMMIT, bid))
    # wrong index/address pairing
    with pytest.raises(VoteSetError):
        vs.add_vote(tt.make_vote(CHAIN, ordered_keys[0], 1, 5, 0, SignedMsgType.PRECOMMIT, bid))
    # bad signature (signed for different chain)
    bad = tt.make_vote("bad-chain", ordered_keys[0], 0, 5, 0, SignedMsgType.PRECOMMIT, bid)
    with pytest.raises(VoteSetError, match="signature"):
        vs.add_vote(bad)
    # conflicting vote -> evidence path
    v1 = tt.make_vote(CHAIN, ordered_keys[0], 0, 5, 0, SignedMsgType.PRECOMMIT, bid)
    assert vs.add_vote(v1)
    assert not vs.add_vote(v1)  # exact duplicate ok, not added
    v2 = tt.make_vote(
        CHAIN, ordered_keys[0], 0, 5, 0, SignedMsgType.PRECOMMIT, tt.make_block_id(b"fork")
    )
    with pytest.raises(ConflictingVoteError):
        vs.add_vote(v2)


def test_part_set_roundtrip():
    data = os.urandom(200_000)
    ps = PartSet.from_data(data, part_size=65536)
    assert ps.is_complete()
    assert ps.header.total == 4
    # reassemble into a fresh set out of order
    ps2 = PartSet(ps.header)
    for idx in [3, 0, 2, 1]:
        assert ps2.add_part(ps.get_part(idx))
    assert ps2.is_complete()
    assert ps2.assemble() == data
    # tampered part rejected
    ps3 = PartSet(ps.header)
    p = ps.get_part(0)
    from tendermint_tpu.types.part_set import Part

    with pytest.raises(ValueError):
        ps3.add_part(Part(0, p.bytes_ + b"x", p.proof))


def test_block_roundtrip_and_validate():
    vals, keys = tt.make_validator_set(4)
    bid = tt.make_block_id()
    last_commit = tt.make_commit(CHAIN, 1, 0, bid, vals, keys)
    txs = (b"tx1", b"tx2")
    header = Header(
        chain_id=CHAIN,
        height=2,
        time_ns=123456789,
        last_block_id=bid,
        last_commit_hash=last_commit.hash(),
        data_hash=txs_hash(txs),
        validators_hash=vals.hash(),
        next_validators_hash=vals.hash(),
        consensus_hash=ConsensusParams().hash(),
        app_hash=b"\x01" * 32,
        last_results_hash=b"",
        evidence_hash=b"",
        proposer_address=vals.get_proposer().address,
    )
    block = Block(header, txs, (), last_commit)
    block.validate_basic()
    decoded = Block.decode(block.encode())
    assert decoded.hash() == block.hash()
    assert decoded.txs == txs
    assert decoded.last_commit.hash() == last_commit.hash()


def test_vote_proposal_roundtrip():
    vals, keys = tt.make_validator_set(1)
    k = list(keys.values())[0]
    bid = tt.make_block_id()
    v = tt.make_vote(CHAIN, k, 0, 7, 2, SignedMsgType.PREVOTE, bid)
    v.validate_basic()
    assert Vote.decode(v.encode()) == v
    p = Proposal(7, 2, -1, bid, 999, b"")
    sb = p.sign_bytes(CHAIN)
    p2 = Proposal(7, 2, -1, bid, 999, k.sign(sb))
    p2.validate_basic()
    assert Proposal.decode(p2.encode()) == p2
    assert k.pub_key().verify_signature(p2.sign_bytes(CHAIN), p2.signature)


def test_duplicate_vote_evidence():
    vals, keys = tt.make_validator_set(4)
    ordered_keys = [keys[v.address] for v in vals.validators]
    bid_a, bid_b = tt.make_block_id(b"a"), tt.make_block_id(b"b")
    va = tt.make_vote(CHAIN, ordered_keys[0], 0, 5, 0, SignedMsgType.PRECOMMIT, bid_a)
    vb = tt.make_vote(CHAIN, ordered_keys[0], 0, 5, 0, SignedMsgType.PRECOMMIT, bid_b)
    ev = DuplicateVoteEvidence.from_votes(va, vb, 1000, vals)
    ev.validate_basic()
    dec = decode_evidence(ev.encode())
    assert dec == ev
    with pytest.raises(ValueError):
        DuplicateVoteEvidence.from_votes(va, va, 1000, vals).validate_basic()


def test_genesis_roundtrip():
    vals, _ = tt.make_validator_set(3)
    doc = GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(v.pub_key, v.voting_power) for v in vals.validators],
        app_state=b'{"accounts": []}',
    )
    doc2 = GenesisDoc.from_json(doc.to_json())
    assert doc2.chain_id == doc.chain_id
    assert doc2.validator_set().hash() == vals.hash()
    assert doc2.app_state == doc.app_state
    assert doc.hash() == doc2.hash()


def test_consensus_params_roundtrip():
    p = ConsensusParams()
    p.validate_basic()
    assert ConsensusParams.decode(p.encode()) == p
    assert p.hash() == ConsensusParams.decode(p.encode()).hash()


def test_verify_commit_range_mixed_set_secp_first():
    """Regression: a mixed validator set whose highest-power (first-
    sorted) validator is secp256k1 must still range-verify — the batch
    verifier is created lazily from a BATCHABLE entry, not keyed off
    validators[0] (which crashed block-sync on restarted mixed-key
    nodes whenever address order put the secp key first)."""
    import hashlib

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.secp256k1 import Secp256k1PrivKey
    from tendermint_tpu.testing import make_block_id, make_commit
    from tendermint_tpu.types.validation import verify_commit_range
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet

    secp = Secp256k1PrivKey(hashlib.sha256(b"mixed-first").digest())
    eds = [
        ed25519.Ed25519PrivKey(hashlib.sha256(b"mixed-%d" % i).digest())
        for i in range(3)
    ]
    # secp gets the highest power -> guaranteed validators[0] after the
    # (-power, address) sort
    vals = ValidatorSet(
        [Validator(secp.pub_key(), 100)]
        + [Validator(k.pub_key(), 10) for k in eds]
    )
    assert vals.validators[0].pub_key.TYPE == "secp256k1"
    keys = {k.pub_key().address(): k for k in [secp] + eds}

    entries = []
    for h in (1, 2):
        bid = make_block_id(b"mixed-range-%d" % h)
        commit = make_commit("mixed-range", h, 0, bid, vals, keys)
        entries.append((vals, bid, h, commit))
    verify_commit_range("mixed-range", entries)  # must not raise
