"""VerifyD — the cross-process verification sidecar (crypto/verifyd.py).

Covers the full contract: the length-prefixed protoenc protocol (no
pickle anywhere near the socket), the daemon's hub-backed verify path
with multi-tenant cross-client packing, busy-shedding at the bounded
in-flight cap, and — most load-bearing — the degrade contract: a dead
daemon can NEVER be a correctness or liveness event (breaker trips to
inline-local verification; a half-open probe re-adopts the remote route
after restart), pinned via the client/daemon metrics, not log tails.

The live-consensus acceptance (byte-identical chain with the sidecar on
vs off) runs an in-process LocalNetwork on a frozen ManualClock — the
same bit-reproducibility mechanism as tests/test_chaos_live.py — so
"identical" means identical block BYTES, not just app hashes. The
multiprocess (real SIGKILL, real node processes) variants live in
tests/test_multiprocess_e2e.py under the slow mark.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import threading
import time

import pytest

from tendermint_tpu.crypto import verifyd as vd
from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey
from tendermint_tpu.crypto.secp256k1 import Secp256k1PrivKey
from tendermint_tpu.crypto.verify_hub import VerifyHub


def _sock_path() -> str:
    # UDS paths are length-limited (~104 bytes); tmp_path fixtures can
    # blow past it, so mint short paths ourselves
    return os.path.join(tempfile.mkdtemp(prefix="vd-"), "vd.sock")


class DaemonThread:
    """An in-process daemon on its own event loop + thread: unit tests
    get a real UDS server without a subprocess interpreter spin-up. The
    daemon's hub is private (allow_remote=False), so a client hub in
    the same process can never route back into itself."""

    def __init__(self, sock: str, **kw):
        self.sock = sock
        self.kw = dict(warm_backend=False, **kw)
        self.daemon: vd.VerifyDaemon | None = None
        self.loop = None
        self._started = threading.Event()
        self._stop_ev = None
        self._thread = None

    def start(self) -> "DaemonThread":
        self._started.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(10), "daemon failed to start"
        return self

    def _run(self):
        async def main():
            self.daemon = vd.VerifyDaemon(self.sock, **self.kw)
            self.loop = asyncio.get_running_loop()
            self._stop_ev = asyncio.Event()
            await self.daemon.start()
            self._started.set()
            await self._stop_ev.wait()
            await self.daemon.stop()

        asyncio.run(main())

    def stop(self):
        """Abrupt from the client's point of view: in-flight requests
        are cancelled and connections closed without a reply — the same
        observable surface as a SIGKILL'd daemon process."""
        if self._thread is None or not self._thread.is_alive():
            return
        self.loop.call_soon_threadsafe(self._stop_ev.set)
        self._thread.join(10)
        assert not self._thread.is_alive(), "daemon thread leaked"


@pytest.fixture(autouse=True)
def _fresh_clients(monkeypatch):
    # fast breaker so half-open re-adoption is testable without sleeps
    monkeypatch.setenv("TMTPU_VERIFYD_BREAKER_RESET", "0.2")
    vd.reset_clients()
    yield
    vd.reset_clients()


def _ed_items(n: int, tag: bytes = b"vd"):
    priv = Ed25519PrivKey(b"\x07" * 32)
    pub = priv.pub_key()
    return [
        (pub, tag + b"-%d" % i, priv.sign(tag + b"-%d" % i)) for i in range(n)
    ]


# ---------------------------------------------------------------------------
# wire codec


def test_codec_roundtrips():
    p = vd.encode_verify_batch(
        9, [("ed25519", b"P" * 32, b"m", b"S" * 64, "backfill"),
            ("sr25519", b"Q" * 32, b"n", b"T" * 64, "live")]
    )
    t, f = vd.decode_message(p)
    assert t == vd.MSG_VERIFY_BATCH and f["req_id"] == 9
    assert f["items"][0] == ("ed25519", b"P" * 32, b"m", b"S" * 64, "backfill")
    assert f["items"][1][4] == "live"

    t, f = vd.decode_message(
        vd.encode_hello_ok(1, vd.DAEMON_SCHEMES, vd.bucket_ladder(), b"e" * 8)
    )
    assert t == vd.MSG_HELLO_OK
    assert f["version"] == 1 and f["epoch"] == b"e" * 8
    assert f["ladder"][0] == 64 and set(f["schemes"]) == set(vd.DAEMON_SCHEMES)

    t, f = vd.decode_message(vd.encode_verdicts(4, [True, False, True]))
    assert (t, f["verdicts"]) == (vd.MSG_VERDICTS, [True, False, True])

    t, f = vd.decode_message(
        vd.encode_verify_aggregate(
            5, [("bls12381", b"K" * 48)], [b"m1", b"m2"], b"G" * 96
        )
    )
    assert t == vd.MSG_VERIFY_AGGREGATE
    assert f["keys"] == [("bls12381", b"K" * 48)]
    assert f["msgs"] == [b"m1", b"m2"] and f["agg_sig"] == b"G" * 96

    for enc, ty in (
        (vd.encode_busy(3), vd.MSG_BUSY),
        (vd.encode_error(3, "nope"), vd.MSG_ERROR),
        (vd.encode_stats(3), vd.MSG_STATS),
        (vd.encode_stats_ok(3, {"a": 1.0}), vd.MSG_STATS_OK),
    ):
        t, f = vd.decode_message(enc)
        assert (t, f["req_id"]) == (ty, 3)
    assert vd.decode_message(vd.encode_stats_ok(3, {"a": 1.0}))[1]["stats"] == {
        "a": 1.0
    }


def test_frame_bounds():
    with pytest.raises(ValueError):
        vd.frame(b"x" * (vd.MAX_FRAME + 1))
    assert vd.frame(b"ab")[:4] == (2).to_bytes(4, "big")


def test_decoder_skips_unknown_fields():
    from tendermint_tpu.libs import protoenc as pe

    payload = (
        pe.varint_field(1, vd.MSG_BUSY)
        + pe.varint_field(2, 7)
        + pe.varint_field(9, 123)  # future extension field
        + pe.bytes_field(10, b"ignored")
    )
    t, f = vd.decode_message(payload)
    assert (t, f["req_id"]) == (vd.MSG_BUSY, 7)


# ---------------------------------------------------------------------------
# daemon + client end-to-end (in-process, real UDS)


def test_remote_verify_batch_end_to_end():
    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        items = _ed_items(16)
        bad = (items[0][0], b"tampered", b"\x00" * 64)
        got = hub.verify_many(items + [bad], timeout=30)
        assert got == [True] * 16 + [False]
        # the remote route served it: client + daemon agree on the count
        assert vd.CLIENT_STATS["remote_dispatches"] >= 1
        assert vd.CLIENT_STATS["remote_sigs"] >= 17
        assert vd.CLIENT_STATS["remote_fallbacks"] == 0
        assert dt.daemon.stats["requests"] >= 1
        assert dt.daemon.stats["sigs"] >= 17
        assert hub.stats()["verify_errors"] == 0
        # hello pinned the daemon's scheme set + bucket ladder
        c = vd.client_for(sock)
        assert c.schemes == frozenset(vd.DAEMON_SCHEMES)
        assert c.ladder and c.ladder[0] == 64
    finally:
        hub.stop()
        dt.stop()


def test_mixed_scheme_batch_matches_local_verdicts():
    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        ed = _ed_items(4, b"mix")
        sp = Secp256k1PrivKey.generate()
        sec = [(sp.pub_key(), b"sec-%d" % i, sp.sign(b"sec-%d" % i)) for i in range(3)]
        items = ed[:2] + sec[:1] + ed[2:] + sec[1:]
        expect = [pk.verify_signature(m, s) for pk, m, s in items]
        assert hub.verify_many(items, timeout=30) == expect == [True] * 7
        # tamper one of each scheme: attribution survives the socket
        items[1] = (items[1][0], items[1][1], b"\x01" * 64)
        items[2] = (items[2][0], items[2][1] + b"x", items[2][2])
        got = hub.verify_many(items, timeout=30)
        assert got == [True, False, False, True, True, True, True]
    finally:
        hub.stop()
        dt.stop()


def test_daemon_sheds_busy_past_inflight_cap_and_client_falls_back():
    sock = _sock_path()
    dt = DaemonThread(sock, max_inflight=2).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        items = _ed_items(8, b"busy")  # 8 > cap of 2 -> busy reply
        assert hub.verify_many(items, timeout=30) == [True] * 8
        assert dt.daemon.stats["shed"] >= 1
        assert vd.CLIENT_STATS["remote_busy"] >= 1
        assert vd.CLIENT_STATS["remote_fallbacks"] >= 1
        # a shed is explicit backpressure, not a failure: the breaker
        # must stay closed (the daemon is healthy, just loaded)
        assert vd.client_for(sock).breaker.state == "closed"
    finally:
        hub.stop()
        dt.stop()


def test_daemon_death_degrades_inline_and_restart_readopts():
    """The satellite contract, fast shape: kill the daemon mid-stream ->
    every verification still answers (inline-local), zero verify_errors,
    no wedged futures; restart the daemon -> the half-open probe
    re-adopts the remote route. Both transitions pinned via metrics."""
    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        assert hub.verify_many(_ed_items(4, b"pre"), timeout=30) == [True] * 4
        pre_remote = vd.CLIENT_STATS["remote_dispatches"]
        assert pre_remote >= 1

        dt.stop()  # connections die without replies — the SIGKILL surface

        # every batch during the outage answers correctly, inline-local
        assert hub.verify_many(_ed_items(6, b"dead"), timeout=30) == [True] * 6
        assert vd.CLIENT_STATS["remote_fallbacks"] >= 1
        assert vd.CLIENT_STATS["remote_dispatches"] == pre_remote
        assert hub.stats()["verify_errors"] == 0
        breaker = vd.client_for(sock).breaker
        assert breaker.opens >= 1

        # restart on the SAME socket path; the half-open probe (0.2 s
        # reset via the fixture env) must re-adopt the remote route
        dt2 = DaemonThread(sock).start()
        try:
            deadline = time.monotonic() + 10
            i = 0
            while vd.CLIENT_STATS["remote_dispatches"] == pre_remote:
                assert time.monotonic() < deadline, "remote route never re-adopted"
                i += 1
                assert hub.verify_many(
                    _ed_items(2, b"again-%d" % i), timeout=30
                ) == [True] * 2
                time.sleep(0.05)
            assert breaker.state == "closed"
            # the fresh boot is visible as a new epoch on the same path
            assert dt2.daemon.epoch != dt.daemon.epoch
            assert vd.client_for(sock).daemon_epoch == dt2.daemon.epoch
        finally:
            dt2.stop()
    finally:
        hub.stop()


def test_cross_client_packing_counted():
    """Two client processes' worth of traffic in one daemon dispatch:
    the amortization win the sidecar exists for, measured via the hub's
    tenant tags (a long daemon-side window makes the pack determinate)."""
    sock = _sock_path()
    dt = DaemonThread(sock, window_ms=150.0, max_batch=512).start()
    try:
        c1 = vd.VerifydClient(sock)
        c2 = vd.VerifydClient(sock)
        items = _ed_items(4, b"pack")
        quads = [(pk, m, s, "live") for pk, m, s in items]
        out: dict = {}

        def go(name, client, quads_):
            out[name] = client.remote_verify_batch(quads_)

        t1 = threading.Thread(target=go, args=("a", c1, quads[:2]))
        t2 = threading.Thread(target=go, args=("b", c2, quads[2:]))
        t1.start(), t2.start()
        t1.join(30), t2.join(30)
        assert out["a"] == [True, True] and out["b"] == [True, True]
        hs = dt.daemon.hub.stats()
        assert hs["cross_tenant_dispatches"] >= 1, hs
        assert dt.daemon.stats["clients_total"] == 2
        c1.close(), c2.close()
    finally:
        dt.stop()


def test_verify_aggregate_routes_remote():
    from tendermint_tpu.crypto import verify_hub as vh
    from tendermint_tpu.crypto.bls import BLSPrivKey, aggregate_signatures

    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = vh.acquire_hub(verifyd_sock=sock, window_ms=1.0)
    try:
        privs = [BLSPrivKey(bytes([i + 1]) * 32) for i in range(2)]
        msgs = [b"agg-vd-%d" % i for i in range(2)]
        agg = aggregate_signatures(
            [p.sign(m) for p, m in zip(privs, msgs)]
        )
        pubs = [p.pub_key() for p in privs]
        assert vh.verify_aggregate(pubs, msgs, agg) is True
        assert vd.CLIENT_STATS["remote_agg_dispatches"] == 1
        assert dt.daemon.stats["agg_requests"] == 1
        # gossip re-verification: the CLIENT-side verdict cache answers
        # without a second socket round-trip
        assert vh.verify_aggregate(pubs, msgs, agg) is True
        assert vd.CLIENT_STATS["remote_agg_dispatches"] == 1
        # tampered aggregate is False through the same remote path
        bad = bytearray(agg)
        bad[0] ^= 0x01
        assert vh.verify_aggregate(pubs, msgs, bytes(bad)) is False
    finally:
        vh.release_hub()
        dt.stop()


def test_aggregate_sheds_at_inflight_cap_and_falls_back():
    """Aggregates ride the same bounded in-flight budget as batches
    (weighted by signer count): past the cap the daemon replies busy
    and the client's LOCAL pairing still answers correctly."""
    from tendermint_tpu.crypto import verify_hub as vh
    from tendermint_tpu.crypto.bls import BLSPrivKey, aggregate_signatures

    sock = _sock_path()
    dt = DaemonThread(sock, max_inflight=1).start()
    hub = vh.acquire_hub(verifyd_sock=sock, window_ms=1.0)
    try:
        privs = [BLSPrivKey(bytes([i + 9]) * 32) for i in range(2)]
        msgs = [b"agg-shed-%d" % i for i in range(2)]
        agg = aggregate_signatures([p.sign(m) for p, m in zip(privs, msgs)])
        pubs = [p.pub_key() for p in privs]
        assert vh.verify_aggregate(pubs, msgs, agg) is True  # local fallback
        assert dt.daemon.stats["shed"] >= 1
        assert dt.daemon.stats["agg_requests"] == 0  # shed BEFORE any work
        assert vd.CLIENT_STATS["remote_busy"] >= 1
        assert vd.CLIENT_STATS["remote_agg_dispatches"] == 0
        # busy is backpressure: the aggregate-purpose breaker stays closed
        assert vd.client_for(sock, "aggregate").breaker.state == "closed"
    finally:
        vh.release_hub()
        dt.stop()


def test_scheme_pin_falls_back_local():
    """A scheme the daemon's hello did not pin never rides the socket —
    the batch verifies locally instead of gambling on the daemon."""
    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        # prime the connection so the pin exists, then shrink it
        assert hub.verify_many(_ed_items(2, b"pin"), timeout=30) == [True] * 2
        c = vd.client_for(sock)
        c.schemes = frozenset({"sr25519"})
        before = dt.daemon.stats["requests"]
        assert hub.verify_many(_ed_items(3, b"pin2"), timeout=30) == [True] * 3
        assert dt.daemon.stats["requests"] == before  # never hit the socket
        assert vd.CLIENT_STATS["remote_fallbacks"] >= 1
    finally:
        hub.stop()
        dt.stop()


def test_daemon_decode_skew_is_error_never_a_false_verdict():
    """Version-skew guard: a key the daemon cannot decode must produce
    an ERROR reply (client verifies the whole batch inline-locally),
    NEVER a fabricated False — a False would be cached client-side as
    an authoritative verdict and permanently reject a valid vote."""
    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=64)
    hub.start()

    def skewed_decode(type_name, data):
        raise ValueError(f"daemon build predates scheme {type_name!r}")

    real = vd.pubkey_from_type_and_bytes
    vd.pubkey_from_type_and_bytes = skewed_decode
    try:
        items = _ed_items(3, b"skew")
        # the daemon errors; the client must fall back and return the
        # TRUE verdicts from local verification
        assert hub.verify_many(items, timeout=30) == [True] * 3
        assert dt.daemon.stats["errors"] >= 1
        assert vd.CLIENT_STATS["remote_fallbacks"] >= 1
        assert vd.CLIENT_STATS["remote_dispatches"] == 0
        # and the cached verdicts are the true ones (repeat = cache hit)
        assert hub.verify_many(items, timeout=30) == [True] * 3
    finally:
        vd.pubkey_from_type_and_bytes = real
        hub.stop()
        dt.stop()


def test_bad_hello_version_refused():
    sock = _sock_path()
    dt = DaemonThread(sock).start()
    try:
        import socket as pysock

        s = pysock.socket(pysock.AF_UNIX, pysock.SOCK_STREAM)
        s.settimeout(5)
        s.connect(sock)
        s.sendall(vd.frame(vd.encode_hello(version=99)))
        hdr = vd.VerifydClient._recv_exact(s, 4)
        payload = vd.VerifydClient._recv_exact(s, int.from_bytes(hdr, "big"))
        t, f = vd.decode_message(payload)
        assert t == vd.MSG_ERROR and "hello" in f["error"]
        s.close()
    finally:
        dt.stop()


def test_metrics_fold_renders_verifyd_families():
    from tendermint_tpu.libs.metrics import NodeMetrics

    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    try:
        assert hub.verify_many(_ed_items(5, b"met"), timeout=30) == [True] * 5
        text = NodeMetrics().render()
        # client-side families carry the remote traffic
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("tendermint_tpu_verifyhub_remote_dispatches ")
        )
        assert float(line.split()[-1]) >= 1
        assert "tendermint_tpu_verifyhub_remote_rtt_seconds_count" in text
        # daemon-side families fold because the daemon runs in-process
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("tendermint_tpu_verifyd_requests ")
        )
        assert float(line.split()[-1]) >= 1
    finally:
        hub.stop()
        dt.stop()


def test_dispatch_span_route_verifyd():
    from tendermint_tpu.libs import trace

    sock = _sock_path()
    dt = DaemonThread(sock).start()
    hub = VerifyHub(verifyd_sock=sock, window_ms=1.0, cache_size=0)
    hub.start()
    was = trace.RECORDER.enabled
    trace.RECORDER.enabled = True
    try:
        assert hub.verify_many(_ed_items(3, b"span"), timeout=30) == [True] * 3
        spans = []
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            spans = [
                d for d in trace.RECORDER.dump(subsystem="hub")
                if d["name"] == "dispatch"
                and d.get("attrs", {}).get("route") == "verifyd"
            ]
            if spans:
                break
            time.sleep(0.02)
        assert spans, "no hub.dispatch span with route=verifyd"
        assert spans[-1]["attrs"]["sigs"] >= 1
    finally:
        trace.RECORDER.enabled = was
        hub.stop()
        dt.stop()


# ---------------------------------------------------------------------------
# live consensus acceptance (in-process network, frozen clock)


async def _run_net_chain(n_heights: int, verifyd_sock: str | None):
    """One 3-validator live run on a frozen ManualClock; returns the
    committed chain as raw block bytes per height (bit-reproducible —
    the test_chaos_live mechanism, chaos-free)."""
    from tendermint_tpu.consensus.harness import LocalNetwork, fast_config
    from tendermint_tpu.crypto import verify_hub as vh
    from tendermint_tpu.libs.clock import ManualClock

    MS = 1_000_000
    genesis_ns = 1_700_000_000_000_000_000
    hub = vh.acquire_hub(
        verifyd_sock=verifyd_sock or "", window_ms=1.0, cache_size=0
    )
    try:
        net = LocalNetwork(
            3, config=fast_config(), base_clock=ManualClock(genesis_ns - 500 * MS)
        )
        await net.start()
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(n_heights, 60) for n in net.nodes)
            )
            chain = {}
            for h in range(1, n_heights + 1):
                blocks = {
                    bytes(n.block_store.load_block(h).encode()) for n in net.nodes
                }
                assert len(blocks) == 1, f"nodes disagree at height {h}"
                chain[h] = blocks.pop()
        finally:
            await net.stop()
        return chain, dict(hub.stats())
    finally:
        vh.release_hub()


@pytest.mark.asyncio
async def test_live_consensus_chain_byte_identical_with_sidecar():
    """Acceptance: the sidecar changes WHERE verification runs, never
    what is committed — a live run with every hub batch served by the
    daemon commits byte-identical blocks to the daemon-less run."""
    # the global hub caches verdicts; isolate the two runs fully
    vd.reset_clients()
    baseline, _ = await _run_net_chain(2, None)

    sock = _sock_path()
    dt = DaemonThread(sock).start()
    try:
        vd.reset_clients()
        chain, stats = await _run_net_chain(2, sock)
        assert chain == baseline, "sidecar run diverged from local run"
        # the remote route actually carried traffic (not a vacuous pass:
        # in-process signers pre-cache their own votes, so require only
        # that every cold dispatch went over the socket)
        assert vd.CLIENT_STATS["remote_dispatches"] >= 1
        assert vd.CLIENT_STATS["remote_fallbacks"] == 0
        assert stats["verify_errors"] == 0
        assert dt.daemon.stats["sigs"] >= 1
    finally:
        dt.stop()
