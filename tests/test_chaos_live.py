"""Live-consensus chaos (the ROADMAP item chaos-net couldn't cover):
with `libs/clock.py` threaded through the consensus SM, a chaos matrix
over LIVE consensus — not just block-sync — becomes bit-reproducible.

Mechanism: every validator runs on a frozen `ManualClock` parked behind
genesis time, skewed per validator by the chaos `clock_skew_ms` fault
class. The vote-time minimum rule (`max(now, block_time + 1ms)`,
reference voteTime) then floors every non-nil vote timestamp to
`block_time + 1ms`, and the weighted-median block-time rule propagates
it: every vote/block timestamp becomes a pure function of (height,
genesis_time) — identical across runs no matter how asyncio schedules
delivery, and robust to validators whose wall clocks disagree."""

import pytest

from tendermint_tpu.consensus import messages as m
from tendermint_tpu.consensus.harness import LocalNetwork, fast_config
from tendermint_tpu.libs.chaos import ChaosConfig, ChaosNetwork
from tendermint_tpu.libs.clock import ManualClock
from tendermint_tpu.types.keys import SignedMsgType

MS = 1_000_000
TARGET = 3


async def _run_live_chaos(seed: int):
    """One 4-validator live run under asymmetric partition + clock skew.
    Returns (header_times, own_precommit_times, fault_counters,
    per-height hash agreement)."""
    chaos = ChaosNetwork(ChaosConfig(seed=seed, clock_skew_ms=80.0))
    genesis_ns = 1_700_000_000_000_000_000  # make_genesis's fixed stamp
    # every validator: frozen behind genesis, then chaos-skewed (±80ms)
    net = LocalNetwork(
        4,
        config=fast_config(),
        chaos=chaos,
        base_clock=ManualClock(genesis_ns - 500 * MS),
    )
    assert net.genesis.genesis_time_ns == genesis_ns
    # node0's votes never reach node1; node1's reach node0 (half-open link)
    chaos.partition_oneway("node0", "node1")

    precommit_ts: dict[tuple[int, int], int] = {}  # (height, val) -> ts
    await net.start()
    try:
        for i, node in enumerate(net.nodes):
            orig = node.cs.broadcast_hook

            def hook(msg, _i=i, _orig=orig):
                if (
                    isinstance(msg, m.VoteMessage)
                    and msg.vote.type == SignedMsgType.PRECOMMIT
                    and not msg.vote.block_id.is_nil()
                ):
                    precommit_ts.setdefault(
                        (msg.vote.height, _i), msg.vote.timestamp_ns
                    )
                _orig(msg)

            node.cs.broadcast_hook = hook
        # liveness: the half-open link must not stall ANYONE. node1
        # misses node0-origin proposals, but the harness's catch-up
        # relay (the part-gossip/block-sync stand-in) replays decided
        # heights, so ALL FOUR nodes must reach the target — the
        # pipelined-ingest chaos matrix relies on runs terminating.
        import asyncio

        await asyncio.gather(
            *(n.cs.wait_for_height(TARGET, 60) for n in net.nodes)
        )
        header_times = {}
        agree = True
        for h in range(1, TARGET + 1):
            stores = [n.block_store for n in net.nodes]
            assert all(s.height() >= h for s in stores), (
                f"a node is missing committed height {h}"
            )
            hashes = {s.load_block(h).hash() for s in stores}
            agree &= len(hashes) == 1
            header_times[h] = net.nodes[0].block_store.load_block(h).header.time_ns
    finally:
        await net.stop()
    return header_times, dict(precommit_ts), dict(chaos.faults), agree


class TestLiveConsensusChaos:
    @pytest.mark.asyncio
    async def test_asym_partition_and_clock_skew_bit_reproducible(self):
        """Acceptance: live consensus under an asymmetric partition and
        per-validator clock skew (a) keeps committing with all nodes
        agreeing per height, and (b) produces IDENTICAL vote/block
        timestamps across two runs with the same seed."""
        t1, v1, f1, agree1 = await _run_live_chaos(seed=424)
        assert agree1, "nodes diverged per height under chaos"
        assert f1["asym_drop"] > 0, "asymmetric partition never bit"
        assert f1["clock_skew"] == 4, "per-validator skewed clocks not handed out"
        genesis_ns = 1_700_000_000_000_000_000
        # the closed form the deterministic clock guarantees:
        # block h is stamped genesis + (h-1)ms, votes on it at +1ms more
        assert t1 == {h: genesis_ns + (h - 1) * MS for h in t1}
        for (h, _val), ts in v1.items():
            assert ts == genesis_ns + h * MS

        t2, v2, f2, agree2 = await _run_live_chaos(seed=424)
        assert agree2
        assert t2 == t1, "block timestamps not reproducible under same seed"
        # every (height, validator) precommit observed in both runs has a
        # bit-identical timestamp
        common = v1.keys() & v2.keys()
        assert common
        assert {k: v1[k] for k in common} == {k: v2[k] for k in common}
