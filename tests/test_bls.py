"""BLS12-381 aggregate-commit path: pure-Python primitives, golden /
pinning vectors, key types, aggregate-commit wire + verification
equivalence, mixed-scheme hub partitioning, PoP rogue-key defense, and
(slow-marked) the JAX limb-kernel bit-identity + the live aggregate
consensus bit-reproducibility run.

Budget note: every pairing-kernel compile lives behind the `slow` mark;
the fast tests below run pure-Python with small validator counts and
share module-scoped fixtures so the whole fast set stays at a few
seconds of pairing work.
"""

from __future__ import annotations

import asyncio
from dataclasses import replace

import pytest

from tendermint_tpu import testing
from tendermint_tpu.crypto import bls, bls_math
from tendermint_tpu.crypto.bls import BLSPrivKey, BLSPubKey
from tendermint_tpu.types import validation
from tendermint_tpu.types.block import (
    BLOCK_ID_FLAG_COMMIT,
    Commit,
    aggregate_commit,
)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.validation import InvalidCommitError

CHAIN = "bls-chain"


# ---------------------------------------------------------------------------
# golden vectors / derived constants


class TestGoldenVectors:
    def test_expand_message_xmd_rfc9380(self):
        """RFC 9380 appendix K.1 (SHA-256, 0x20-byte outputs) — pins the
        expander byte-exactly against the published vectors."""
        dst = b"QUUX-V01-CS02-with-expander-SHA256-128"
        assert (
            bls_math.expand_message_xmd(b"", dst, 0x20).hex()
            == "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"
        )
        assert (
            bls_math.expand_message_xmd(b"abc", dst, 0x20).hex()
            == "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"
        )

    def test_derived_constants_match_published_values(self):
        """The import-time derivations (twist order disambiguation,
        trace identities) must land on the published BLS12-381
        cofactors — a wrong generator or modulus would shift these."""
        assert bls_math.H1_COFACTOR == 0x396C8C005555E1568C00AAAB0000AAAB
        assert bls_math.H2_COFACTOR == (
            0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5
        )

    def test_generators_have_order_r(self):
        assert bls_math.g1_in_subgroup(bls_math.G1_GEN)
        assert bls_math.g2_in_subgroup(bls_math.G2_GEN)

    def test_pairing_bilinear_and_nondegenerate(self):
        e = bls_math.pairing(bls_math.G1_GEN, bls_math.G2_GEN)
        assert e != bls_math.F12_ONE
        # e(2P, 3Q) == e(P, Q)^6
        e23 = bls_math.pairing(
            bls_math.g1_mul(bls_math.G1_GEN, 2),
            bls_math.g2_mul(bls_math.G2_GEN, 3),
        )
        assert e23 == bls_math.f12_pow(e, bin(6)[2:])

    def test_implementation_pinning_vectors(self):
        """Frozen outputs of the framework scheme (keygen, sign, PoP,
        hash-to-point) for a fixed seed/message: any refactor of the
        field/tower/map code must keep these byte-identical — this is
        what pins the JAX limb path and future optimizations."""
        k = BLSPrivKey(b"\x07" * 32)
        assert k.pub_key().bytes().hex() == (
            "94f62c023df56df654510b9fb69de65bc6822a4912ead016ed08e761aac3ce32"
            "6d3dbe0ef05a8ab51e081826087b09cc"
        )
        assert k.sign(b"tmtpu-bls-golden").hex() == (
            "8a0ba06f01194028b6c69937427557f17e53b569f3998fde9310a6bd6b42fbfc"
            "d63e4cf0bab9c122ee368aebeae655d0090e0202b4d7895dfaed1ec98575d567"
            "d9e0d335aaa5779112f71b8d2cd4fd3bdd34499d0963152a016821a3584aa4ab"
        )
        assert k.pop_prove().hex() == (
            "8349f898d2006845023f0ad9fd7dcc195ca51340e8db2449282cc421f1106616"
            "6dc82b32eeb96c70e9c77375d2e38f4913afd5e326fe233dc4571d6a9d2c4419"
            "18004d5e928feb010203492b582a4014959fd11dedb6a5000d3f5385e30cf7b4"
        )
        h = bls_math.hash_to_point_g2(b"tmtpu-bls-golden")
        assert bls_math.g2_compress(h).hex() == (
            "a7ada6f7f5d5c1b9ec9e51fd56f3a679567d74dcfb0670c67bd805cab397e782"
            "c930d9d86b22fa25c4ef0f70f5b2405810ab7ca81d967ba6c4d912d24169e19a"
            "e41cffc4859dcdb66baa71b5b8a71376268e6930b47af5f1276bfb2e32f74e44"
        )


# ---------------------------------------------------------------------------
# serialization / point validation


class TestSerialization:
    def test_g1_g2_round_trip(self):
        k = BLSPrivKey(b"\x11" * 32)
        pk = bls_math.g1_decompress(k.pub_key().bytes())
        assert bls_math.g1_compress(pk) == k.pub_key().bytes()
        sig = k.sign(b"rt")
        assert bls_math.g2_compress(bls_math.g2_decompress(sig)) == sig
        assert bls_math.g1_decompress(bls_math.g1_compress(None)) is None
        assert bls_math.g2_decompress(bls_math.g2_compress(None)) is None

    def test_malformed_encodings_rejected(self):
        good = BLSPrivKey(b"\x11" * 32).pub_key().bytes()
        with pytest.raises(ValueError):
            bls_math.g1_decompress(bytes(48))  # compression bit unset
        with pytest.raises(ValueError):
            bls_math.g1_decompress(b"\xc0" + b"\x01" * 47)  # dirty infinity
        x_ge_p = bytearray((bls_math.P).to_bytes(48, "big"))
        x_ge_p[0] |= 0x80
        with pytest.raises(ValueError):
            bls_math.g1_decompress(bytes(x_ge_p))
        # x not on curve: flip bytes until decompress refuses
        bad = bytearray(good)
        bad[47] ^= 0x01
        try:
            bls_math.g1_decompress(bytes(bad))
        except ValueError:
            pass  # either off-curve (raises) or another valid x — both fine

    def test_non_subgroup_point_rejected_by_pubkey_cache(self):
        """E(Fq) has a large cofactor: almost every on-curve point is
        OUTSIDE G1. Such a pubkey must be unusable."""
        x = 1
        while True:
            y2 = (x * x * x + bls_math.B1) % bls_math.P
            y = pow(y2, (bls_math.P + 1) // 4, bls_math.P)
            if y * y % bls_math.P == y2:
                pt = (x, y)
                if not bls_math.g1_in_subgroup(pt):
                    break
            x += 1
        enc = bls_math.g1_compress(pt)
        assert bls.pubkey_point(enc) is None
        assert not BLSPubKey(enc).verify_signature(b"m", bytes(96))

    def test_pubkey_registry_and_proto(self):
        from tendermint_tpu import crypto

        pk = BLSPrivKey(b"\x22" * 32).pub_key()
        again = crypto.pubkey_from_type_and_bytes("bls12381", pk.bytes())
        assert again == pk and isinstance(again, BLSPubKey)
        assert crypto.pubkey_from_proto(crypto.pubkey_to_proto(pk)) == pk
        assert len(pk.address()) == 20


# ---------------------------------------------------------------------------
# signature scheme


class TestSignatures:
    def test_sign_verify_and_tamper(self):
        k = BLSPrivKey(b"\x33" * 32)
        pk = k.pub_key()
        sig = k.sign(b"payload")
        assert pk.verify_signature(b"payload", sig)
        assert not pk.verify_signature(b"payloae", sig)
        assert not pk.verify_signature(b"payload", sig[:-1] + bytes([sig[-1] ^ 1]))
        assert not pk.verify_signature(b"payload", sig[:32])
        other = BLSPrivKey(b"\x34" * 32).pub_key()
        assert not other.verify_signature(b"payload", sig)

    def test_aggregate_round_trip_and_rejections(self):
        keys = [BLSPrivKey(bytes([40 + i]) * 32) for i in range(3)]
        msgs = [b"m%d" % i for i in range(3)]
        sigs = [k.sign(m) for k, m in zip(keys, msgs)]
        agg = bls.aggregate_signatures(sigs)
        pubs = [k.pub_key() for k in keys]
        assert bls.aggregate_verify(pubs, msgs, agg)
        assert not bls.aggregate_verify(pubs, msgs[::-1], agg)
        assert not bls.aggregate_verify(pubs[::-1], msgs, agg)
        assert not bls.aggregate_verify(pubs[:2], msgs[:2], agg)
        assert not bls.aggregate_verify(pubs, msgs, sigs[0])
        # aggregation order must not matter (point addition commutes)
        assert bls.aggregate_signatures(sigs[::-1]) == agg

    def test_pop_prove_verify(self):
        k = BLSPrivKey(b"\x55" * 32)
        pop = k.pop_prove()
        assert k.pub_key().pop_verify(pop)
        # a PoP is domain-separated from ordinary signatures
        assert not k.pub_key().pop_verify(k.sign(k.pub_key().bytes()))
        # another key's PoP proves nothing for this key
        other = BLSPrivKey(b"\x56" * 32)
        assert not k.pub_key().pop_verify(other.pop_prove())


# ---------------------------------------------------------------------------
# aggregate commit: wire + verification equivalence


@pytest.fixture(scope="module")
def bls_commit():
    """4-validator BLS set with one commit (one nil vote): the shared
    fixture every aggregate test reuses — pairings are the budget."""
    vals, by_addr = testing.make_validator_set(4, key_types=("bls12381",))
    bid = testing.make_block_id(b"agg")
    commit = testing.make_commit(
        CHAIN, 5, 0, bid, vals, by_addr, nil_indices=frozenset({2})
    )
    return vals, by_addr, bid, commit


class TestAggregateCommit:
    def test_wire_round_trip_hash_and_validate(self, bls_commit):
        vals, _, bid, commit = bls_commit
        agg = aggregate_commit(commit, vals)
        assert agg.is_aggregate() and len(agg.agg_sig) == 96
        assert all(cs.signature == b"" for cs in agg.signatures)
        assert Commit.decode(agg.encode()) == agg
        agg.validate_basic()
        # the aggregate is commit content: hashes must differ from the
        # per-sig form AND from a different aggregate
        assert agg.hash() != commit.hash()
        other = replace(agg, agg_sig=bytes(96))
        assert other.hash() != agg.hash()
        # per-sig wire carries ~n sig bytes; aggregate carries one
        assert len(agg.encode()) < len(commit.encode()) - 3 * 90
        # deterministic: same votes in -> byte-identical aggregate out
        assert aggregate_commit(commit, vals).encode() == agg.encode()

    def test_validate_rejects_mixed_forms(self, bls_commit):
        vals, _, _, commit = bls_commit
        agg = aggregate_commit(commit, vals)
        # aggregate commit smuggling a per-validator signature
        sigs = list(agg.signatures)
        sigs[0] = replace(sigs[0], signature=commit.signatures[0].signature)
        with pytest.raises(ValueError, match="must not carry"):
            replace(agg, signatures=tuple(sigs)).validate_basic()
        with pytest.raises(ValueError, match="aggregate signature size"):
            replace(agg, agg_sig=b"\x01" * 95).validate_basic()

    def test_accept_equivalence_and_rejections(self, bls_commit):
        """The acceptance surface: aggregate verify_commit accepts
        exactly where per-signature verification accepts, and rejects
        forged / bitmap-mismatch / per-sig-tampered variants."""
        vals, by_addr, bid, commit = bls_commit
        validation.verify_commit(CHAIN, vals, bid, 5, commit)
        agg = aggregate_commit(commit, vals)
        validation.verify_commit(CHAIN, vals, bid, 5, agg)
        validation.verify_commit_light(CHAIN, vals, bid, 5, agg)
        validation.verify_commit_light_trusting(CHAIN, vals, agg)
        # forged aggregate
        bad = replace(agg, agg_sig=agg.agg_sig[:-1] + bytes([agg.agg_sig[-1] ^ 1]))
        with pytest.raises(InvalidCommitError):
            validation.verify_commit(CHAIN, vals, bid, 5, bad)
        # bitmap mismatch: nil vote re-flagged as a block vote
        sigs = list(agg.signatures)
        sigs[2] = replace(sigs[2], flag=BLOCK_ID_FLAG_COMMIT)
        with pytest.raises(InvalidCommitError):
            validation.verify_commit(
                CHAIN, vals, bid, 5, replace(agg, signatures=tuple(sigs))
            )

    def test_absent_signer_forgery_rejected(self, bls_commit):
        """A commit whose aggregate was built WITHOUT validator 3's
        signature cannot claim index 3 signed."""
        vals, by_addr, bid, _ = bls_commit
        commit = testing.make_commit(
            CHAIN, 5, 0, bid, vals, by_addr, absent_indices=frozenset({3})
        )
        agg = aggregate_commit(commit, vals)
        validation.verify_commit(CHAIN, vals, bid, 5, agg)
        sigs = list(agg.signatures)
        sigs[3] = replace(
            sigs[0], validator_address=vals.validators[3].address
        )
        with pytest.raises(InvalidCommitError):
            validation.verify_commit(
                CHAIN, vals, bid, 5, replace(agg, signatures=tuple(sigs))
            )

    def test_insufficient_power_rejected_before_pairing(self, bls_commit):
        vals, by_addr, bid, _ = bls_commit
        commit = testing.make_commit(
            CHAIN, 5, 0, bid, vals, by_addr,
            absent_indices=frozenset({1, 2, 3}),
        )
        agg = aggregate_commit(commit, vals)
        with pytest.raises(InvalidCommitError, match="insufficient voting power"):
            validation.verify_commit(CHAIN, vals, bid, 5, agg)

    def test_range_verify_handles_aggregate_entries(self, bls_commit):
        vals, by_addr, bid, commit = bls_commit
        agg = aggregate_commit(commit, vals)
        bid2 = testing.make_block_id(b"agg2")
        c2 = aggregate_commit(
            testing.make_commit(CHAIN, 6, 0, bid2, vals, by_addr), vals
        )
        validation.verify_commit_range(
            CHAIN, [(vals, bid, 5, agg), (vals, bid2, 6, c2)]
        )
        bad = replace(c2, agg_sig=agg.agg_sig)
        with pytest.raises(InvalidCommitError) as ei:
            validation.verify_commit_range(
                CHAIN, [(vals, bid, 5, agg), (vals, bid2, 6, bad)]
            )
        assert ei.value.failed_index == 1


# ---------------------------------------------------------------------------
# mixed-scheme correctness (satellite)


class TestMixedScheme:
    def test_mixed_commit_verifies_and_matches_sequential(self):
        """part ed25519 / part BLS validator set: the scheme-partitioned
        funnel's verdicts are identical to sequential per-sig verify."""
        vals, by_addr = testing.make_validator_set(
            4, key_types=("bls12381", "ed25519")
        )
        bid = testing.make_block_id(b"mixed")
        commit = testing.make_commit(CHAIN, 7, 0, bid, vals, by_addr)
        validation.verify_commit(CHAIN, vals, bid, 7, commit)
        # tamper one signature of EACH scheme; the partitioned batch
        # path must attribute exactly like per-sig verification
        for idx in (0, 1):
            sigs = list(commit.signatures)
            s = sigs[idx].signature
            sigs[idx] = replace(sigs[idx], signature=s[:-1] + bytes([s[-1] ^ 1]))
            bad = replace(commit, signatures=tuple(sigs))
            with pytest.raises(InvalidCommitError, match=f"index {idx}"):
                validation.verify_commit(CHAIN, vals, bid, 7, bad)
            seq_ok = [
                vals.get_by_index(i).pub_key.verify_signature(
                    bad.vote_sign_bytes(CHAIN, i), cs.signature
                )
                for i, cs in enumerate(bad.signatures)
            ]
            assert [i for i, ok in enumerate(seq_ok) if not ok] == [idx]

    def test_hub_partitions_mixed_batch(self):
        from tendermint_tpu.crypto import verify_hub

        vals, by_addr = testing.make_validator_set(
            4, key_types=("bls12381", "ed25519")
        )
        bid = testing.make_block_id(b"hubmix")
        commit = testing.make_commit(CHAIN, 8, 0, bid, vals, by_addr)
        hub = verify_hub.acquire_hub(window_ms=1.0)
        try:
            items = [
                (
                    vals.get_by_index(i).pub_key,
                    commit.vote_sign_bytes(CHAIN, i),
                    cs.signature,
                )
                for i, cs in enumerate(commit.signatures)
            ]
            assert hub.verify_many(items) == [True] * 4
            # and through the full commit funnel (hub path)
            validation.verify_commit(CHAIN, vals, bid, 8, commit)
        finally:
            verify_hub.release_hub()

    def test_aggregate_refuses_non_bls_signer(self):
        vals, by_addr = testing.make_validator_set(
            4, key_types=("bls12381", "ed25519")
        )
        bid = testing.make_block_id(b"noagg")
        commit = testing.make_commit(CHAIN, 9, 0, bid, vals, by_addr)
        with pytest.raises(ValueError, match="not bls12381"):
            aggregate_commit(commit, vals)

    def test_aggregate_verify_rejects_non_bls_included_signer(self, bls_commit):
        """An aggregate commit whose included slot resolves to a non-BLS
        validator must reject (satellite: aggregate commits reject when
        any included signer is non-BLS)."""
        vals, by_addr, bid, commit = bls_commit
        agg = aggregate_commit(commit, vals)
        mixed_vals, _ = testing.make_validator_set(
            4, key_types=("ed25519",), seed=b"other"
        )
        with pytest.raises(InvalidCommitError, match="non-BLS signer"):
            validation.verify_commit(CHAIN, mixed_vals, bid, 5, agg)


# ---------------------------------------------------------------------------
# PoP / genesis (rogue-key defense)


class TestGenesisPop:
    def test_genesis_requires_valid_pop_for_bls(self):
        k = BLSPrivKey(b"\x66" * 32)
        gv_ok = GenesisValidator(k.pub_key(), 10, "v0", pop=k.pop_prove())
        doc = GenesisDoc(chain_id=CHAIN, validators=[gv_ok])
        doc.validate_basic()
        assert len(doc.validator_set()) == 1
        # missing PoP
        doc_missing = GenesisDoc(
            chain_id=CHAIN, validators=[GenesisValidator(k.pub_key(), 10, "v0")]
        )
        with pytest.raises(ValueError, match="missing proof of possession"):
            doc_missing.validator_set()
        # wrong key's PoP (the rogue-key shape: an attacker publishing a
        # derived key cannot prove possession of its secret)
        rogue = GenesisValidator(
            k.pub_key(), 10, "v0", pop=BLSPrivKey(b"\x67" * 32).pop_prove()
        )
        with pytest.raises(ValueError, match="invalid proof of possession"):
            GenesisDoc(chain_id=CHAIN, validators=[rogue]).validate_basic()

    def test_abci_validator_update_requires_pop(self):
        """Rogue-key defense at the POST-genesis entry point: an ABCI
        validator update adding a bls12381 key without a valid PoP is
        rejected (a forged commit controls its own timestamps, so equal
        sign-bytes — and thus the rogue-key combination — are always
        available to an attacker; PoP is the load-bearing defense)."""
        from tendermint_tpu.abci import types as abci
        from tendermint_tpu.state.execution import validator_updates_to_validators
        from tendermint_tpu.types.params import ConsensusParams, ValidatorParams

        params = ConsensusParams(
            validator=ValidatorParams(pub_key_types=("ed25519", "bls12381"))
        )
        k = BLSPrivKey(b"\x69" * 32)
        good = abci.ValidatorUpdate("bls12381", k.pub_key().bytes(), 10, k.pop_prove())
        out = validator_updates_to_validators((good,), params)
        assert out[0].pub_key == k.pub_key()
        # wire round-trip keeps the pop
        assert abci.ValidatorUpdate.decode(good.encode()) == good
        missing = abci.ValidatorUpdate("bls12381", k.pub_key().bytes(), 10)
        with pytest.raises(ValueError, match="proof of possession"):
            validator_updates_to_validators((missing,), params)
        rogue = abci.ValidatorUpdate(
            "bls12381", k.pub_key().bytes(), 10,
            BLSPrivKey(b"\x6a" * 32).pop_prove(),
        )
        with pytest.raises(ValueError, match="proof of possession"):
            validator_updates_to_validators((rogue,), params)
        # removals (power 0) don't need a PoP
        removal = abci.ValidatorUpdate("bls12381", k.pub_key().bytes(), 0)
        assert validator_updates_to_validators((removal,), params)[0].voting_power == 0

    def test_genesis_json_round_trips_pop(self):
        k = BLSPrivKey(b"\x68" * 32)
        doc = GenesisDoc(
            chain_id=CHAIN,
            validators=[GenesisValidator(k.pub_key(), 10, "v0", pop=k.pop_prove())],
        )
        again = GenesisDoc.from_json(doc.to_json())
        assert again.validators[0].pop == doc.validators[0].pop
        assert again.validators[0].pub_key == k.pub_key()
        # ed25519 validators stay pop-free in JSON
        from tendermint_tpu.crypto.ed25519 import Ed25519PrivKey

        ed = Ed25519PrivKey(b"\x01" * 32)
        doc2 = GenesisDoc(
            chain_id=CHAIN, validators=[GenesisValidator(ed.pub_key(), 10)]
        )
        assert "pop" not in doc2.to_json()
        GenesisDoc.from_json(doc2.to_json()).validate_basic()


# ---------------------------------------------------------------------------
# hub aggregate chokepoint


def test_pairing_kernel_bucket_guard_raises_without_compile():
    """A non-bucket shape must raise loudly (not `assert` — python -O
    strips those) BEFORE any kernel is built: an over-cap batch slipping
    through would cold-compile a minutes-scale pairing kernel inline.
    verify_items chunks at _MAX_ITEMS so it never constructs one."""
    from tendermint_tpu.crypto.tpu import bls_pairing

    with pytest.raises(ValueError, match="non-bucket"):
        bls_pairing._get_kernel(300, 2)
    with pytest.raises(ValueError, match="non-bucket"):
        bls_pairing._get_kernel(4, 3)
    assert bls_pairing.bucket_items(300) == bls_pairing._MAX_ITEMS  # caps


class TestHubAggregate:
    def test_verify_aggregate_caches_verdicts(self, bls_commit):
        from tendermint_tpu.crypto import verify_hub

        vals, _, bid, commit = bls_commit
        agg = aggregate_commit(commit, vals)
        pubs, msgs = [], []
        for i, cs in enumerate(agg.signatures):
            if cs.is_absent():
                continue
            pubs.append(vals.get_by_index(i).pub_key)
            msgs.append(agg.vote_sign_bytes(CHAIN, i))
        hub = verify_hub.acquire_hub(window_ms=1.0)
        try:
            assert verify_hub.verify_aggregate(pubs, msgs, agg.agg_sig)
            before = hub.stats()["cache_hits"]
            assert verify_hub.verify_aggregate(pubs, msgs, agg.agg_sig)
            assert hub.stats()["cache_hits"] == before + 1
            # a different signer set is a different cache key
            assert not verify_hub.verify_aggregate(pubs[:-1], msgs[:-1], agg.agg_sig)
        finally:
            verify_hub.release_hub()

    def test_verify_aggregate_without_hub(self, bls_commit):
        from tendermint_tpu.crypto import verify_hub

        vals, _, _, commit = bls_commit
        agg = aggregate_commit(commit, vals)
        pubs, msgs = [], []
        for i, cs in enumerate(agg.signatures):
            if cs.is_absent():
                continue
            pubs.append(vals.get_by_index(i).pub_key)
            msgs.append(agg.vote_sign_bytes(CHAIN, i))
        assert verify_hub.running_hub() is None
        assert verify_hub.verify_aggregate(pubs, msgs, agg.agg_sig)
        assert not verify_hub.verify_aggregate(pubs, list(reversed(msgs)), agg.agg_sig)


# ---------------------------------------------------------------------------
# slow: 150-validator equivalence, JAX bit-identity, live consensus


@pytest.mark.slow
class TestAggregate150:
    def test_150_validator_equivalence(self):
        """The acceptance shape at full scale: a 150-validator chain's
        aggregate commit accepts exactly when per-signature verification
        accepts, and a single forged position rejects both forms."""
        vals, by_addr = testing.make_validator_set(150, key_types=("bls12381",))
        bid = testing.make_block_id(b"agg150")
        commit = testing.make_commit(CHAIN, 11, 0, bid, vals, by_addr)
        validation.verify_commit(CHAIN, vals, bid, 11, commit)
        agg = aggregate_commit(commit, vals)
        validation.verify_commit(CHAIN, vals, bid, 11, agg)
        validation.verify_commit_light(CHAIN, vals, bid, 11, agg)
        # wire: one aggregate vs 150 signatures
        assert len(agg.encode()) < len(commit.encode()) - 149 * 90
        # forge one signer: build the aggregate from 149 real sigs + one
        # signature by a key OUTSIDE the set claiming index 17
        outsider = BLSPrivKey(b"\x99" * 32)
        sigs = [
            cs.signature if i != 17
            else outsider.sign(commit.vote_sign_bytes(CHAIN, 17))
            for i, cs in enumerate(commit.signatures)
        ]
        forged = replace(
            agg, agg_sig=bls.aggregate_signatures(sigs)
        )
        with pytest.raises(InvalidCommitError):
            validation.verify_commit(CHAIN, vals, bid, 11, forged)


@pytest.mark.slow
class TestJaxBitIdentity:
    """The JAX limb path against the pure-Python reference. One shared
    kernel compile (the (2, 2) bucket) serves every check here."""

    def test_field_tower_bit_identical(self):
        import numpy as np
        import jax.numpy as jnp

        from tendermint_tpu.crypto.tpu import bls_field as F

        import random

        rnd = random.Random(1234)

        def to_l(v):
            return jnp.asarray(F.int_to_limbs(v))

        for _ in range(8):
            a, b = rnd.randrange(bls_math.P), rnd.randrange(bls_math.P)
            assert F.limbs_to_int(np.asarray(F.mul(to_l(a), to_l(b)))) == a * b % bls_math.P
            assert F.limbs_to_int(np.asarray(F.sub(to_l(a), to_l(b)))) == (a - b) % bls_math.P
        # adversarial max weak-normal limbs: the f32 GEMM bound edge
        la = jnp.full((F.LIMBS,), 526, jnp.int32)
        va = F.limbs_to_int(np.asarray(la))
        assert F.limbs_to_int(np.asarray(F.mul(la, la))) == va * va % bls_math.P
        assert int(np.asarray(F.mul(la, la)).max()) <= 526
        a = rnd.randrange(1, bls_math.P)
        assert F.limbs_to_int(np.asarray(F.fp_inv(to_l(a)))) == pow(a, bls_math.P - 2, bls_math.P)
        f = tuple(rnd.randrange(bls_math.P) for _ in range(12))
        g = tuple(rnd.randrange(bls_math.P) for _ in range(12))

        def f12_t(t):
            return jnp.stack(
                [jnp.stack([to_l(t[2 * i]), to_l(t[2 * i + 1])]) for i in range(6)]
            )

        assert F.f12_canonical_ints(F.f12_mul(f12_t(f), f12_t(g))) == bls_math.f12_mul(f, g)
        assert F.f12_canonical_ints(F.f12_inv(f12_t(f))) == bls_math.f12_inv(f)

    def test_pairing_kernel_bit_identical(self):
        from tendermint_tpu.crypto.tpu import bls_pairing

        p = bls_math.g1_mul(bls_math.G1_GEN, 5)
        q = bls_math.g2_mul(bls_math.G2_GEN, 9)
        assert bls_pairing.pairing_f12_ints(p, q) == bls_math.pairing(p, q)

    def test_batched_verify_matches_pure(self):
        from tendermint_tpu.crypto.tpu import bls_pairing

        keys = [BLSPrivKey(bytes([70 + i]) * 32) for i in range(3)]
        msgs = [b"kv-%d" % i for i in range(3)]
        triples = []
        for i, k in enumerate(keys):
            sig = k.sign(msgs[i])
            msg = msgs[i] if i != 1 else b"tampered"
            triples.append(
                (
                    bls.pubkey_point(k.pub_key().bytes()),
                    msg,
                    bls.signature_point(sig),
                )
            )
        kernel = list(bls_pairing.verify_items(triples))
        pure = [
            bls_math.verify(pk, m, sp) for pk, m, sp in triples
        ]
        assert kernel == pure == [True, False, True]

    def test_aggregate_commit_on_kernel_matches_pure(self, monkeypatch):
        """The full aggregate-commit check through the device route
        (TMTPU_BLS_TPU=1) agrees with the pure path, accept and
        reject."""
        monkeypatch.setenv("TMTPU_BLS_TPU", "1")
        from tendermint_tpu.crypto.batch import bls_aggregate_verify

        keys = [BLSPrivKey(bytes([80 + i]) * 32) for i in range(3)]
        msgs = [b"agg-%d" % i for i in range(3)]
        agg = bls.aggregate_signatures([k.sign(m) for k, m in zip(keys, msgs)])
        pubs = [k.pub_key() for k in keys]
        before = dict(bls.STATS)
        assert bls_aggregate_verify(pubs, msgs, agg)
        assert not bls_aggregate_verify(pubs, msgs[::-1], agg)
        # the device route maintains the same operational counters as
        # the pure path (the bls_* families must not read zero on the
        # deployments that enable the kernel)
        assert bls.STATS["aggregate_verifies"] == before["aggregate_verifies"] + 2
        assert bls.STATS["aggregate_signers"] == before["aggregate_signers"] + 6
        assert bls.STATS["aggregate_failures"] == before["aggregate_failures"] + 1
        monkeypatch.setenv("TMTPU_BLS_TPU", "0")
        assert bls.aggregate_verify(pubs, msgs, agg)


@pytest.mark.slow
class TestLiveAggregateConsensus:
    @pytest.mark.asyncio
    async def test_live_bls_aggregate_net_bit_reproducible(self):
        """Acceptance: a live BLS validator net with
        commit_scheme=bls-aggregate commits aggregate-form seen
        commits, and two same-seed runs produce byte-identical blocks
        AND byte-identical aggregate commits (the chaos
        bit-reproducibility surface with the aggregate path ON)."""

        async def run_once():
            from tendermint_tpu.consensus.harness import LocalNetwork, fast_config
            from tendermint_tpu.libs.clock import ManualClock

            MS = 1_000_000
            cfg = fast_config()
            cfg.commit_scheme = "bls-aggregate"
            # byte-identity needs round determinism: pure-Python BLS
            # verifies (~0.25 s each) race fast_config's sub-second
            # timeouts, so different runs can commit in different
            # rounds (a wall-time effect, not an aggregation one).
            # With generous timeouts round 0 always completes, and
            # with 3 equal-power validators +2/3 requires ALL three
            # precommits — the aggregate signer set is exactly
            # deterministic.
            for f in (
                "timeout_propose_ns",
                "timeout_prevote_ns",
                "timeout_precommit_ns",
            ):
                setattr(cfg, f, 60_000 * MS)
            genesis_ns = 1_700_000_000_000_000_000
            net = LocalNetwork(
                3,
                config=cfg,
                base_clock=ManualClock(genesis_ns - 500 * MS),
                key_type="bls12381",
            )
            await net.start()
            try:
                await asyncio.gather(
                    *(n.cs.wait_for_height(2, 240) for n in net.nodes)
                )
                blocks = [
                    net.nodes[0].block_store.load_block(h).encode()
                    for h in (1, 2)
                ]
                seen = net.nodes[0].block_store.load_seen_commit(2)
                commits = [seen.encode()]
                # every node stored the same chain
                for n in net.nodes[1:]:
                    for h in (1, 2):
                        assert (
                            n.block_store.load_block(h).encode() == blocks[h - 1]
                        )
                return blocks, commits, seen
            finally:
                await net.stop()

        blocks1, commits1, seen1 = await run_once()
        assert seen1.is_aggregate(), "seen commit not aggregate under bls-aggregate"
        # height-2 blocks carry the height-1 commit as last_commit: it
        # must be the aggregate form on the wire
        from tendermint_tpu.types.block import Block

        b2 = Block.decode(blocks1[1])
        assert b2.last_commit is not None and b2.last_commit.is_aggregate()
        blocks2, commits2, _ = await run_once()
        assert blocks1 == blocks2, "same-seed aggregate chain not byte-identical"
        assert commits1 == commits2
