"""Consensus state-machine tests (modeled on the reference's
internal/consensus/state_test.go and replay_test.go scenarios)."""

import asyncio
import os
import tempfile

import pytest

from tendermint_tpu.config import ConsensusConfig
from tendermint_tpu.consensus import messages as m
from tendermint_tpu.consensus.harness import (
    LocalNetwork,
    Node,
    fast_config,
    make_genesis,
)
from tendermint_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker
from tendermint_tpu.consensus.types import HeightVoteSet, RoundStep
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.privval import (
    DoubleSignError,
    FilePV,
    MockPV,
    STEP_PRECOMMIT,
)
from tendermint_tpu.testing import make_block_id, make_validator_set, make_vote
from tendermint_tpu.types.keys import SignedMsgType
from tendermint_tpu.types.vote import Vote


# ---------------------------------------------------------------------------
# privval
# ---------------------------------------------------------------------------


class TestFilePV:
    def _mk(self, tmp):
        return FilePV.generate(
            os.path.join(tmp, "key.json"), os.path.join(tmp, "state.json")
        )

    def test_roundtrip(self):
        with tempfile.TemporaryDirectory() as tmp:
            pv = self._mk(tmp)
            pv2 = FilePV.load(pv.key_path, pv.state_path)
            assert pv2.priv_key.bytes() == pv.priv_key.bytes()

    def test_sign_vote_and_double_sign_guard(self):
        with tempfile.TemporaryDirectory() as tmp:
            pv = self._mk(tmp)
            bid = make_block_id(b"a")
            vote = Vote(
                type=SignedMsgType.PRECOMMIT,
                height=5,
                round=0,
                block_id=bid,
                timestamp_ns=1_700_000_000_000_000_000,
                validator_address=pv.get_pub_key().address(),
                validator_index=0,
            )
            signed = pv.sign_vote("c", vote)
            assert pv.get_pub_key().verify_signature(
                vote.sign_bytes("c"), signed.signature
            )
            # identical re-sign: same signature returned (crash-recovery path)
            again = pv.sign_vote("c", vote)
            assert again.signature == signed.signature
            # conflicting block at same HRS: refused
            vote_b = Vote(**{**vote.__dict__, "block_id": make_block_id(b"b")})
            with pytest.raises(DoubleSignError):
                pv.sign_vote("c", vote_b)
            # differs only in timestamp: allowed — old signature AND old
            # timestamp are returned, so the result still verifies
            vote_ts = Vote(
                **{**vote.__dict__, "timestamp_ns": vote.timestamp_ns + 5}
            )
            resigned = pv.sign_vote("c", vote_ts)
            assert resigned.signature == signed.signature
            assert resigned.timestamp_ns == vote.timestamp_ns
            assert pv.get_pub_key().verify_signature(
                resigned.sign_bytes("c"), resigned.signature
            )

    def test_guard_survives_restart(self):
        with tempfile.TemporaryDirectory() as tmp:
            pv = self._mk(tmp)
            bid = make_block_id(b"a")
            vote = Vote(
                type=SignedMsgType.PRECOMMIT,
                height=7,
                round=1,
                block_id=bid,
                timestamp_ns=1_700_000_000_000_000_000,
                validator_address=pv.get_pub_key().address(),
                validator_index=0,
            )
            pv.sign_vote("c", vote)
            pv2 = FilePV.load(pv.key_path, pv.state_path)
            assert pv2.last_sign_state.height == 7
            assert pv2.last_sign_state.step == STEP_PRECOMMIT
            lower = Vote(**{**vote.__dict__, "round": 0})
            with pytest.raises(DoubleSignError):
                pv2.sign_vote("c", lower)


# ---------------------------------------------------------------------------
# HeightVoteSet
# ---------------------------------------------------------------------------


class TestHeightVoteSet:
    def test_rounds_and_catchup(self):
        vals, keys = make_validator_set(4)
        hvs = HeightVoteSet("c", 3, vals)
        key0 = keys[vals.validators[0].address]
        bid = make_block_id()
        v = make_vote("c", key0, 0, 3, 5, SignedMsgType.PREVOTE, bid)
        # round 5 not open, no peer claim → dropped silently
        assert hvs.add_vote(v, "p1") is False
        hvs.set_peer_maj23(5, SignedMsgType.PREVOTE, "p1")
        assert hvs.add_vote(v, "p1") is True
        assert hvs.prevotes(5).get_vote(0) == v

    def test_pol_info(self):
        vals, keys = make_validator_set(3)
        hvs = HeightVoteSet("c", 1, vals)
        hvs.set_round(1)
        bid = make_block_id()
        for i, val in enumerate(vals.validators):
            v = make_vote(
                "c", keys[val.address], i, 1, 1, SignedMsgType.PREVOTE, bid
            )
            assert hvs.add_vote(v)
        r, pol_bid = hvs.pol_info()
        assert r == 1 and pol_bid == bid


# ---------------------------------------------------------------------------
# TimeoutTicker
# ---------------------------------------------------------------------------


class TestTicker:
    @pytest.mark.asyncio
    async def test_fires_and_replaces(self):
        t = TimeoutTicker()
        t.schedule(TimeoutInfo(10_000_000, 1, 0, RoundStep.PROPOSE))
        # newer HRS replaces
        t.schedule(TimeoutInfo(5_000_000, 1, 1, RoundStep.PROPOSE))
        ti = await asyncio.wait_for(t.tock.get(), 1.0)
        assert ti.round == 1
        # stale schedule ignored while pending
        t.schedule(TimeoutInfo(5_000_000, 2, 0, RoundStep.PREVOTE_WAIT))
        t.schedule(TimeoutInfo(60_000_000_000, 1, 0, RoundStep.PROPOSE))
        ti = await asyncio.wait_for(t.tock.get(), 1.0)
        assert ti.height == 2
        t.stop()


# ---------------------------------------------------------------------------
# message codec
# ---------------------------------------------------------------------------


class TestMessages:
    def test_roundtrip_all(self):
        vals, keys = make_validator_set(2)
        key0 = keys[vals.validators[0].address]
        bid = make_block_id()
        vote = make_vote("c", key0, 0, 4, 2, SignedMsgType.PRECOMMIT, bid)
        ba = BitArray.from_indices(8, [1, 5])
        msgs = [
            m.NewRoundStepMessage(4, 2, 3, 17, -1),
            m.NewValidBlockMessage(4, 2, (3, b"\x01" * 32), ba, True),
            m.VoteMessage(vote),
            m.HasVoteMessage(4, 2, SignedMsgType.PREVOTE, 1),
            m.VoteSetMaj23Message(4, 2, SignedMsgType.PREVOTE, bid),
            m.VoteSetBitsMessage(4, 2, SignedMsgType.PRECOMMIT, bid, ba),
            m.ProposalPOLMessage(4, 1, ba),
        ]
        for msg in msgs:
            assert m.decode_message(m.encode_message(msg)) == msg

    def test_wal_wrapping(self):
        ti = TimeoutInfo(1_000_000, 5, 1, RoundStep.PREVOTE_WAIT)
        out, peer = m.decode_wal_message(m.encode_wal_message(ti))
        assert out == ti and peer is None
        msg = m.HasVoteMessage(9, 0, SignedMsgType.PREVOTE, 3)
        out, peer = m.decode_wal_message(m.encode_wal_message(msg, "peer-1"))
        assert out == msg and peer == "peer-1"


# ---------------------------------------------------------------------------
# end-to-end consensus
# ---------------------------------------------------------------------------


class TestConsensus:
    @pytest.mark.asyncio
    async def test_single_validator_produces_blocks(self):
        net = LocalNetwork(1)
        await net.start()
        try:
            await net.wait_for_height(3, timeout=20)
            node = net.nodes[0]
            assert node.block_store.height() >= 3
            blk = node.block_store.load_block(2)
            assert blk is not None
            assert blk.last_commit.height == 1
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_four_validators_reach_consensus(self):
        net = LocalNetwork(4)
        await net.start()
        try:
            await net.wait_for_height(3, timeout=30)
            hashes = {n.block_store.load_block(2).hash() for n in net.nodes}
            assert len(hashes) == 1, "nodes committed different blocks"
            # all four validators should be signing
            commit = net.nodes[0].block_store.load_seen_commit(2)
            signed = sum(1 for s in commit.signatures if s.is_commit())
            assert signed >= 3
        finally:
            await net.stop()

    @pytest.mark.asyncio
    async def test_consensus_with_txs(self):
        net = LocalNetwork(2)
        await net.start()
        try:
            await net.wait_for_height(2, timeout=20)
        finally:
            await net.stop()
        # blocks were produced and committed identically
        b1 = net.nodes[0].block_store.load_block(1)
        b2 = net.nodes[1].block_store.load_block(1)
        assert b1.hash() == b2.hash()

    @pytest.mark.asyncio
    async def test_one_node_down_still_commits(self):
        """3 of 4 validators (>2/3 power) keep committing."""
        net = LocalNetwork(4)
        # node 3 never starts its consensus SM: simulate a down validator
        down = net.nodes.pop(3)
        await net.start()
        try:
            await net.wait_for_height(2, timeout=30)
            commit = net.nodes[0].block_store.load_seen_commit(1)
            signed = sum(1 for s in commit.signatures if s.is_commit())
            assert signed == 3
        finally:
            await net.stop()


class TestWALReplay:
    @pytest.mark.asyncio
    async def test_crash_and_resume(self):
        """Run a single-validator chain, stop it, restart from the same
        stores+WAL, verify it continues from the committed height."""
        genesis, keys = make_genesis(1)
        with tempfile.TemporaryDirectory() as wal_dir:
            node = Node(genesis, keys[0], wal_dir=wal_dir)
            await node.start()
            await node.cs.wait_for_height(2, timeout=20)
            height_before = node.block_store.height()
            await node.stop()

            # restart reusing the same stores and WAL (fresh SM)
            node2 = Node(genesis, keys[0], wal_dir=wal_dir)
            node2.block_store = node.block_store
            node2.state_store = node.state_store
            node2.app = node.app
            from tendermint_tpu.proxy import AppConns

            node2.app_conns = AppConns.local(node.app)
            await node2.start()
            try:
                await node2.cs.wait_for_height(height_before + 1, timeout=20)
                assert node2.block_store.height() > height_before
            finally:
                await node2.stop()


class TestNoEmptyBlocks:
    """create_empty_blocks=false (reference state.go:919 handleTxsAvailable):
    the chain must idle with an empty mempool and resume when txs arrive,
    even with create_empty_blocks_interval=0."""

    @pytest.mark.asyncio
    async def test_stalls_empty_then_advances_on_tx(self):
        from dataclasses import replace

        from tendermint_tpu.consensus.harness import LocalNetwork, fast_config

        cfg = replace(fast_config(), create_empty_blocks=False)
        net = LocalNetwork(2, config=cfg)
        await net.start()
        try:
            # proof blocks still happen: height 1 (initial height) always,
            # and height 2 because executing block 1 changed the app hash
            # (genesis "" -> hash of empty kv state). Then: stall.
            await net.wait_for_height(2, timeout=20)
            await asyncio.sleep(1.0)
            assert all(n.cs.rs.height == 3 for n in net.nodes), (
                "produced an empty non-proof block despite "
                f"create_empty_blocks=false: {[n.cs.rs.height for n in net.nodes]}"
            )
            # inject a tx into every mempool -> consensus must wake and
            # commit it (block 3), plus one proof block (4), then stall at 5
            for n in net.nodes:
                await n.mempool.check_tx(b"k=v")
            await net.wait_for_height(4, timeout=20)
            blk = net.nodes[0].block_store.load_block(3)
            assert b"k=v" in blk.txs
            await asyncio.sleep(1.0)
            assert all(n.cs.rs.height == 5 for n in net.nodes)
        finally:
            await net.stop()
