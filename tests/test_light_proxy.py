"""Light RPC proxy + merkle proof operators (reference
light/proxy/proxy.go:18, light/rpc/client.go, crypto/merkle/proof_op.go).
"""

import asyncio

import pytest

from tendermint_tpu.crypto import merkle
from tendermint_tpu.rpc.client import HTTPClient, HTTPProvider, RPCClientError
from tests.test_node import NodeNet
from tests.test_rpc import rpc_net


class TestProofOps:
    def test_value_op_roundtrip(self):
        items = {b"a": b"1", b"planet": b"mars", b"z": b"26"}
        leaves = [merkle.kv_leaf(k, v) for k, v in sorted(items.items())]
        root, proofs = merkle.proofs_from_byte_slices(leaves)
        keys = sorted(items)
        for i, k in enumerate(keys):
            op = merkle.value_op(k, proofs[i])
            ops = merkle.ProofOperators([op])
            assert ops.verify_value(root, merkle.key_path(k), items[k])
            # wrong value fails
            assert not ops.verify_value(root, merkle.key_path(k), b"forged")
            # wrong key path fails
            assert not ops.verify_value(root, merkle.key_path(b"nope"), items[k])
            # wrong root fails
            assert not ops.verify_value(b"\x00" * 32, merkle.key_path(k), items[k])

    def test_proof_op_codec(self):
        op = merkle.ProofOp("tmtpu:value", b"key", b"\x01\x02")
        assert merkle.ProofOp.decode(op.encode()) == op

    def test_unknown_op_type_rejected(self):
        op = merkle.ProofOp("bogus", b"k", b"")
        assert not merkle.ProofOperators([op]).verify_value(
            b"\x00" * 32, merkle.key_path(b"k"), b"v"
        )


class TestLightProxy:
    @pytest.mark.asyncio
    async def test_proxy_serves_verified_surface(self):
        """Start a real 2-node chain + light proxy; a plain RPC client
        against the PROXY gets verified commits/validators and a
        proof-checked abci_query."""
        from tendermint_tpu.light.client import LightClient, TrustOptions
        from tendermint_tpu.light.proxy import LightProxyEnv
        from tendermint_tpu.rpc.server import RPCServer

        net, clients = await rpc_net()
        primary_http = clients[0]
        proxy_client = None
        server = None
        try:
            # commit a kv pair so abci_query has something to prove
            await primary_http.broadcast_tx_commit(b"saturn=rings")

            chain_id = net.nodes[0].genesis.chain_id
            provider = HTTPProvider(chain_id, primary_http)
            anchor = await provider.light_block(1)
            lc = LightClient(
                chain_id,
                TrustOptions(10**18, 1, anchor.header.hash()),
                provider,
            )
            server = RPCServer(LightProxyEnv(lc, primary_http))
            await server.start("127.0.0.1", 0)
            proxy_client = HTTPClient(f"http://127.0.0.1:{server.port}")

            com = await proxy_client.commit(2)
            assert com["signed_header"]["commit"]["height"] == "2"
            vals = await proxy_client.validators(2)
            assert int(vals["total"]) == 2
            blk = await proxy_client.block(2)
            assert blk["block"]["header"]["height"] == "2"

            # proof-verified query through the proxy
            res = await proxy_client.call(
                "abci_query", path="", data=b"saturn".hex(), prove=True
            )
            assert bytes.fromhex(res["response"]["value"]) == b"rings"
            assert res["response"]["proof_verified"] is True

            # unsupported stateless routes surface a clean error
            with pytest.raises(RPCClientError):
                await proxy_client.call("tx_search", query="tm.event='Tx'")
        finally:
            if proxy_client is not None:
                await proxy_client.close()
            if server is not None:
                await server.stop()
            for c in clients:
                await c.close()
            await net.stop()

    @pytest.mark.asyncio
    async def test_proxy_rejects_forged_query_value(self):
        """A lying primary (value swapped, proof kept) must be caught by
        the proof check."""
        from tendermint_tpu.light.client import LightClient, TrustOptions
        from tendermint_tpu.light.proxy import LightProxyEnv

        net, clients = await rpc_net()
        primary_http = clients[0]
        try:
            await primary_http.broadcast_tx_commit(b"venus=hot")
            chain_id = net.nodes[0].genesis.chain_id
            provider = HTTPProvider(chain_id, primary_http)
            anchor = await provider.light_block(1)
            lc = LightClient(
                chain_id, TrustOptions(10**18, 1, anchor.header.hash()), provider
            )

            class LyingClient:
                """Wraps the real client but corrupts abci_query values."""

                def __getattr__(self, name):
                    return getattr(primary_http, name)

                async def call(self, method, **params):
                    res = await primary_http.call(method, **params)
                    if method == "abci_query":
                        res["response"]["value"] = b"cold".hex()
                    return res

            env = LightProxyEnv(lc, LyingClient())
            from tendermint_tpu.rpc.core import RPCError

            with pytest.raises(RPCError, match="proof verification FAILED"):
                await env.abci_query(path="", data=b"venus".hex())
        finally:
            for c in clients:
                await c.close()
            await net.stop()
