"""tmtlint — the AST invariant analyzer suite (tendermint_tpu/tools/lint).

Every rule gets a positive fixture (the exact pattern it exists to
catch) and a negative one (the disciplined version must stay clean),
pragma-suppression semantics are pinned, and the whole-tree run is the
tier-1 gate: the repo itself must lint clean, fast enough not to eat
the suite's budget.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

from tendermint_tpu.tools.lint import (
    ALL_RULES,
    BAD_PRAGMA,
    DEFAULT_ALLOWLIST,
    RULES_BY_ID,
    Allowlist,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: a rel path inside every strict-profile scope (consensus is covered by
#: clock-discipline and nondeterminism; scope-specific tests override)
NODE_PATH = "tendermint_tpu/consensus/somefile.py"


def run(src: str, rule_id: str, rel: str = NODE_PATH, allowlist=None):
    """Single-rule findings for an inline fixture."""
    out = lint_source(
        textwrap.dedent(src), rel, [RULES_BY_ID[rule_id]], allowlist
    )
    return [f for f in out if f.rule == rule_id]


def run_all(src: str, rel: str = NODE_PATH, allowlist=None):
    return lint_source(textwrap.dedent(src), rel, ALL_RULES, allowlist)


# ---------------------------------------------------------------------------
# blocking-in-async


def test_blocking_sleep_in_async_flagged():
    src = """
    import time
    async def worker():
        time.sleep(1.0)
    """
    fs = run(src, "blocking-in-async")
    assert len(fs) == 1 and fs[0].line == 4


def test_async_sleep_and_sync_sleep_clean():
    src = """
    import asyncio, time
    async def worker():
        await asyncio.sleep(1.0)
    def sync_worker():
        time.sleep(1.0)
    """
    assert run(src, "blocking-in-async") == []


def test_nested_sync_def_is_its_own_context():
    # the nested def runs via to_thread — blocking there is the FIX
    src = """
    import time, asyncio
    async def worker():
        def heavy():
            time.sleep(1.0)
        await asyncio.to_thread(heavy)
    """
    assert run(src, "blocking-in-async") == []


def test_raw_open_and_result_in_async_flagged():
    src = """
    async def worker(fut):
        with open("x") as f:
            data = f.read()
        return fut.result()
    """
    assert {f.line for f in run(src, "blocking-in-async")} == {3, 5}


def test_fs_layer_open_in_async_clean():
    src = """
    async def worker(self):
        with self.fs.open("x", "ab") as f:
            pass
    """
    assert run(src, "blocking-in-async") == []


def test_from_import_and_alias_cannot_evade():
    # `from time import sleep` / `import time as t` resolve through the
    # file's import table — renaming is not an escape hatch
    src = """
    from time import sleep
    import time as t
    async def worker():
        sleep(1.0)
        t.sleep(1.0)
    """
    assert {f.line for f in run(src, "blocking-in-async")} == {5, 6}


def test_from_import_cannot_evade_clock_and_random_rules():
    src = """
    from time import monotonic
    from random import choice
    def deadline():
        return monotonic() + 5.0
    def pick(peers):
        return choice(peers)
    """
    assert len(run(src, "clock-discipline", rel="tendermint_tpu/blocksync/x.py")) == 1
    assert len(run(src, "nondeterminism", rel="tendermint_tpu/p2p/x.py")) == 1


def test_subprocess_in_async_flagged():
    src = """
    import subprocess
    async def worker():
        subprocess.run(["ls"])
    """
    assert len(run(src, "blocking-in-async")) == 1


def test_blocking_relaxed_for_tests_profile():
    src = """
    import time
    async def helper():
        time.sleep(0.1)
    """
    assert run(src, "blocking-in-async", rel="tests/test_x.py") == []


# ---------------------------------------------------------------------------
# absorbed-cancellation


def test_bare_except_without_reraise_flagged():
    src = """
    async def loop():
        try:
            await work()
        except:
            cleanup()
    """
    fs = run(src, "absorbed-cancellation")
    assert len(fs) == 1 and "bare" in fs[0].message


def test_base_exception_with_reraise_clean():
    src = """
    async def loop():
        try:
            await work()
        except BaseException:
            cleanup()
            raise
    """
    assert run(src, "absorbed-cancellation") == []


def test_swallowed_cancelled_error_flagged_and_reraise_clean():
    bad = """
    import asyncio
    async def loop():
        try:
            await work()
        except asyncio.CancelledError:
            cleanup()
    """
    good = bad + "            raise\n"
    assert len(run(bad, "absorbed-cancellation")) == 1
    assert run(good, "absorbed-cancellation") == []


def test_cancelled_in_tuple_flagged():
    src = """
    import asyncio
    async def loop():
        try:
            await work()
        except (ConnectionError, asyncio.CancelledError):
            pass
    """
    assert len(run(src, "absorbed-cancellation")) == 1


def test_silent_except_exception_around_await_flagged():
    bad = """
    async def loop(self):
        try:
            await work()
        except Exception:
            pass
    """
    good = """
    async def loop(self):
        try:
            await work()
        except Exception as e:
            self.logger.debug("dropped: %r", e)
    """
    assert len(run(bad, "absorbed-cancellation")) == 1
    assert run(good, "absorbed-cancellation") == []


def test_unshielded_wait_for_in_cleanup_flagged():
    bad = """
    import asyncio
    async def stop(self):
        try:
            await self.run()
        finally:
            await asyncio.wait_for(self.drain(), 1.0)
    """
    good = """
    import asyncio
    async def stop(self):
        try:
            await self.run()
        finally:
            await asyncio.wait_for(asyncio.shield(self.drain()), 1.0)
    """
    fs = run(bad, "absorbed-cancellation")
    assert len(fs) == 1 and "shield" in fs[0].message
    assert run(good, "absorbed-cancellation") == []


def test_raise_inside_nested_def_is_not_a_reraise():
    # a `raise` in a nested callback runs in a different frame — the
    # handler itself still swallows the cancellation
    src = """
    async def loop():
        try:
            await work()
        except BaseException:
            def on_done():
                raise RuntimeError("nested")
            register(on_done)
    """
    assert len(run(src, "absorbed-cancellation")) == 1


def test_sync_function_bare_except_not_this_rules_business():
    src = """
    def loop():
        try:
            work()
        except:
            pass
    """
    assert run(src, "absorbed-cancellation") == []


def test_absorbed_cancellation_applies_to_tests_profile():
    src = """
    import asyncio
    async def helper():
        try:
            await work()
        except asyncio.CancelledError:
            pass
    """
    assert len(run(src, "absorbed-cancellation", rel="tests/test_x.py")) == 1


# ---------------------------------------------------------------------------
# task-leak


def test_dropped_create_task_flagged():
    src = """
    import asyncio
    async def fire(self):
        asyncio.get_running_loop().create_task(self.work())
        asyncio.ensure_future(self.work())
    """
    assert {f.line for f in run(src, "task-leak")} == {4, 5}


def test_tracked_task_clean():
    src = """
    import asyncio
    async def fire(self):
        t = asyncio.create_task(self.work())
        self._tasks.append(asyncio.create_task(self.work()))
        self.spawn(self.work())
        return t
    """
    assert run(src, "task-leak") == []


# ---------------------------------------------------------------------------
# clock-discipline


def test_wall_clock_in_consensus_flagged():
    src = """
    import time
    def vote_time():
        return time.time_ns()
    def deadline():
        return time.monotonic() + 5.0
    """
    assert {f.line for f in run(src, "clock-discipline")} == {4, 6}


def test_injected_clock_clean():
    src = """
    def vote_time(self):
        return self.clock.now_ns()
    def deadline(self):
        return self.clock.monotonic() + 5.0
    """
    assert run(src, "clock-discipline") == []


def test_clock_rule_scoped_to_consensus_adjacent_dirs():
    src = """
    import time
    def stamp():
        return time.time()
    """
    # libs/ (e.g. flowrate meters) and crypto/ are out of scope
    assert run(src, "clock-discipline", rel="tendermint_tpu/libs/flowrate.py") == []
    assert len(run(src, "clock-discipline", rel="tendermint_tpu/blocksync/x.py")) == 1
    assert len(run(src, "clock-discipline", rel="tendermint_tpu/statesync/x.py")) == 1


# ---------------------------------------------------------------------------
# verify-chokepoint


def test_direct_verify_signature_flagged():
    src = """
    def check(pk, msg, sig):
        return pk.verify_signature(msg, sig)
    """
    fs = run(src, "verify-chokepoint", rel="tendermint_tpu/types/vote.py")
    assert len(fs) == 1 and "VerifyHub" in fs[0].message


def test_verify_signature_interface_def_clean():
    src = """
    class PubKey:
        def verify_signature(self, msg, sig):
            raise NotImplementedError
    """
    assert run(src, "verify-chokepoint", rel="tendermint_tpu/types/keys.py") == []


def test_sync_facade_in_coroutine_flagged():
    """The pipelined ingest made the hub's SYNC facade inside a
    coroutine a lint error in consensus/blocksync/statesync: it blocks
    the event loop per signature and pins batch occupancy at 1."""
    src = """
    async def handle(self, vote):
        ok = self.hub.verify_sync(pk, msg, sig)
        ok2 = self.hub.submit_nowait(pk, msg, sig).result(5.0)
    """
    fs = run(src, "verify-chokepoint", rel="tendermint_tpu/consensus/ingest.py")
    assert len(fs) == 2
    assert "blocks the event loop" in fs[0].message
    assert "sync facade in disguise" in fs[1].message
    # same pattern in blocksync is equally flagged
    assert len(run(src, "verify-chokepoint", rel="tendermint_tpu/blocksync/pool.py")) == 2


def test_sync_facade_clean_cases():
    # sync defs may block (the evidence pool, replay); the async hub API
    # is the blessed path; .result() on other receivers is untouched
    src = """
    def sync_check(self, pk, msg, sig):
        return self.hub.verify_sync(pk, msg, sig)
    async def pipelined(self, pk, msg, sig):
        return await self.hub.verify(pk, msg, sig)
    async def other_future(self):
        return self.pool.submit(job).result()
    """
    assert run(src, "verify-chokepoint", rel="tendermint_tpu/consensus/state.py") == []
    # outside consensus/blocksync/statesync the facade stays legal (the
    # evidence pool and validation shim are synchronous by design)
    flagged = """
    async def handle(self):
        return self.hub.verify_sync(pk, msg, sig)
    """
    assert run(flagged, "verify-chokepoint", rel="tendermint_tpu/types/validation.py") == []


def test_sync_facade_pragma_escape_hatch():
    src = """
    async def handle(self):
        return self.hub.verify_sync(pk, msg, sig)  # tmtlint: allow[verify-chokepoint] -- measured: cache hit path only
    """
    assert run(src, "verify-chokepoint", rel="tendermint_tpu/consensus/state.py") == []


def test_sync_facade_flagged_in_mempool_and_rpc():
    """TxIngress put mempool/ and rpc/ on the flood-facing event loop:
    the sync hub facade (and direct verify) is a defect there too."""
    src = """
    async def admit(self, tx):
        ok = self.hub.verify_sync(pk, msg, sig)
    """
    assert len(run(src, "verify-chokepoint", rel="tendermint_tpu/mempool/ingress.py")) == 1
    assert len(run(src, "verify-chokepoint", rel="tendermint_tpu/rpc/core.py")) == 1


def test_bls_funnel_calls_flagged_outside_crypto():
    """The aggregate-commit path must not grow a second verify funnel:
    direct pairing / aggregate-verify calls outside crypto/ bypass the
    hub's verdict cache and the breaker-guarded device routing."""
    src = """
    def check_commit(self, pubs, msgs, agg):
        if not bls.aggregate_verify(pubs, msgs, agg):
            raise ValueError("bad aggregate")
    def raw_pairing(self, p, q):
        return bls_math.pairing(p, q)
    def kernel_direct(self, items):
        return bls_pairing.verify_pairs_batch(items, pad_to=4)
    """
    fs = run(src, "verify-chokepoint", rel="tendermint_tpu/types/validation.py")
    assert len(fs) == 3
    assert all("second verify funnel" in f.message for f in fs)
    # blocksync is equally fenced
    assert len(run(src, "verify-chokepoint", rel="tendermint_tpu/blocksync/pool.py")) == 3


def test_bls_funnel_clean_cases():
    # the hub chokepoint itself, PoP checks (construction-time), and
    # aggregation (not verification) all stay legal outside crypto/
    src = """
    def check_commit(self, pubs, msgs, agg):
        return verify_aggregate(pubs, msgs, agg)
    def check_pop(self, gv):
        return gv.pub_key.pop_verify(gv.pop)
    def make_aggregate(self, sigs):
        return bls.aggregate_signatures(sigs)
    """
    assert run(src, "verify-chokepoint", rel="tendermint_tpu/types/validation.py") == []
    # inside crypto/ the primitives ARE the chokepoint (allowlisted)
    direct = """
    def verify(self, pubs, msgs, agg):
        return bls_math.aggregate_verify(pubs, msgs, agg)
    """
    assert (
        run(
            direct,
            "verify-chokepoint",
            rel="tendermint_tpu/crypto/bls.py",
            allowlist=Allowlist.load(DEFAULT_ALLOWLIST),
        )
        == []
    )


def test_verifyd_funnel_calls_flagged_outside_crypto():
    """crypto/verifyd is the ONLY legal raw-socket verify path: a call
    site talking to the sidecar directly skips the hub's verdict cache,
    lanes, AND the breaker's inline-local fallback — a daemon crash
    there becomes a liveness event instead of a degrade."""
    src = """
    def fast_verify(self, items):
        client = client_for(self.sock_path)
        return client.remote_verify_batch(items)
    def agg(self, pubs, msgs, sig):
        return verifyd.VerifydClient(self.sock).remote_verify_aggregate(pubs, msgs, sig)
    """
    fs = run(src, "verify-chokepoint", rel="tendermint_tpu/blocksync/pool.py")
    assert len(fs) == 4  # client_for + remote_verify_batch + ctor + agg
    assert all("raw-socket verify path" in f.message for f in fs)
    # consensus is equally fenced
    assert len(run(src, "verify-chokepoint", rel="tendermint_tpu/consensus/state.py")) == 4


def test_verifyd_funnel_clean_cases():
    # the hub route (config knob) and diagnostics stay legal outside
    # crypto/; inside crypto/ the client IS the chokepoint (allowlisted)
    src = """
    def build_hub(self, cfg):
        return VerifyHub(verifyd_sock=cfg.verifyd_sock)
    def diagnostics(self, client):
        return client.remote_stats()
    """
    assert run(src, "verify-chokepoint", rel="tendermint_tpu/node.py") == []
    direct = """
    def route(self, batch):
        return client_for(self.verifyd_sock).remote_verify_batch(batch)
    """
    assert (
        run(
            direct,
            "verify-chokepoint",
            rel="tendermint_tpu/crypto/verify_hub.py",
            allowlist=Allowlist.load(DEFAULT_ALLOWLIST),
        )
        == []
    )


# ---------------------------------------------------------------------------
# hash-chokepoint


def test_raw_sha256_flagged_in_hash_hot_paths():
    """ISSUE 20: raw hashlib in types/state/consensus/mempool/light
    bypasses the HashHub (lane stats, metrics, device batching)."""
    src = """
    import hashlib
    def tx_key(tx):
        return hashlib.sha256(tx).digest()
    """
    for rel in (
        "tendermint_tpu/types/tx.py",
        "tendermint_tpu/state/execution.py",
        "tendermint_tpu/consensus/state.py",
        "tendermint_tpu/mempool/pool.py",
        "tendermint_tpu/light/client.py",
    ):
        fs = run(src, "hash-chokepoint", rel=rel)
        assert len(fs) == 1 and "HashHub" in fs[0].message, rel


def test_sha256_via_import_alias_and_relative_import_flagged():
    # resolve_call canonicalizes absolute aliases; relative imports stay
    # bare — the short name catches the primitive either way
    src = """
    from hashlib import sha256 as s256
    from ..crypto.hashes import sha256

    def double(data):
        return s256(sha256(data)).digest()
    """
    fs = run(src, "hash-chokepoint", rel="tendermint_tpu/types/block.py")
    assert len(fs) == 2


def test_hub_routes_and_crypto_sink_are_clean():
    # the blessed funnel calls are exactly what the rule pushes toward
    src = """
    from ..crypto.hash_hub import sha256_many, sha256_one
    from ..crypto import merkle

    def roots(chunks, tx):
        return merkle.hash_from_byte_slices(chunks), sha256_one(tx)
    """
    assert run(src, "hash-chokepoint", rel="tendermint_tpu/types/block.py") == []
    # crypto/ is the sink: out of scope by construction, no pragma needed
    raw = """
    import hashlib
    def digest(m):
        return hashlib.sha256(m).digest()
    """
    assert run(raw, "hash-chokepoint", rel="tendermint_tpu/crypto/hashes.py") == []
    # and non-hot trees (tools/, rpc/) are out of scope too
    assert run(raw, "hash-chokepoint", rel="tendermint_tpu/tools/dumper.py") == []


def test_hash_chokepoint_pragma_needs_reason():
    flagged = """
    import hashlib
    def seed(label):
        return hashlib.sha256(label).digest()  # tmtlint: allow[hash-chokepoint]
    """
    fs = lint_source(
        textwrap.dedent(flagged),
        "tendermint_tpu/consensus/chaos.py",
        [RULES_BY_ID["hash-chokepoint"]],
        known_rules=set(RULES_BY_ID),
    )
    assert {f.rule for f in fs} == {"hash-chokepoint", BAD_PRAGMA}
    reasoned = """
    import hashlib
    def seed(label):
        return hashlib.sha256(label).digest()  # tmtlint: allow[hash-chokepoint] -- fixture: derivation, not a hot path
    """
    assert run(reasoned, "hash-chokepoint", rel="tendermint_tpu/consensus/chaos.py") == []


def test_hash_chokepoint_checked_in_allowlist():
    # the seeded chaos/attack harnesses are exempted by prefix in
    # allowlist.json — with the reason recorded there, not inline
    src = """
    import hashlib
    def derive(label):
        return hashlib.sha256(label).digest()
    """
    assert (
        run(
            src,
            "hash-chokepoint",
            rel="tendermint_tpu/consensus/byzantine.py",
            allowlist=Allowlist.load(DEFAULT_ALLOWLIST),
        )
        == []
    )
    # the exemption is prefix-scoped: a neighbor file is still flagged
    assert (
        len(
            run(
                src,
                "hash-chokepoint",
                rel="tendermint_tpu/consensus/state.py",
                allowlist=Allowlist.load(DEFAULT_ALLOWLIST),
            )
        )
        == 1
    )


# ---------------------------------------------------------------------------
# unbounded-queue


def test_unbounded_queue_flagged_on_flood_path():
    """Every queue on the tx-ingress / event-fan-out path buffers work
    an attacker generates for free — maxsize (plus shed-on-full) is
    mandatory there."""
    src = """
    import asyncio
    class Ingress:
        def __init__(self):
            self.q = asyncio.Queue()
            self.q0 = asyncio.Queue(0)
            self.qkw = asyncio.Queue(maxsize=0)
            self.qneg = asyncio.Queue(-1)  # asyncio: <= 0 means infinite
            self.qnegkw = asyncio.Queue(maxsize=-5)
    """
    for rel in (
        "tendermint_tpu/mempool/ingress.py",
        "tendermint_tpu/rpc/server.py",
        "tendermint_tpu/libs/pubsub.py",
    ):
        assert {f.line for f in run(src, "unbounded-queue", rel=rel)} == {
            5, 6, 7, 8, 9,
        }


def test_bounded_queue_and_out_of_scope_clean():
    bounded = """
    import asyncio
    class Ingress:
        def __init__(self, depth):
            self.q = asyncio.Queue(depth)
            self.q2 = asyncio.Queue(maxsize=depth + 1)
    """
    assert run(bounded, "unbounded-queue", rel="tendermint_tpu/mempool/ingress.py") == []
    # consensus internals are bounded by protocol structure, not by this
    # rule — the scope is the user-facing flood path only
    unbounded = """
    import asyncio
    q = asyncio.Queue()
    """
    assert run(unbounded, "unbounded-queue", rel="tendermint_tpu/consensus/state.py") == []


def test_unbounded_queue_from_import_cannot_evade():
    src = """
    from asyncio import Queue
    class Sub:
        def __init__(self):
            self.q = Queue()
    """
    assert len(run(src, "unbounded-queue", rel="tendermint_tpu/rpc/core.py")) == 1


def test_crypto_backends_allowlisted():
    src = """
    def check(pk, msg, sig):
        return pk.verify_signature(msg, sig)
    """
    allow = Allowlist.load(DEFAULT_ALLOWLIST)
    assert (
        run(src, "verify-chokepoint", rel="tendermint_tpu/crypto/batch.py", allowlist=allow)
        == []
    )
    # ...and the allowlist is per-rule, not a blanket file exemption
    assert (
        run(src, "verify-chokepoint", rel="tendermint_tpu/types/vote.py", allowlist=allow)
        != []
    )


# ---------------------------------------------------------------------------
# shape-bucketing


def test_prep_without_pad_to_flagged():
    """An unpadded kernel host-prep call hands XLA the raw batch length
    as a static shape — a cold compile per distinct size on the hot
    path. Both name-style and method-style calls are caught."""
    src = """
    from tendermint_tpu.crypto.tpu.verify import prepare_batch_eq

    def dispatch(tpuv, entries):
        a = prepare_batch_eq(entries)
        b = tpuv.prepare_resolved(entries)
        return a, b
    """
    fs = run(src, "shape-bucketing", rel="tendermint_tpu/crypto/tpu/somefile.py")
    assert [f.line for f in fs] == [5, 6]


def test_bls_pairing_prep_without_pad_to_flagged():
    """The BLS pairing prep is shape-gated like the ed25519 preps: an
    unpadded call cold-compiles a pairing kernel per batch length."""
    src = """
    def dispatch(items):
        return prepare_pairing_batch(items, pair_pad=2)
    """
    fs = run(src, "shape-bucketing", rel="tendermint_tpu/crypto/tpu/bls_x.py")
    assert len(fs) == 1 and "pad" in fs[0].message
    padded = """
    def dispatch(items, b):
        return prepare_pairing_batch(items, pad_to=b, pair_pad=2)
    """
    assert run(padded, "shape-bucketing", rel="tendermint_tpu/crypto/tpu/bls_x.py") == []


def test_prep_with_pad_to_clean():
    src = """
    def dispatch(tpuv, entries, b):
        ok = tpuv.prepare_batch_eq(entries, pad_to=b)
        ok2 = tpuv.prepare_batch(entries, pad_to=b)
        other = tpuv.prepare_dinner(entries)  # unrelated name
        return ok, ok2, other
    """
    assert run(src, "shape-bucketing", rel=NODE_PATH) == []


def test_prep_rule_relaxed_for_tests_profile():
    """tests/ build ad-hoc shapes on purpose (compile cost is theirs to
    pay); the rule only gates node code."""
    src = """
    def helper(tpuv, entries):
        return tpuv.prepare_batch_eq(entries)
    """
    assert run(src, "shape-bucketing", rel="tests/test_something.py") == []


# ---------------------------------------------------------------------------
# fs-discipline


def test_raw_binary_write_open_flagged():
    src = """
    def append(path, rec):
        with open(path, "ab") as f:
            f.write(rec)
    """
    fs = run(src, "fs-discipline", rel="tendermint_tpu/consensus/wal.py")
    assert len(fs) == 1


def test_read_only_and_fs_layer_opens_clean():
    src = """
    def read(self, path):
        with open(path, "rb") as f:
            return f.read()
    def append(self, path, rec):
        with self.fs.open(path, "ab") as f:
            f.write(rec)
    """
    assert run(src, "fs-discipline", rel="tendermint_tpu/consensus/wal.py") == []


def test_os_mutations_flagged_in_store_scope_only():
    src = """
    import os
    def swap(a, b):
        os.replace(a, b)
        os.fsync(3)
    """
    assert {f.line for f in run(src, "fs-discipline", rel="tendermint_tpu/store/x.py")} == {4, 5}
    # out of scope: p2p has no storage write path to protect
    assert run(src, "fs-discipline", rel="tendermint_tpu/p2p/x.py") == []


def test_sqlite_owned_db_allowlisted():
    src = """
    import os
    def swap(a, b):
        os.replace(a, b)
    """
    allow = Allowlist.load(DEFAULT_ALLOWLIST)
    assert (
        run(src, "fs-discipline", rel="tendermint_tpu/store/db.py", allowlist=allow)
        == []
    )


# ---------------------------------------------------------------------------
# nondeterminism


def test_global_random_flagged_seeded_instance_clean():
    bad = """
    import random
    def pick(peers):
        return random.choice(peers)
    """
    good = """
    import random
    def make_rng(seed):
        return random.Random(seed)
    def pick(rng, peers):
        return rng.choice(peers)
    """
    assert len(run(bad, "nondeterminism", rel="tendermint_tpu/p2p/pex.py")) == 1
    assert run(good, "nondeterminism", rel="tendermint_tpu/p2p/pex.py") == []


def test_os_entropy_flagged():
    src = """
    import os
    def nonce():
        return os.urandom(8)
    """
    assert len(run(src, "nondeterminism", rel="tendermint_tpu/libs/chaos.py")) == 1


def test_crypto_handshake_entropy_allowlisted():
    src = """
    import os
    def nonce():
        return os.urandom(8)
    """
    allow = Allowlist.load(DEFAULT_ALLOWLIST)
    assert (
        run(src, "nondeterminism", rel="tendermint_tpu/p2p/secret.py", allowlist=allow)
        == []
    )


def test_set_iteration_flagged_sorted_clean():
    bad = """
    def fanout(self, peers):
        for p in set(peers):
            self.send(p)
    """
    good = """
    def fanout(self, peers):
        for p in sorted(set(peers)):
            self.send(p)
    """
    assert len(run(bad, "nondeterminism", rel="tendermint_tpu/p2p/x.py")) == 1
    assert run(good, "nondeterminism", rel="tendermint_tpu/p2p/x.py") == []


# ---------------------------------------------------------------------------
# span-discipline


def test_span_outside_with_flagged():
    # held in a variable (never closed) and dropped on the floor — both
    # leak the measurement
    src = """
    from tendermint_tpu.libs import trace
    def f():
        sp = trace.span("hub", "dispatch")
        trace.span("hub", "queue")
    """
    assert {f.line for f in run(src, "span-discipline")} == {4, 5}


def test_span_in_with_clean():
    src = """
    from tendermint_tpu.libs import trace
    def f():
        with trace.span("hub", "dispatch") as sp:
            sp.set(batch=4)
        with trace.RECORDER.span("hub", "queue"):
            pass
    """
    assert run(src, "span-discipline") == []


def test_span_discipline_record_emit_exempt():
    # explicit-boundary APIs are closed by construction
    src = """
    from tendermint_tpu.libs import trace
    def f(ctx, t0, t1):
        trace.record(ctx, "consensus", "ingest.wait", t0, t1)
        trace.emit("backend", "attach", duration_s=0.5)
        trace.finish(ctx, "consensus", "msg")
    """
    assert run(src, "span-discipline") == []


def test_recorder_span_outside_with_flagged():
    src = """
    def f(recorder):
        leaked = recorder.span("hub", "x")
    """
    assert len(run(src, "span-discipline")) == 1


def test_unrelated_span_method_clean():
    # a .span() on a non-recorder receiver is not a trace span
    src = """
    def f(wing):
        area = wing.span("m")
    """
    assert run(src, "span-discipline") == []


def test_wall_clock_in_trace_layer_flagged():
    src = """
    import time
    def stamp():
        return time.time()
    """
    fs = run(src, "span-discipline", rel="tendermint_tpu/libs/trace.py")
    assert len(fs) == 1 and "wall-clock" in fs[0].message
    # time.monotonic is the duration domain — legal in the trace layer
    src_ok = """
    import time
    def dur():
        return time.monotonic()
    """
    assert run(src_ok, "span-discipline", rel="tendermint_tpu/libs/trace.py") == []
    # and wall clocks OUTSIDE the trace layer are other rules' business
    assert run(src, "span-discipline", rel="tendermint_tpu/rpc/core.py") == []


def test_watchdog_wall_clock_allowlisted():
    src = """
    import time
    def report_name():
        return f"wedged-{int(time.time()*1000)}.txt"
    """
    allow = Allowlist.load(DEFAULT_ALLOWLIST)
    assert (
        lint_source(
            textwrap.dedent(src),
            "tendermint_tpu/libs/watchdog.py",
            [RULES_BY_ID["span-discipline"]],
            allow,
        )
        == []
    )


# ---------------------------------------------------------------------------
# byz-containment


def test_byzantine_import_flagged_in_production_code():
    """The exact hazard the rule exists for: production wiring gaining
    a path to the unguarded double-signing strategy layer."""
    for src in (
        "from .consensus import byzantine",
        "from .consensus.byzantine import ByzConfig",
        "import tendermint_tpu.consensus.byzantine as byz",
    ):
        fs = run(src, "byz-containment", rel="tendermint_tpu/node.py")
        assert len(fs) == 1, src
        assert "quarantined" in fs[0].message
    # relative forms from inside the consensus package
    for src in (
        "from .byzantine import ByzantineNode",
        "from . import byzantine",
    ):
        fs = run(
            src, "byz-containment", rel="tendermint_tpu/consensus/routernet.py"
        )
        assert len(fs) == 1, src


def test_byzantine_import_allowed_in_harness_and_clean_elsewhere():
    # the scenario harness and the module itself ARE the legal users
    assert (
        run(
            "from .byzantine import ByzConfig, audit_net",
            "byz-containment",
            rel="tendermint_tpu/consensus/scenarios.py",
        )
        == []
    )
    assert (
        run(
            "from . import messages as m",
            "byz-containment",
            rel="tendermint_tpu/consensus/byzantine.py",
        )
        == []
    )
    # unrelated consensus imports never trip it
    assert (
        run(
            "from .consensus import messages, scenarios",
            "byz-containment",
            rel="tendermint_tpu/node.py",
        )
        == []
    )


def test_sync_facade_flagged_in_light():
    """LightFleet put light/ on the fleet-serving event loop: one
    blocking verify in a LightD coroutine stalls every concurrent sync
    session, so the sync facade (and direct verify) is a defect there."""
    src = """
    async def sync(self, height):
        ok = self.hub.verify_sync(pk, msg, sig)
        ok2 = self.hub.submit_nowait(pk, msg, sig).result(5.0)
    """
    fs = run(src, "verify-chokepoint", rel="tendermint_tpu/light/fleet.py")
    assert len(fs) == 2
    # sync defs in light/ stay legal (the stateless verifier core)
    clean = """
    def check(self, pk, msg, sig):
        return self.hub.verify_sync(pk, msg, sig)
    """
    assert run(clean, "verify-chokepoint", rel="tendermint_tpu/light/verifier.py") == []


def test_lunatic_provider_import_flagged_in_production_code():
    """light/byzantine (the lunatic forged-header provider) is
    quarantined exactly like consensus/byzantine: production wiring
    holding validator keys must never be able to sign a forged header."""
    for src, rel in (
        ("from .light import byzantine", "tendermint_tpu/node.py"),
        (
            "from .light.byzantine import LunaticProvider",
            "tendermint_tpu/node.py",
        ),
        (
            "import tendermint_tpu.light.byzantine as lb",
            "tendermint_tpu/cli.py",
        ),
        ("from .byzantine import LunaticConfig", "tendermint_tpu/light/fleet.py"),
        ("from . import byzantine", "tendermint_tpu/light/proxy.py"),
    ):
        fs = run(src, "byz-containment", rel=rel)
        assert len(fs) == 1, (src, rel)
        assert "quarantined" in fs[0].message


def test_lunatic_provider_import_allowed_in_harness_and_itself():
    # the scenario harness is the single legal injection seam for BOTH
    # quarantined strategy layers
    assert (
        run(
            "from ..light.byzantine import LunaticConfig, LunaticProvider",
            "byz-containment",
            rel="tendermint_tpu/consensus/scenarios.py",
        )
        == []
    )
    assert (
        run(
            "from .provider import Provider",
            "byz-containment",
            rel="tendermint_tpu/light/byzantine.py",
        )
        == []
    )
    # unrelated light imports never trip it
    assert (
        run(
            "from .light import fleet, verifier",
            "byz-containment",
            rel="tendermint_tpu/node.py",
        )
        == []
    )


def test_byzantine_containment_holds_on_the_real_tree():
    """The repo itself: the only files naming consensus/byzantine are
    the allowlisted harness modules (the whole-tree clean gate below
    covers this too — this pins the specific rule)."""
    from tendermint_tpu.tools.lint import lint_paths

    all_findings, n_files = lint_paths(
        [os.path.join(REPO, "tendermint_tpu")],
        [RULES_BY_ID["byz-containment"]],
        Allowlist.load(DEFAULT_ALLOWLIST),
    )
    findings = [f for f in all_findings if f.rule == "byz-containment"]
    assert n_files > 100  # the whole tree was actually scanned
    assert findings == [], [f.render() for f in findings]


def test_sync_facade_flagged_in_statesync():
    """BootFleet put statesync/ on the fleet-serving event loop: one
    blocking verify in a BootD coroutine stalls every concurrent chunk
    session AND every joiner's backfill batch, so the sync facade (and
    direct verify) is a defect there too."""
    src = """
    async def verify_backfill(self, blocks):
        ok = self.hub.verify_sync(pk, msg, sig)
        ok2 = self.hub.submit_nowait(pk, msg, sig).result(5.0)
    """
    fs = run(src, "verify-chokepoint", rel="tendermint_tpu/statesync/fleet.py")
    assert len(fs) == 2
    # sync defs in statesync/ stay legal (runs via asyncio.to_thread)
    clean = """
    def _check(self, pk, msg, sig):
        return self.hub.verify_sync(pk, msg, sig)
    """
    assert run(clean, "verify-chokepoint", rel="tendermint_tpu/statesync/fleet.py") == []


def test_poisoned_donor_import_flagged_in_production_code():
    """statesync/byzantine (the poisoned-snapshot donor app) is
    quarantined exactly like the other two strategy layers: a
    production node must be structurally unable to serve corrupted
    chunks to joiners."""
    for src, rel in (
        ("from .statesync import byzantine", "tendermint_tpu/node.py"),
        (
            "from .statesync.byzantine import PoisonedSnapshotApp",
            "tendermint_tpu/node.py",
        ),
        (
            "import tendermint_tpu.statesync.byzantine as sb",
            "tendermint_tpu/cli.py",
        ),
        ("from .byzantine import PoisonedSnapshotApp", "tendermint_tpu/statesync/fleet.py"),
        ("from . import byzantine", "tendermint_tpu/statesync/reactor.py"),
    ):
        fs = run(src, "byz-containment", rel=rel)
        assert len(fs) == 1, (src, rel)
        assert "quarantined" in fs[0].message
    # the scenario harness stays the single legal injection seam
    assert (
        run(
            "from ..statesync.byzantine import PoisonedSnapshotApp",
            "byz-containment",
            rel="tendermint_tpu/consensus/scenarios.py",
        )
        == []
    )
    # unrelated statesync imports never trip it
    assert (
        run(
            "from .statesync import fleet, reactor",
            "byz-containment",
            rel="tendermint_tpu/node.py",
        )
        == []
    )


# ---------------------------------------------------------------------------
# pragmas


PRAGMA_FIXTURE = """
import time
async def worker():
    time.sleep(1.0){pragma}
"""


def test_pragma_with_reason_suppresses():
    src = PRAGMA_FIXTURE.format(
        pragma="  # tmtlint: allow[blocking-in-async] -- fixture: startup only"
    )
    assert run_all(src) == []


def test_pragma_without_reason_does_not_suppress_and_is_reported():
    src = PRAGMA_FIXTURE.format(pragma="  # tmtlint: allow[blocking-in-async]")
    rules = {f.rule for f in run_all(src)}
    assert rules == {"blocking-in-async", BAD_PRAGMA}


def test_pragma_for_other_rule_does_not_suppress():
    src = PRAGMA_FIXTURE.format(
        pragma="  # tmtlint: allow[clock-discipline] -- wrong rule"
    )
    assert {f.rule for f in run_all(src)} == {"blocking-in-async"}


def test_wildcard_pragma_suppresses_everything():
    src = PRAGMA_FIXTURE.format(pragma="  # tmtlint: allow[*] -- fixture")
    assert run_all(src) == []


def test_comment_line_pragma_covers_next_code_line():
    src = """
    import time
    async def worker():
        # tmtlint: allow[blocking-in-async] -- fixture: covers the line below
        time.sleep(1.0)
    """
    assert run_all(src) == []


def test_stacked_comment_pragmas_all_cover_the_next_code_line():
    src = """
    import time, random
    async def worker():
        # tmtlint: allow[blocking-in-async] -- fixture: reason one
        # tmtlint: allow[nondeterminism] -- fixture: reason two
        time.sleep(random.random())
    """
    assert run_all(src, rel="tendermint_tpu/p2p/x.py") == []


def test_pragma_inside_string_literal_is_not_a_pragma():
    # pragma scanning is token-based: pragma-shaped TEXT in a string is
    # neither a suppression nor a bad-pragma (the line above in this
    # very file documents the syntax without tripping the tree gate)
    src = """
    import time
    async def worker():
        doc = "# tmtlint: allow[blocking-in-async] -- not a comment"
        time.sleep(1.0); bad = "# tmtlint: allow[blocking-in-async]"
    """
    assert {f.rule for f in run_all(src)} == {"blocking-in-async"}


def test_pragma_with_unknown_rule_id_is_reported():
    """A typo'd rule id used to suppress nothing and report nothing —
    the worst failure mode for an auditable-suppression scheme. With the
    registry handed to the run, the typo is itself a finding."""
    src = textwrap.dedent(
        """
        import time
        async def worker():
            time.sleep(1.0)  # tmtlint: allow[blocking-in-asink] -- typo'd id
        """
    )
    fs = lint_source(src, NODE_PATH, ALL_RULES, known_rules=set(RULES_BY_ID))
    rules = {f.rule for f in fs}
    # the typo'd pragma does not suppress, AND the typo is reported
    assert rules == {"blocking-in-async", BAD_PRAGMA}
    bad = [f for f in fs if f.rule == BAD_PRAGMA]
    assert any("unknown rule id" in f.message and "blocking-in-asink" in f.message
               for f in bad)


def test_pragma_with_known_ids_wildcard_and_badpragma_never_flagged_unknown():
    src = textwrap.dedent(
        """
        import time
        async def worker():
            time.sleep(1.0)  # tmtlint: allow[*] -- fixture
        """
    )
    assert lint_source(src, NODE_PATH, ALL_RULES, known_rules=set(RULES_BY_ID)) == []
    # without a registry (single-rule fixture runs) unknown ids are not
    # this run's business — same gating as bad-pragma vs --rule
    src2 = textwrap.dedent(
        """
        import time
        async def worker():
            time.sleep(1.0)  # tmtlint: allow[no-such-rule] -- still reported missing nothing
        """
    )
    fs = lint_source(src2, NODE_PATH, ALL_RULES)  # known_rules=None
    assert {f.rule for f in fs} == {"blocking-in-async"}


# ---------------------------------------------------------------------------
# driver + whole-tree gate (tier-1)


def _lint(*args: str) -> subprocess.CompletedProcess:
    """Run the REAL entrypoint (`scripts/tmtlint`) — the tier-1 gate,
    CI and pre-commit all go through this one file, so the gate test
    must too (one code path, no second driver to drift)."""
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tmtlint"), *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_repo_tree_is_clean_and_fast():
    """THE gate: the repo's own code holds every invariant the analyzers
    enforce — including the interprocedural and wire-schema passes —
    and the full run fits the tier-1 time budget (suite is ~815s of
    870s — this must stay a rounding error)."""
    out = _lint("--json")
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert payload["files_scanned"] > 100  # actually walked the tree
    assert len(payload["rules"]) >= 15
    # bench guard: wall time is recorded in the JSON and bounded
    assert payload["elapsed_s"] < 10.0, f"lint too slow: {payload['elapsed_s']}s"
    # per-rule finding counts ride the JSON (zeros included) so BENCH
    # rounds can diff lint drift across PRs
    assert set(payload["per_rule"]) == set(payload["rules"])
    assert all(v == 0 for v in payload["per_rule"].values())
    for required in (
        "transitive-blocking",
        "wire-schema",
        "wire-bounds",
        "wiregen-drift",
    ):
        assert required in payload["per_rule"]


def test_legacy_lint_py_alias_same_code_path():
    """scripts/lint.py predates the tmtlint CLI; it must stay a pure
    alias (same main(), same output shape)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--json", "--rule", "task-leak", "tendermint_tpu/libs"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["rules"] == ["task-leak"] and "per_rule" in payload


def test_retired_regex_shims_route_through_tmtlint():
    """check_fs_callsites / check_verify_callsites predate the PR 4
    framework; they are now aliases over the tmtlint rules (per-file +
    transitive) and must exit clean on the tree."""
    for shim in ("check_fs_callsites.py", "check_verify_callsites.py"):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", shim)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, (shim, out.stdout, out.stderr)


def test_driver_rule_filter_and_errors():
    out = _lint("--rule", "no-such-rule")
    assert out.returncode == 2 and "unknown rule" in out.stderr
    out = _lint("--list-rules")
    assert out.returncode == 0
    for rule in ALL_RULES:
        assert rule.id in out.stdout


def test_driver_rejects_nonexistent_paths():
    # a typo'd path must NOT scan 0 files and report clean
    out = _lint("no/such/dir")
    assert out.returncode == 2 and "no such path" in out.stderr


def test_single_rule_run_reports_only_that_rule(tmp_path):
    # bad pragmas elsewhere in a file must not fail a --rule spot check
    # (they belong to the full gate); the shims rely on this
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "async def f():\n"
        "    time.sleep(1)  # tmtlint: allow[task-leak]\n"
    )
    out = _lint("--rule", "task-leak", "--json", str(bad))
    assert out.returncode == 0, out.stdout + out.stderr
    assert json.loads(out.stdout)["findings"] == []
    # the full run still reports both the finding and the bad pragma
    out = _lint("--json", str(bad))
    rules = {f["rule"] for f in json.loads(out.stdout)["findings"]}
    assert rules == {"blocking-in-async", BAD_PRAGMA}


def test_driver_reports_findings_with_location(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import asyncio\n"
        "async def f(self):\n"
        "    asyncio.ensure_future(self.g())\n"
    )
    out = _lint(str(bad))
    assert out.returncode == 1
    assert "task-leak" in out.stderr and "bad.py:3" in out.stderr
    out = _lint("--json", str(bad))
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert [f["rule"] for f in payload["findings"]] == ["task-leak"]
    assert payload["findings"][0]["line"] == 3
