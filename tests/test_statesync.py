"""Statesync tests: snapshot discovery, chunk fetch, app restore, state
bootstrap, backfill — over the real p2p channels (modeled on reference
internal/statesync/{reactor,syncer}_test.go but end-to-end)."""

import asyncio

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.node import NodeConfig
from tendermint_tpu.p2p.types import NodeAddress
from tendermint_tpu.statesync.reactor import SyncConfig
from tests.test_node import NodeNet

LONG_NS = 10 * 365 * 24 * 3600 * 10**9


class TestStateSync:
    @pytest.mark.asyncio
    async def test_fresh_node_restores_from_snapshot(self):
        """Validators run past a snapshot height (kvstore snapshots every
        10 blocks); a fresh node state-syncs instead of replaying."""
        net = NodeNet(3)
        await net.start()
        try:
            # put some app state in, then run past height 10
            await net.nodes[0].mempool.check_tx(b"saturn=rings")
            await net.wait_for_height(12, timeout=90)

            # trust anchor: height 1 header hash from an existing node
            meta1 = net.nodes[0].block_store.load_block_meta(1)
            late = net._make_node(9, None)
            late.config.state_sync = SyncConfig(
                trust_height=1, trust_hash=meta1.header.hash(),
                trust_period_ns=LONG_NS, backfill_blocks=4,
            )
            net.nodes.append(late)
            await late.start()
            for peer in net.nodes[:3]:
                late.peer_manager.add_address(
                    NodeAddress(node_id=peer.node_id, protocol="memory")
                )
            # wait until restored + block-synced near the tip
            target = net.nodes[0].block_store.height()
            await late.wait_for_height(target, timeout=90)

            # app state restored (including pre-snapshot txs)
            res = late.app.query(abci.RequestQuery(data=b"saturn"))
            assert res.value == b"rings"
            # the store base reflects a snapshot bootstrap, not replay
            assert late.block_store.base() > 1
            # backfilled headers are servable below the base
            bf = late.block_store.load_block_meta(late.block_store.base() - 2)
            assert bf is not None
        finally:
            await net.stop()
